"""Multi-tenant plane tier-1 wiring (ISSUE 17): GET+JSON-RPC
/dump_tenants over a live server with a mounted multi-tenant plane,
post-stop history (the _LAST pattern), /metrics tenant families riding
a real scrape (top-K + _retired cardinality bound), and the
tenant_report --diff regression detector (including the miswired
--fail-on-regression gate).

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP).
Host-only: the whole file must run with NO jax import (asserted).
"""
import copy
import json
import sys
import urllib.request

import pytest

from cometbft_tpu.verifyplane import VerifyPlane, set_global_plane
from cometbft_tpu.verifyplane import plane as planemod
from cometbft_tpu.verifyplane import tenants as vtenants

_JAX_LOADED_BEFORE = "jax" in sys.modules

CHAIN = "ztenant-chain"


class _Pub:
    def verify_signature(self, msg, sig):
        return True


def _mini_net(n_nodes=2):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([140 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis(CHAIN, vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    return nodes


def test_dump_tenants_over_real_rpc():
    """GET /dump_tenants and the JSON-RPC form over a live server (the
    curl surface), /metrics tenant families on a real scrape with the
    top-K + _retired cardinality bound, and post-stop history via the
    module global (_LAST)."""
    old_g, old_l = planemod._GLOBAL, planemod._LAST
    old_rg, old_rl = vtenants._GLOBAL, vtenants._LAST
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    nodes = _mini_net(2)
    try:
        set_global_plane(plane)
        assert vtenants.global_registry() is plane.tenants
        for n in nodes:
            n.start()
        url = nodes[0].rpc_listen("127.0.0.1", 0)
        assert nodes[0].consensus.wait_for_height(1, timeout=30.0)
        # the live nodes' own vote traffic is tenant-keyed by chain_id;
        # a second chain's rows through the same plane makes the dump
        # (and the scrape) genuinely multi-tenant
        plane.tenants.register("other-chain", row_quota=1024)
        f = plane.submit_many([(_Pub(), b"m", b"s")] * 3,
                              chain_id="other-chain")
        assert f.result(5) == (True, True, True)
        with urllib.request.urlopen(url + "/dump_tenants",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["tenants"][CHAIN]["rows"] >= 1
        assert doc["tenants"]["other-chain"]["rows"] == 3
        assert doc["tenants"]["other-chain"]["row_quota"] == 1024
        assert doc["registry_size"] >= 2
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_tenants",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["tenants"]["other-chain"]["rows"] == 3
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("cometbft_verifyplane_tenant_rows_total",
                    "cometbft_verifyplane_tenant_sheds_total",
                    "cometbft_verifyplane_tenant_registry_size",
                    "cometbft_verifyplane_tenant_resident_bytes"):
            assert fam in text, fam
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith(
                "cometbft_verifyplane_tenant_rows_total{")
            and 'tenant="other-chain"' in ln)
        assert float(line.split()[-1]) == 3.0
        # the monotonicity accumulator's series is always exposed
        assert any('tenant="_retired"' in ln
                   for ln in text.splitlines()
                   if ln.startswith(
                       "cometbft_verifyplane_tenant_rows_total{"))
        snapshot = vtenants.dump_tenants()
    finally:
        for n in nodes:
            n.stop()
        set_global_plane(None)
        plane.stop()
        planemod._GLOBAL, planemod._LAST = old_g, old_l
        vtenants._GLOBAL, vtenants._LAST = old_rg, old_rl
    # history after the plane unmounted: _LAST still serves the dump
    vtenants.set_global_registry(plane.tenants)
    vtenants.clear_global_registry(plane.tenants)
    try:
        doc = vtenants.dump_tenants()
        assert doc["tenants"]["other-chain"]["rows"] == 3
        # the live nodes kept voting past the snapshot; history is
        # monotone, never rewound
        assert doc["tenants"][CHAIN]["rows"] >= \
            snapshot["tenants"][CHAIN]["rows"]
    finally:
        vtenants._GLOBAL, vtenants._LAST = old_rg, old_rl


def test_dump_tenants_empty_doc_fallback():
    """With no registry ever mounted, /dump_tenants serves the empty
    document, not an error (the curl-on-a-fresh-node case)."""
    old_rg, old_rl = vtenants._GLOBAL, vtenants._LAST
    vtenants._GLOBAL = vtenants._LAST = None
    try:
        doc = vtenants.dump_tenants()
        assert doc["tenants"] == {} and doc["registry_size"] == 0
    finally:
        vtenants._GLOBAL, vtenants._LAST = old_rg, old_rl


def test_tenant_report_diff_detects_synthetic_regression(
        tmp_path, capsys):
    """The --diff CLI path flags injected shed/wait regressions (exit
    1 under --fail-on-regression), stays quiet on identical dumps, and
    errors on a miswired gate (--fail-on-regression without --diff)."""
    from tools import tenant_report

    reg = vtenants.TenantRegistry()
    reg.register("chain-a", row_quota=64)
    reg.note_served("chain-a", "bulk", 100, 1.0)
    reg.note_served("chain-b", "consensus", 40, 0.5)
    dump = reg.dump()
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump))
    doctored = copy.deepcopy(dump)
    doctored["tenants"]["chain-a"]["sheds"] = 75
    doctored["tenants"]["chain-b"]["warm_skips"] = 30
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(doctored))

    rc = tenant_report.main([str(a_path), str(a_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = tenant_report.main([str(a_path), str(b_path), "--diff",
                             "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "sheds_total" in out and "warm_skips_total" in out
    assert "chain-a" in out  # the per-tenant shed-growth note
    with pytest.raises(SystemExit):
        tenant_report.main([str(a_path), "--fail-on-regression"])
    # the single-dump report renders the per-tenant table
    capsys.readouterr()
    assert tenant_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "chain-a" in out and "chain-b" in out
    assert "2 tenants" in out
    # bench --json-out evidence files are a first-class input shape
    wrapped = {"results": {"cfg17_smoke": {
        "metric": "x", "value": 1.0,
        "extra": {"tenants_dump": dump}}}}
    w_path = tmp_path / "bench.json"
    w_path.write_text(json.dumps(wrapped))
    loaded = tenant_report.load_tenants(str(w_path))
    assert loaded["tenants"]["chain-a"]["rows"] == 100
    junk = tmp_path / "junk.json"
    junk.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        tenant_report.load_tenants(str(junk))


def test_cross_dump_hammer_during_plane_stop():
    """ISSUE 20 satellite: reader threads hammering all three dump
    surfaces (tenants + devices + flushes — the module-level bodies
    the RPC handlers serve) WHILE the plane verifies fused
    multi-tenant batches and then WHILE it stops. No dump may raise or
    produce an unserializable document, and the post-stop history must
    still reconcile EXACTLY: the registry's per-tenant device totals
    equal the flush ledger's charged columns (integer us, drift all
    zero) even though the readers raced the ledger drain."""
    import threading
    import time

    from cometbft_tpu.libs import deviceledger

    old_g, old_l = planemod._GLOBAL, planemod._LAST
    old_rg, old_rl = vtenants._GLOBAL, vtenants._LAST
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    stop_hammer = threading.Event()
    served = {"tenants": 0, "devices": 0, "flushes": 0}
    errors = []

    def hammer(name, fn):
        while not stop_hammer.is_set():
            try:
                json.dumps(fn())
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append((name, repr(e)))
                return
            served[name] += 1
            time.sleep(0.002)  # 1-core host: don't starve the plane

    threads = [
        threading.Thread(target=hammer, args=pair, daemon=True)
        for pair in (("tenants", vtenants.dump_tenants),
                     ("devices", deviceledger.dump_devices),
                     ("flushes", planemod.dump_flushes))]
    try:
        set_global_plane(plane)
        for t in threads:
            t.start()
        # interleaved per-tenant work plus concurrent cross-tenant
        # bursts, so the rows split rule runs under the hammer too
        for i in range(6):
            futs = [plane.submit_many(
                        [(_Pub(), b"m", b"s")] * (2 + i % 3),
                        chain_id=c)
                    for c in ("hammer-a", "hammer-b")]
            for f in futs:
                assert all(f.result(30.0))
        # stop WHILE the dump threads hammer: the exact seam this
        # satellite targets — ledger drain + registry charge racing
        # the read side
        plane.stop()
        time.sleep(0.05)  # a few post-stop dumps land under the test
    finally:
        stop_hammer.set()
        for t in threads:
            t.join(timeout=10.0)
        plane.stop()
        set_global_plane(None)
        planemod._GLOBAL, planemod._LAST = old_g, old_l
        vtenants._GLOBAL, vtenants._LAST = old_rg, old_rl
    assert not errors, errors
    assert all(n >= 1 for n in served.values()), served
    assert not any(t.is_alive() for t in threads)
    # post-stop history: device columns present, charges conserved
    recs = plane.ledger.records()
    assert recs, "no flush recorded"
    doc = plane.tenants.dump()
    for col in ("device_ms", "comp_ms", "h2d_ms", "delta_bytes"):
        assert col in doc["tenants"]["hammer-a"], doc["tenants"]
    assert doc["tenants"]["hammer-a"]["rows"] >= 18  # 2+3+4 per pass
    rd = vtenants.reconcile_device(recs, plane.tenants)
    assert all(v == 0 for v in rd["drift"].values()), rd


def test_no_jax_import():
    """The whole file ran host-only: nothing here may pull jax in."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
