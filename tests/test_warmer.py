"""Epoch-churn cache bounding + the next-epoch table warmer (ISSUE 12).

Everything here is host-only and jax-free by design: the bounded-LRU /
eviction / warm-attribution core lives in cometbft_tpu/ops/table_cache
and the warmer's machinery takes an injected build_fn, so the churn
survival properties — memory flat across N epochs, the LIVE epoch's
table never evicted, warmer faults degrading to the cold path — are
provable on the 1-core tier-1 host without a device build.
"""
import gc
import threading
import weakref

import pytest

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.ops import table_cache as tc
from cometbft_tpu.verifyplane import warmer as wm


@pytest.fixture(autouse=True)
def _clean():
    fp.reset()
    yield
    fp.reset()
    wm.set_global_warmer(None)
    wm._LAST = None


class FakeTable:
    """Sized stand-in for a ValsetTable (duck-typed via nbytes)."""

    def __init__(self, nbytes=1000):
        self.nbytes = nbytes


# ---------------------------------------------------------------------------
# bounded caches: eviction pressure, live-table safety, accounting
# ---------------------------------------------------------------------------


def test_bounded_lru_eviction_pressure_holds_memory_flat():
    """N epochs of churn through a capacity-C cache: resident bytes
    stay bounded by C tables, evictions are counted honestly, and the
    LIVE epoch's table — touched by every flush — never evicts."""
    cache = tc.BoundedLRU("tables", 4, size_fn=tc.default_size)
    ev0 = tc.STATS["evictions_tables"]
    live_key = b"live-epoch"
    cache.put(live_key, FakeTable(10_000))
    peak = 0
    for epoch in range(50):
        # a steady flush stream hits the live table between epochs
        assert cache.get(live_key) is not None, f"live evicted @ {epoch}"
        cache.put(b"epoch-%d" % epoch, FakeTable(10_000))
        peak = max(peak, cache.resident_bytes())
        assert len(cache) <= 4
    assert peak <= 4 * 10_000  # memory flat: never more than capacity
    assert tc.STATS["evictions_tables"] - ev0 == 50 - 3  # honest count
    assert cache.get(live_key) is not None  # survived all 50 epochs


def test_set_capacity_trims_and_clamps():
    cache = tc.BoundedLRU("tables", 8, size_fn=tc.default_size)
    for i in range(8):
        cache.put(i, FakeTable(100))
    ev0 = tc.STATS["evictions_tables"]
    cache.set_capacity(3)
    assert len(cache) == 3 and cache.resident_bytes() == 300
    assert tc.STATS["evictions_tables"] - ev0 == 5
    # capacity 1 would let a next-epoch warm insert evict the LIVE
    # table mid-flush: clamped to 2
    cache.set_capacity(1)
    assert cache.capacity == 2


def test_rotated_out_table_is_actually_evictable():
    """The churn leak regression: once the bounded caches drop a
    retired epoch's entries, NOTHING keeps the old table alive — no
    lingering strong ref via memo tuples (weakref dies after gc)."""
    cache = tc.BoundedLRU("tables", 2, size_fn=tc.default_size)
    old = FakeTable(5000)
    ref = weakref.ref(old)
    cache.put(b"epoch-0", old)
    del old
    cache.put(b"epoch-1", FakeTable(5000))
    cache.put(b"epoch-2", FakeTable(5000))  # evicts epoch-0
    gc.collect()
    assert ref() is None, "rotated-out table still strongly referenced"


def test_config_capacities_flow_into_caches():
    from cometbft_tpu.config.config import Config, ConfigError

    saved = tc.capacities()
    try:
        cfg = Config()
        cfg.crypto.table_cache_tables = 5
        cfg.crypto.table_cache_shard_tables = 3
        cfg.crypto.table_cache_memo_entries = 4
        cfg.validate_basic()
        cfg.crypto.apply_table_cache()
        caps = tc.capacities()
        assert caps["tables"] == 5 and caps["shard_tables"] == 3
        assert caps["valset_memo"] == 4 and caps["key_memo"] == 8
        cfg.crypto.table_cache_tables = 1
        with pytest.raises(ConfigError):
            cfg.validate_basic()
        # the deck keeps a live sharded table per half: flights > 1
        # needs shard-cache headroom for a both-halves warm
        cfg.crypto.table_cache_tables = 8
        cfg.crypto.table_cache_shard_tables = 2
        cfg.verify_plane.pipeline_flights = 2
        with pytest.raises(ConfigError):
            cfg.validate_basic()
        cfg.crypto.table_cache_shard_tables = 4
        cfg.validate_basic()
    finally:
        tc.set_capacities(**saved)


def test_warm_next_epoch_knob_builds_warmer():
    """[verify_plane] warm_next_epoch gates the node's TableWarmer;
    the knob survives a TOML round trip."""
    from cometbft_tpu.config.config import (
        Config,
        load_config,
        save_config,
    )

    cfg = Config()
    cfg.verify_plane.enable = True
    assert cfg.verify_plane.warm_next_epoch is True  # default on
    assert isinstance(cfg.verify_plane.build_warmer(), wm.TableWarmer)
    cfg.verify_plane.warm_next_epoch = False
    assert cfg.verify_plane.build_warmer() is None
    cfg.verify_plane.enable = False
    cfg.verify_plane.warm_next_epoch = True
    assert cfg.verify_plane.build_warmer() is None  # plane off: no warm
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "config.toml")
        cfg.verify_plane.warm_next_epoch = False
        save_config(cfg, p)
        assert load_config(p).verify_plane.warm_next_epoch is False


def test_warmed_key_attribution_bounded():
    base = tc.STATS["warmed_hits"]
    tc.note_warmed(b"k1")
    assert tc.consume_warmed(b"k1") is True
    assert tc.consume_warmed(b"k1") is False  # one hit per warm
    assert tc.STATS["warmed_hits"] - base == 1
    for i in range(100):
        tc.note_warmed(b"flood-%d" % i)
    assert len(tc._WARMED) <= tc._WARMED_MAX


# ---------------------------------------------------------------------------
# the warmer: build, degrade, supersede, stop-mid-warm
# ---------------------------------------------------------------------------


class FakeBreaker:
    def __init__(self, state="closed"):
        self.state = state


def test_warmer_builds_and_attributes(tmp_path):
    built = []
    w = wm.TableWarmer(build_fn=lambda p, pw: built.append((p, pw)),
                       breaker=FakeBreaker())
    w.start()
    try:
        w.request((b"a" * 32, b"b" * 32), (5, 7))
        assert w.wait_idle(5.0)
        assert built == [((b"a" * 32, b"b" * 32), (5, 7))]
        assert w.stats()["builds_ok"] == 1
    finally:
        w.stop()


def test_warmer_failpoint_degrades_to_cold_path():
    """warmer.build raising must count a failure and touch nothing —
    the next rotation simply takes the cold path."""
    built = []
    fp.registry().arm_from_spec("warmer.build=raise*1")
    w = wm.TableWarmer(build_fn=lambda p, pw: built.append(1),
                       breaker=FakeBreaker())
    w.start()
    try:
        w.request((b"x",), (1,))
        assert w.wait_idle(5.0)
        assert built == [] and w.stats()["builds_failed"] == 1
        # the armed shot is spent: the next warm succeeds
        w.request((b"y",), (1,))
        assert w.wait_idle(5.0)
        assert built == [1] and w.stats()["builds_ok"] == 1
    finally:
        w.stop()


def test_warmer_skips_when_breaker_open():
    built = []
    brk = FakeBreaker("open")
    w = wm.TableWarmer(build_fn=lambda p, pw: built.append(1),
                       breaker=brk)
    w.start()
    try:
        w.request((b"x",), (1,))
        assert w.wait_idle(5.0)
        assert built == [] and w.stats()["builds_skipped"] == 1
        brk.state = "closed"
        w.request((b"x",), (1,))
        assert w.wait_idle(5.0)
        assert built == [1]
    finally:
        w.stop()


def test_warmer_no_device_no_buildfn_skips():
    w = wm.TableWarmer(breaker=FakeBreaker(), use_device=False)
    w.start()
    try:
        w.request((b"x",), (1,))
        assert w.wait_idle(5.0)
        assert w.stats()["builds_skipped"] == 1
    finally:
        w.stop()


def test_warmer_latest_request_wins():
    """Back-to-back rotations: an unstarted older request is
    superseded — the warmer never builds a stale epoch's table."""
    gate = threading.Event()
    built = []

    def slow_build(p, pw):
        built.append(p)
        gate.wait(5.0)

    w = wm.TableWarmer(build_fn=slow_build, breaker=FakeBreaker())
    w.start()
    try:
        w.request((b"e1",), None)
        # wait until e1's build is holding the gate, then pile on
        for _ in range(200):
            if built:
                break
            threading.Event().wait(0.01)
        assert built == [(b"e1",)]
        w.request((b"e2",), None)
        w.request((b"e3",), None)  # supersedes e2 before it starts
        gate.set()
        assert w.wait_idle(5.0)
        assert built == [(b"e1",), (b"e3",)]
        assert w.stats()["superseded"] == 1
    finally:
        w.stop()


def test_warmer_stop_mid_warm_is_clean():
    """stop() during a wedged build returns promptly (the build is
    abandoned to its daemon thread) and later requests are refused."""
    gate = threading.Event()
    w = wm.TableWarmer(build_fn=lambda p, pw: gate.wait(10.0),
                       breaker=FakeBreaker())
    w.start()
    w.request((b"e1",), None)
    import time

    t0 = time.monotonic()
    w.stop()
    assert time.monotonic() - t0 < 5.0
    assert not w.is_running()
    w.request((b"e2",), None)  # no-op on a stopped warmer
    gate.set()


def test_notify_next_valset_plumbs_through_global():
    """state/execution.py's seam: a registered running warmer receives
    the extracted (pubs, powers) columns; with none registered the
    notify is a no-op."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    privs = [PrivKey.generate(bytes([40 + i]) * 32) for i in range(3)]
    vs = ValidatorSet([Validator(p.pub_key(), 10 + i)
                       for i, p in enumerate(privs)])
    wm.notify_next_valset(vs)  # no warmer: must not raise

    built = []
    w = wm.TableWarmer(build_fn=lambda p, pw: built.append((p, pw)),
                       breaker=FakeBreaker())
    w.start()
    wm.set_global_warmer(w)
    try:
        wm.notify_next_valset(vs)
        assert w.wait_idle(5.0)
        assert len(built) == 1
        pubs, powers = built[0]
        assert pubs == tuple(v.pub_key.data for v in vs.validators)
        assert powers == tuple(v.voting_power for v in vs.validators)
    finally:
        wm.clear_global_warmer(w)
        w.stop()


def test_warmer_metrics_scrape():
    """/metrics: the warmer build outcomes, eviction counters, warm
    hits and resident bytes all surface (lint-clean names) at scrape
    time, sampled from the jax-free core."""
    from cometbft_tpu.libs.metrics import NodeMetrics
    from tools.metrics_lint import lint_registry

    w = wm.TableWarmer(build_fn=lambda p, pw: None,
                       breaker=FakeBreaker())
    w.start()
    wm.set_global_warmer(w)
    try:
        w.request((b"m1",), None)
        assert w.wait_idle(5.0)
        tc.note_warmed(b"scrape-test")
        tc.consume_warmed(b"scrape-test")
        m = NodeMetrics()
        assert lint_registry(m.registry) == []
        text = m.expose_text()
        assert "cometbft_crypto_table_cache_evictions_total" in text
        assert "cometbft_crypto_table_cache_resident_bytes" in text
        assert ('cometbft_verifyplane_valset_warmer_builds_total'
                '{outcome="ok"} 1') in text
        assert "cometbft_verifyplane_valset_warmer_hits_total" in text
    finally:
        wm.clear_global_warmer(w)
        w.stop()


def test_rotation_on_live_node_reaches_warmer(tmp_path):
    """End to end through the REAL path: a kvstore ``val:`` tx commits
    on a live single-node chain -> finalize_block validator_updates ->
    update_with_change_set -> state/execution.py notifies the warmer
    with the epoch e+1 columns (the new member present, at its new
    power)."""
    import base64
    import time

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    priv = PrivKey.generate(b"\x61" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("warm-e2e", vals)
    built = []
    w = wm.TableWarmer(build_fn=lambda p, pw: built.append((p, pw)),
                       breaker=FakeBreaker())
    w.start()
    wm.set_global_warmer(w)
    node = Node(KVStoreApplication(), state,
                privval=FilePV(priv), home=str(tmp_path / "n0"))
    try:
        node.start()
        new_pub = PrivKey.generate(b"\x62" * 32).pub_key().data
        tx = b"val:" + base64.b64encode(new_pub) + b"!7!e1"
        node.mempool.check_tx(tx)
        deadline = time.monotonic() + 30
        while not built and time.monotonic() < deadline:
            time.sleep(0.05)
        assert built, "rotation never reached the warmer"
        pubs, powers = built[0]
        assert new_pub in pubs
        assert powers[pubs.index(new_pub)] == 7
    finally:
        node.stop()
        wm.clear_global_warmer(w)
        w.stop()


# ---------------------------------------------------------------------------
# rotation hardening (review findings): duplicate updates in one
# block, warm-attribution honesty, and mesh-key targeting
# ---------------------------------------------------------------------------


def test_kvstore_dedups_validator_updates_last_wins():
    """Two rotations of ONE validator landing in the same block (out
    at epoch k, back in at k+1) must collapse to a single update —
    update_with_change_set rejects duplicate addresses, and that
    rejection would halt the chain on every honest node."""
    import base64

    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.abci.kvstore import KVStoreApplication

    app = KVStoreApplication()
    b64 = base64.b64encode(b"\x10" * 32)
    resp = app.finalize_block(abci.RequestFinalizeBlock(
        txs=[b"val:" + b64 + b"!0!e1", b"val:" + b64 + b"!5!e2"],
        height=1))
    assert [r.code for r in resp.tx_results] == [0, 0]
    assert len(resp.validator_updates) == 1
    assert resp.validator_updates[0].power == 5  # last tx wins


def test_kvstore_rejects_negative_power():
    """A negative-power val tx is malformed at every gate (CheckTx,
    ProcessProposal, FinalizeBlock result) — update_with_change_set
    raises on negative power, so letting it through would hand anyone
    a one-tx chain halt."""
    import base64

    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.abci.kvstore import KVStoreApplication

    app = KVStoreApplication()
    tx = b"val:" + base64.b64encode(b"\x11" * 32) + b"!-1"
    assert app.check_tx(abci.RequestCheckTx(tx=tx)).code == 1
    assert app.process_proposal(
        abci.RequestProcessProposal(txs=[tx])
    ).status == abci.PROCESS_PROPOSAL_REJECT
    resp = app.finalize_block(abci.RequestFinalizeBlock(txs=[tx],
                                                        height=1))
    assert resp.tx_results[0].code == 1
    assert resp.validator_updates == []


def test_warmer_repeat_notify_does_not_self_consume(monkeypatch):
    """A repeat warm request for an IDENTICAL valset must not let the
    warmer's own lookup pop the still-pending warm mark (that would
    count a warmed_hit no verifier ever saw): the warmer peeks the
    cache instead of running the consuming hit path."""
    from cometbft_tpu.ops import ed25519_cached as ec

    pubs, powers = (b"repeat-epoch" * 2 + b"xxxxxxxx",), (3,)
    key = ec._cache_key(pubs, powers)
    calls = []
    monkeypatch.setattr(
        ec, "table_for_pubs_info",
        lambda p, pw: (calls.append(1) or (FakeTable(), False)))
    w = wm.TableWarmer(breaker=FakeBreaker(), use_device=True,
                       mesh_fn=lambda: None)
    w.start()
    try:
        hits0 = tc.STATS["warmed_hits"]
        w.request(pubs, powers)
        assert w.wait_idle(5.0)
        assert len(calls) == 1 and key in tc._WARMED
        # the table is now cached; a repeat notify peeks, skips the
        # consuming lookup, and leaves the mark pending
        with tc.LOCK:
            tc.TABLES.put(key, FakeTable())
        w.request(pubs, powers)
        assert w.wait_idle(5.0)
        assert len(calls) == 1  # no second lookup at all
        assert key in tc._WARMED  # mark still pending for a verifier
        assert tc.STATS["warmed_hits"] == hits0
    finally:
        w.stop()
        with tc.LOCK:
            tc.TABLES.pop(key)
        tc._WARMED.pop(key, None)


def test_flush_mesh_publishes_halves_before_resolved():
    """The warmer reads (_mesh_resolved, _mesh, _halves) from its own
    thread: the plane must assign the halves BEFORE publishing
    _mesh_resolved, or a concurrent warm targets the full mesh whose
    key no deck flush ever looks up."""
    import inspect

    from cometbft_tpu.verifyplane.plane import VerifyPlane

    src = inspect.getsource(VerifyPlane._flush_mesh)
    assert src.index("self._halves") < src.index(
        "self._mesh_resolved = True"), \
        "_mesh_resolved published before _halves is assigned"


def test_update_state_filters_unapplicable_changes():
    """The engine-side belt-and-braces: duplicate addresses collapse
    (last wins) and removals of not-in-set validators drop — both
    deterministically — instead of wedging apply_block."""
    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.block import Block, Data, Header
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    privs = [PrivKey.generate(bytes([50 + i]) * 32) for i in range(3)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("filter-chain", vals)
    ex = BlockExecutor(None, None)
    header = Header(chain_id="filter-chain", height=1,
                    time=Timestamp(1_700_000_000, 0))
    block = Block(header, Data([]), None)
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))
    ghost = PrivKey.generate(b"\x77" * 32).pub_key()
    neg = PrivKey.generate(b"\x78" * 32).pub_key()
    dup = privs[0].pub_key()
    resp = abci.ResponseFinalizeBlock(
        tx_results=[], app_hash=b"",
        validator_updates=[
            abci.ValidatorUpdate(dup.data, 0),      # out...
            abci.ValidatorUpdate(dup.data, 17),     # ...and back: wins
            abci.ValidatorUpdate(ghost.data, 0),    # never a member
            abci.ValidatorUpdate(neg.data, -5),     # buggy app
        ])
    new_state = ex._update_state(state, bid, block, resp)
    nv = new_state.next_validators
    assert nv.has_address(dup.address())
    _, v = nv.get_by_address(dup.address())
    assert v.voting_power == 17
    assert not nv.has_address(ghost.address())
    assert not nv.has_address(neg.address())
    assert len(nv) == 3


def test_warmer_does_not_claim_tables_built_cold(monkeypatch):
    """Honest attribution: when the rotation's first commit beat the
    warm (consensus paid the cold build, the warmer's lookup is a
    HIT), the warmer must NOT mark the key — warmed_hits would credit
    the warmer for a stall that actually happened."""
    from cometbft_tpu.ops import ed25519_cached as ec

    sent = {"hit": True}
    monkeypatch.setattr(ec, "table_for_pubs_info",
                        lambda p, pw: (object(), sent["hit"]))
    noted = []
    monkeypatch.setattr(ec, "note_warmed", noted.append)
    w = wm.TableWarmer(breaker=FakeBreaker(), use_device=True,
                       mesh_fn=lambda: None)
    w.start()
    try:
        w.request((b"cold-already-paid",), (1,))
        assert w.wait_idle(5.0)
        assert noted == []  # hit: no false credit
        sent["hit"] = False
        w.request((b"genuinely-warmed",), (1,))
        assert w.wait_idle(5.0)
        assert len(noted) == 1  # built: attributed
    finally:
        w.stop()


def test_warmer_mesh_targets_match_dispatch_keys(monkeypatch):
    """The warm must target the meshes flushes actually look tables up
    under: the effective_mesh-clamped fan-out, and the deck's HALVES
    when pipeline_flights configured them — warming the full resolved
    mesh would never match a clamped/half lookup key."""
    from types import SimpleNamespace

    from cometbft_tpu.verifyplane import fused as fz
    from cometbft_tpu.verifyplane import plane as vp

    mesh8 = fz.plane_mesh(0)
    assert mesh8 is not None and mesh8.devices.size == 8
    halves = fz.half_meshes(mesh8)
    assert len(halves) == 2

    w = wm.TableWarmer(breaker=FakeBreaker())
    # no halves (single-flight plane): the effective FULL mesh —
    # clamped to the devices a 300-validator set actually fills
    fake = SimpleNamespace(_mesh_resolved=True, _mesh=mesh8,
                           _halves=[])
    monkeypatch.setattr(vp, "_GLOBAL", fake)
    targets = w._mesh_targets(300)
    assert targets == [fz.effective_mesh(mesh8, 300)[0]]
    assert targets[0].devices.size < 8  # clamped, not the full mesh
    # halves configured: BOTH halves' effective meshes (steady deck
    # flushes ride halves, so those are the lookup keys)
    fake._halves = halves
    targets = w._mesh_targets(300)
    assert targets == [fz.effective_mesh(h, 300)[0] for h in halves]
    # a valset that fits one stride: no sharded warm at all
    assert w._mesh_targets(50) == []


# ---------------------------------------------------------------------------
# the election rule (simnet/actors.py)
# ---------------------------------------------------------------------------


def test_proportional_election_deterministic_bounded_churn():
    from cometbft_tpu.simnet import actors

    stakes = {i: (b"pub-%d" % i, 1 + i % 7) for i in range(40)}
    committee = list(range(20))
    standby = list(range(20, 40))
    c1 = actors.proportional_election(7, 3, committee, standby,
                                      stakes, 0.25)
    c2 = actors.proportional_election(7, 3, committee, standby,
                                      stakes, 0.25)
    assert c1 == c2  # pure function of (seed, epoch, committee)
    new_committee, new_standby, out, inn = c1
    assert len(out) == len(inn) == 5  # exactly 25% of 20
    assert set(out) <= set(committee) and set(inn) <= set(standby)
    assert len(new_committee) == 20
    assert sorted(new_committee + new_standby) == list(range(40))
    # a different epoch draws a different rotation
    c3 = actors.proportional_election(7, 4, committee, standby,
                                      stakes, 0.25)
    assert c3 != c1
    # stake-proportionality, coarsely: across many epochs the heaviest
    # standby members win seats far more often than the lightest
    wins = {i: 0 for i in standby}
    for epoch in range(200):
        _, _, _, inn = actors.proportional_election(
            11, epoch, committee, standby, stakes, 0.25)
        for i in inn:
            wins[i] += 1
    heavy = [i for i in standby if stakes[i][1] >= 6]
    light = [i for i in standby if stakes[i][1] <= 2]
    heavy_rate = sum(wins[i] for i in heavy) / len(heavy)
    light_rate = sum(wins[i] for i in light) / len(light)
    assert heavy_rate > 2 * light_rate, (heavy_rate, light_rate)
