"""Differential tests: JAX limb field arithmetic vs Python big ints."""
import numpy as np
import pytest

import jax.numpy as jnp

from cometbft_tpu.ops.field import F25519, FSECP, NLIMBS, limbs_to_int

RNG = np.random.default_rng(7)
FIELDS = [F25519, FSECP]


def rand_elems(f, n):
    vals = [int.from_bytes(RNG.bytes(40), "little") % f.p for _ in range(n)]
    limbs = np.stack([f.from_int(v) for v in vals])
    return vals, jnp.asarray(limbs)


def check(f, got_limbs, expect_ints):
    got = limbs_to_int(np.asarray(got_limbs))
    got = np.asarray(got % f.p if isinstance(got, int) else [g % f.p for g in got])
    exp = np.asarray([e % f.p for e in expect_ints])
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("f", FIELDS, ids=["ed25519", "secp256k1"])
def test_add_sub_mul(f):
    a_int, a = rand_elems(f, 32)
    b_int, b = rand_elems(f, 32)
    check(f, f.add(a, b), [x + y for x, y in zip(a_int, b_int)])
    check(f, f.sub(a, b), [x - y for x, y in zip(a_int, b_int)])
    check(f, f.mul(a, b), [x * y for x, y in zip(a_int, b_int)])
    check(f, f.square(a), [x * x for x in a_int])
    check(f, f.neg(a), [-x for x in a_int])
    check(f, f.mul_small(a, 121666), [x * 121666 for x in a_int])


@pytest.mark.parametrize("f", FIELDS, ids=["ed25519", "secp256k1"])
def test_deep_chain_no_canonical(f):
    """Stress the lazy-limb invariant: long op chains w/o canonicalization."""
    a_int, a = rand_elems(f, 8)
    b_int, b = rand_elems(f, 8)
    x, xi = a, list(a_int)
    for i in range(50):
        if i % 3 == 0:
            x, xi = f.mul(x, b), [u * v for u, v in zip(xi, b_int)]
        elif i % 3 == 1:
            x, xi = f.sub(f.add(x, x), b), [2 * u - v for u, v in zip(xi, b_int)]
        else:
            x, xi = f.square(x), [u * u for u in xi]
        xi = [u % f.p for u in xi]
    check(f, x, xi)
    # limbs stayed mul-safe throughout
    assert int(np.abs(np.asarray(x)).max()) <= 2**13 + 2**6


@pytest.mark.parametrize("f", FIELDS, ids=["ed25519", "secp256k1"])
def test_edge_values(f):
    vals = [0, 1, 2, f.p - 1, f.p - 2, (f.p - 1) // 2, 19, 2**255 - 20]
    vals = [v % f.p for v in vals]
    limbs = jnp.asarray(np.stack([f.from_int(v) for v in vals]))
    check(f, f.mul(limbs, limbs), [v * v for v in vals])
    check(f, f.sub(limbs, f.add(limbs, limbs)), [-v for v in vals])
    z = f.sub(limbs, limbs)
    assert bool(np.all(np.asarray(f.is_zero(z))))
    # v + 1 is zero mod p exactly when v == p - 1
    zp = np.asarray(f.is_zero(f.add(limbs, f.const(1, (len(vals),)))))
    np.testing.assert_array_equal(zp, np.asarray([v == f.p - 1 for v in vals]))


@pytest.mark.parametrize("f", FIELDS, ids=["ed25519", "secp256k1"])
def test_pow_inv_canonical_parity(f):
    a_int, a = rand_elems(f, 4)
    check(f, f.pow_const(a, 5), [pow(v, 5, f.p) for v in a_int])
    check(f, f.inv(a), [pow(v, f.p - 2, f.p) for v in a_int])
    canon = np.asarray(f.canonical(f.mul(a, a)))
    assert (canon >= 0).all() and (canon < 2**13).all()
    got = limbs_to_int(canon)
    np.testing.assert_array_equal(
        np.asarray([int(g) for g in got]),
        np.asarray([v * v % f.p for v in a_int]),
    )
    par = np.asarray(f.parity(a))
    np.testing.assert_array_equal(par, np.asarray([v & 1 for v in a_int]))
    assert bool(np.all(np.asarray(f.eq(a, a))))


def test_from_bytes_le():
    raw = RNG.integers(0, 256, size=(16, 32), dtype=np.uint8)
    limbs = F25519.from_bytes_le(raw, nbits=255)
    ints = limbs_to_int(limbs)
    for i in range(16):
        expect = int.from_bytes(raw[i].tobytes(), "little") & ((1 << 255) - 1)
        assert int(ints[i]) == expect


def test_eq_across_representations():
    """Same value reached via different op chains must compare equal."""
    f = F25519
    a_int, a = rand_elems(f, 8)
    x = f.mul(a, f.const(3, (8,)))
    y = f.add(f.add(a, a), a)
    assert bool(np.all(np.asarray(f.eq(x, y))))
