"""Archival bootstrap plane tier-1 wiring (ISSUE 18): GET+JSON-RPC
/dump_catchup over a live server, /metrics statesync families riding a
real scrape, and the catchup_report --diff regression detector
(including the miswired --fail-on-regression gate).

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP).
"""
import copy
import json
import sys
import urllib.request

import pytest

from cometbft_tpu.blocksync import catchup as cu
from cometbft_tpu.blocksync.catchup import CatchupLedger
from cometbft_tpu.libs import tracing
from cometbft_tpu.statesync import stats as ss_stats

_JAX_LOADED_BEFORE = "jax" in sys.modules


def _ledger(n_flushes=10, blocks=10, sigs=30, gap_ms=100.0,
            verify_ms=2.0, resumes=0, boundaries_every=5,
            warm=True, skipped_first=0):
    """Deterministic ledger on a virtual clock: exact window rates."""
    now = [10 ** 12]
    tracing.set_clock(lambda: now[0])
    try:
        led = CatchupLedger()
        h = 1
        for i in range(n_flushes):
            skipped = skipped_first if i == 0 else 0
            boundary = boundaries_every and (i + 1) % boundaries_every == 0
            led.record(first=h, last=h + blocks - 1, blocks=blocks,
                       sigs=sigs, skipped=skipped, read_ms=0.5,
                       verify_ms=verify_ms, apply_ms=0.3,
                       boundary=boundary, warmed=boundary and warm)
            h += blocks
            now[0] += int(gap_ms * 1e6)
        for _ in range(resumes):
            led.note_resume()
        return led
    finally:
        tracing.set_clock(None)


def _dump(led):
    return {"records": led.records(), "summary": led.summary(),
            "counters": dict(led.counters)}


def test_dump_catchup_over_real_rpc(tmp_path):
    """GET /dump_catchup and the JSON-RPC form over a live server (the
    curl surface), plus the statesync metric families on a real
    /metrics scrape."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    old_g, old_l = cu._GLOBAL, cu._LAST
    led = _ledger(n_flushes=4, resumes=1)
    cu.set_global_ledger(led)
    ss_stats.reset()
    ss_stats.bump("chunks_fetched", 7)
    ss_stats.bump("snapshots_shed", 2)
    priv = PrivKey.generate(b"\x18" * 32)
    vals = ValidatorSet([Validator(priv.pub_key(), 10)])
    state = State.make_genesis("zcatchup-chain", vals)
    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    node = Node(KVStoreApplication(), state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=fast)
    node.start()
    try:
        url = node.rpc_listen("127.0.0.1", 0)
        assert node.consensus.wait_for_height(1, timeout=30.0)
        with urllib.request.urlopen(url + "/dump_catchup",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["counters"]["flushes"] == 4
        assert doc["counters"]["resumes"] == 1
        assert len(doc["records"]) == 4
        assert doc["summary"]["blocks_per_s"] > 0
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_catchup",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["counters"]["flushes"] == 4
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("cometbft_statesync_chunks_total",
                    "cometbft_statesync_fetch_timeouts_total",
                    "cometbft_statesync_providers_total",
                    "cometbft_statesync_retry_snapshot_rounds_total",
                    "cometbft_statesync_snapshots_total"):
            assert fam in text, fam
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("cometbft_statesync_chunks_total{")
                    and 'kind="fetched"' in ln)
        assert float(line.split()[-1]) == 7.0
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("cometbft_statesync_snapshots_total{")
            and 'kind="shed"' in ln)
        assert float(line.split()[-1]) == 2.0
    finally:
        node.stop()
        ss_stats.reset()
        cu._GLOBAL, cu._LAST = old_g, old_l


def test_catchup_report_diff_detects_synthetic_regression(
        tmp_path, capsys):
    """The --diff CLI flags an injected throughput decay + verify-time
    growth (exit 1 under --fail-on-regression), stays quiet on
    identical dumps, and errors on a miswired gate."""
    from tools import catchup_report

    dump_a = _dump(_ledger())
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump_a))
    # B: the firehose got 4x slower and every flush pays cold tables
    led_b = _ledger(gap_ms=400.0, verify_ms=30.0, resumes=1,
                    warm=False)
    dump_b = _dump(led_b)
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(dump_b))

    rc = catchup_report.main([str(a_path), str(a_path), "--diff",
                              "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = catchup_report.main([str(a_path), str(b_path), "--diff",
                              "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "blocks_per_s" in out and "verify_ms" in out
    # the resume-without-skips and cold-boundaries notes both fire
    assert "re-verified work" in out
    assert "ZERO warm-ahead" in out
    with pytest.raises(SystemExit):
        catchup_report.main([str(a_path), "--fail-on-regression"])
    # the single-dump report renders the per-flush table
    capsys.readouterr()
    assert catchup_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "100 blocks applied" in out
    assert "valset" in out and "boundaries" in out.replace(
        "boundaries,", "boundaries")
    # bench --json-out evidence files are a first-class input shape
    wrapped = {"results": {"cfg18_smoke": {
        "metric": "x", "value": 1.0,
        "extra": {"catchup_dump": dump_a}}}}
    w_path = tmp_path / "bench.json"
    w_path.write_text(json.dumps(wrapped))
    loaded = catchup_report.load_catchup(str(w_path))
    assert loaded["counters"]["flushes"] == 10
    junk = tmp_path / "junk.json"
    junk.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        catchup_report.load_catchup(str(junk))


def test_report_figures_from_ledger_dump():
    from tools import catchup_report

    rep = catchup_report.catchup_report(_dump(_ledger(
        skipped_first=3, resumes=1)))
    assert rep["blocks_applied"] == 100
    assert rep["blocks_verified"] == 97
    assert rep["blocks_skipped"] == 3
    assert rep["resumes"] == 1
    assert rep["boundaries"] == 2
    assert rep["blocks_per_s"] == pytest.approx(100 / 0.9, rel=0.01)
    assert 0 < rep["verify_frac"] < 1


def test_no_jax_import():
    """The whole file ran host-only: nothing here may pull jax in."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
