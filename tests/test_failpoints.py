"""Failpoint registry unit tests (libs/failpoints.py, the libs/fail
analog): arming, actions, trigger counts, spec parsing, crash-handler
override."""
import time

import pytest

from cometbft_tpu.libs import failpoints as fp


@pytest.fixture(autouse=True)
def clean_registry():
    fp.reset()
    fp.set_crash_handler(None)
    yield
    fp.reset()
    fp.set_crash_handler(None)


def test_unarmed_is_noop():
    fp.register("t.point", "doc")
    fp.fail_point("t.point")  # nothing armed: no raise, no delay
    assert "t.point" in fp.registry().names()


def test_raise_action_and_counts():
    fp.register("t.raise")
    fp.arm("t.raise", "raise", count=2)
    for _ in range(2):
        with pytest.raises(fp.FailpointError):
            fp.fail_point("t.raise")
    # self-disarmed after the trigger count
    fp.fail_point("t.raise")
    st = fp.registry().stats("t.raise")
    assert st["fires"] == 2 and st["action"] == ""


def test_delay_action():
    fp.arm("t.delay", "delay", arg=0.05)
    t0 = time.monotonic()
    fp.fail_point("t.delay")
    assert time.monotonic() - t0 >= 0.05


def test_flake_is_deterministic():
    """flake:3 fires on every 3rd evaluation — no RNG anywhere."""
    fp.arm("t.flake", "flake", arg=3)
    fired = []
    for i in range(9):
        try:
            fp.fail_point("t.flake")
            fired.append(False)
        except fp.FailpointError:
            fired.append(True)
    assert fired == [False, False, True] * 3


def test_crash_handler_override():
    crashes = []
    fp.set_crash_handler(lambda name: crashes.append(name))
    fp.arm("t.crash", "crash", count=1)
    fp.fail_point("t.crash")
    assert crashes == ["t.crash"]
    fp.fail_point("t.crash")  # count exhausted
    assert crashes == ["t.crash"]


def test_simulated_crash_handler():
    fp.set_crash_handler(fp.simulated_crash)
    fp.arm("t.simcrash", "crash")
    with pytest.raises(fp.SimulatedCrash):
        fp.fail_point("t.simcrash")


def test_spec_parse_and_arm():
    spec = "a.b=crash*1; c.d=delay:0.5 ;e.f=flake:4*2"
    assert fp.parse_spec(spec) == [
        ("a.b", "crash", 0.0, 1),
        ("c.d", "delay", 0.5, -1),
        ("e.f", "flake", 4.0, 2),
    ]
    assert fp.arm_from_spec(spec) == 3
    assert fp.registry().stats("c.d")["action"] == "delay"


def test_spec_rejects_garbage():
    with pytest.raises(ValueError):
        fp.parse_spec("no-equals-sign")
    with pytest.raises(ValueError):
        fp.parse_spec("a.b=explode")
    with pytest.raises(ValueError):
        fp.arm("x", "explode")


def test_disarm_and_reset():
    fp.arm("t.x", "raise")
    fp.disarm("t.x")
    fp.fail_point("t.x")
    fp.arm("t.x", "raise")
    fp.arm("t.y", "raise")
    fp.reset()
    fp.fail_point("t.x")
    fp.fail_point("t.y")


def test_instrumented_seams_registered():
    """Every seam the ISSUE names is a registered, discoverable point."""
    import cometbft_tpu.blocksync.pool  # noqa: F401
    import cometbft_tpu.blocksync.reactor  # noqa: F401
    import cometbft_tpu.consensus.state  # noqa: F401
    import cometbft_tpu.consensus.wal  # noqa: F401
    import cometbft_tpu.crypto.batch  # noqa: F401
    import cometbft_tpu.p2p.switch  # noqa: F401
    import cometbft_tpu.p2p.transport  # noqa: F401

    names = fp.registry().names()
    for expected in (
        "wal.pre_write", "wal.post_write", "wal.pre_fsync",
        "wal.mid_rotate",
        "consensus.wal.pre_vote", "consensus.wal.post_vote",
        "consensus.wal.pre_proposal", "consensus.wal.post_proposal",
        "consensus.pre_finalize", "consensus.post_block_save",
        "blocksync.request", "blocksync.deliver", "blocksync.process",
        "p2p.dial", "p2p.handshake",
        "crypto.device_dispatch",
    ):
        assert expected in names, f"failpoint {expected} not registered"


def test_config_spec_validation():
    from cometbft_tpu.config.config import Config, ConfigError

    cfg = Config()
    cfg.failpoints.spec = "wal.pre_fsync=crash*1"
    cfg.validate_basic()  # parses cleanly, does NOT arm
    assert fp.registry().stats("wal.pre_fsync") is None or \
        fp.registry().stats("wal.pre_fsync")["action"] == ""
    cfg.failpoints.spec = "wal.pre_fsync=explode"
    with pytest.raises(ConfigError):
        cfg.validate_basic()


def test_counters_surface_every_point():
    """ISSUE 5 satellite: per-point trigger counts are reachable from
    the registry (they were tracked but unreachable from /metrics)."""
    fp.register("t.counted", "doc")
    fp.arm("t.counted", "raise", count=1)
    with pytest.raises(fp.FailpointError):
        fp.fail_point("t.counted")
    fp.fail_point("t.counted")  # self-disarmed: hit not counted armed
    c = fp.counters()
    assert c["t.counted"]["hits"] == 1
    assert c["t.counted"]["fires"] == 1
    assert c["t.counted"]["armed"] is False
    # unarmed registered points appear too (zero rows)
    assert "wal.pre_fsync" in c


def test_fired_points_emit_trace_instants():
    from cometbft_tpu.libs import tracing

    tracing.enable(capacity=32)
    try:
        fp.arm("t.traced", "raise", count=1)
        with pytest.raises(fp.FailpointError):
            fp.fail_point("t.traced")
        evs = tracing.export_chrome()["traceEvents"]
        fires = [e for e in evs if e["name"] == "failpoint.fire"]
        assert fires and fires[0]["args"] == {"point": "t.traced",
                                              "action": "raise"}
    finally:
        tracing.disable()


def test_registry_swap_keeps_fire_hooks_intact():
    """ISSUE 5 satellite: trace/metric hooks survive registry swaps —
    a per-node fresh_registry inherits the current custom fire hook at
    creation (the simnet's shape), and restoring the original registry
    leaves its own hooks exactly as they were: a node-local hook can
    never contaminate the restored global."""
    seen = []
    fp.registry().set_fire_hook(lambda n, a: seen.append((n, a)))
    try:
        node_reg = fp.fresh_registry(fp.simulated_crash)
        old = fp.swap_registry(node_reg)
        try:
            assert node_reg._fire_hook is old._fire_hook
            fp.arm("n.point", "raise", count=1)
            with pytest.raises(fp.FailpointError):
                fp.fail_point("n.point")
        finally:
            restored = fp.swap_registry(old)
            assert restored is node_reg
        # the hook observed the swapped-in registry's fire...
        assert seen == [("n.point", "raise")]
        # ...and still observes the restored original
        fp.arm("t.after", "raise", count=1)
        with pytest.raises(fp.FailpointError):
            fp.fail_point("t.after")
        assert seen[-1] == ("t.after", "raise")
    finally:
        fp.registry().set_fire_hook(None)
    # restore direction never contaminates: a node registry that grew
    # its OWN hook must not leave it on the global after swap-back
    node_reg = fp.fresh_registry(fp.simulated_crash)
    node_hook = lambda n, a: None  # noqa: E731
    node_reg.set_fire_hook(node_hook)
    old = fp.swap_registry(node_reg)
    assert fp.swap_registry(old) is node_reg
    assert fp.registry()._fire_hook is not node_hook
