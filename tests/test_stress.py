"""Threading stress: hammer the concurrent core (Switch/MConnection/
ConsensusState) looking for deadlocks and races.

Reference strategy: `make test_race` (-race) + go-deadlock + leaktest
(SURVEY.md §4). Python has no tsan, so this hunts the same bugs
behaviorally: many threads doing conflicting operations under time
bounds; a deadlock or a poisoned lock shows up as a timeout, a crash,
or a thread that never exits.
"""
import threading
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def _threads_snapshot():
    return {t.ident for t in threading.enumerate()}


@pytest.mark.slow
def test_switch_connect_disconnect_storm(tmp_path):
    """Peers dialing/disconnecting while broadcasts are in flight: the
    switch must neither deadlock nor leak threads (leaktest analog)."""
    from cometbft_tpu.p2p.switch import Switch

    before = _threads_snapshot()
    ka = NodeKey(PrivKey.generate(b"\x01" * 32))
    kb = NodeKey(PrivKey.generate(b"\x02" * 32))
    sa, sb = Switch(ka, "storm-net"), Switch(kb, "storm-net")
    addr = sa.listen()
    sa.start()
    sb.start()
    stop = threading.Event()
    errs = []

    def broadcaster(sw):
        i = 0
        while not stop.is_set():
            try:
                sw.broadcast(0x30, b"storm-%d" % i)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return
            i += 1
            time.sleep(0.001)

    ts = [threading.Thread(target=broadcaster, args=(s,), daemon=True)
          for s in (sa, sb) for _ in range(3)]
    for t in ts:
        t.start()
    try:
        for cycle in range(6):
            sb.dial_peer(addr, persistent=False)
            deadline = time.time() + 5
            while sb.num_peers() < 1 and time.time() < deadline:
                time.sleep(0.01)
            for p in list(sb.peers.values()):
                sb.stop_peer_for_error(p, "storm cycle")
            deadline = time.time() + 5
            while sb.num_peers() > 0 and time.time() < deadline:
                time.sleep(0.01)
            assert sb.num_peers() == 0, f"peer stuck in cycle {cycle}"
    finally:
        stop.set()
        for t in ts:
            t.join(timeout=5)
        sa.stop()
        sb.stop()
    assert not errs, errs
    # allow teardown threads to die, then check for leaks
    time.sleep(1.0)
    leaked = _threads_snapshot() - before
    alive = [t for t in threading.enumerate()
             if t.ident in leaked and t.is_alive()
             and "mconn" in (t.name or "")]
    assert not alive, f"leaked mconn threads: {alive}"


@pytest.mark.slow
def test_consensus_under_concurrent_intake(tmp_path):
    """4-node net committing while extra threads slam broadcast_tx and
    query from outside — the consensus thread must keep making progress
    and shut down cleanly (the hand-rolled-locks confidence test)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("stress-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    stop = threading.Event()
    errs = []

    def hammer(node, k):
        i = 0
        while not stop.is_set():
            try:
                node.broadcast_tx(b"s%d-%d=%d" % (k, i, i))
                node.query(b"s%d-%d" % (k, i))
                node.height()
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return
            i += 1
            time.sleep(0.002)

    ts = [threading.Thread(target=hammer, args=(nodes[k % 4], k),
                           daemon=True) for k in range(8)]
    for t in ts:
        t.start()
    try:
        for n in nodes:
            assert n.consensus.wait_for_height(6, timeout=90), \
                f"stalled at {n.height()} under load"
        # all nodes agree despite the storm
        h = {n.block_store.load_block(4).hash() for n in nodes}
        assert len(h) == 1
    finally:
        stop.set()
        for t in ts:
            t.join(timeout=5)
        for n in nodes:
            n.stop()
    assert not errs, errs[:3]
