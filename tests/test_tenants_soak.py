"""Multi-tenant verify plane at simnet scale (ISSUE 17 acceptance).

K chain groups share ONE process-global verify plane while chaos and a
signed flood ride one of them:

  * cross-tenant coalescing is ledger-evidenced — two chains' rows
    queued together land in ONE fused flush whose per-tenant
    attribution sums to the flush total;
  * a tenant past its row quota is shed with an explicit retry-hinted
    TenantOverloaded verdict, while another tenant's CONSENSUS lane
    never sees a tenant gate;
  * a real-thread noisy neighbor hammering the BULK lane is quota-shed
    while the victim chains keep committing with bounded verify waits
    and ZERO consensus sheds;
  * the whole multi-chain run — chaos, flood, tenant ledger columns
    and registry totals — replays byte-identically from (seed,
    schedule), and a chain group's commits are bit-identical to the
    SAME chain run solo (the shared plane changes the economics, never
    the verdicts).

Budget discipline follows test_soak.py: the expensive runs are built
once in a module-scoped lazy cache and shared across tests.
"""
import threading

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.simnet import Simnet
from cometbft_tpu.verifyplane import (
    LANE_BULK,
    LANE_CONSENSUS,
    PlaneOverloaded,
    TenantOverloaded,
    VerifyPlane,
    set_global_plane,
)

pytestmark = pytest.mark.simnet


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


N_PER_CHAIN = 3
SEED = 9090
TARGET_H = 4

# chaos on chain group 0 ONLY (nodes 0-2): a signed flood riding
# simnet-0's BULK lane, garbage votes, and a partition that isolates
# node 2 — with 3 equal validators that stalls chain 0 dead until the
# heal. Group 1 (nodes 3-5) is scheduled NOTHING: it is the victim/
# control chain, which is what makes the solo comparison and the
# noisy-neighbor isolation assertions meaningful.
MCHAOS = [
    {"at": 0.6, "op": "flood", "node": 0, "rate": 20.0,
     "duration": 3.0, "signed": True, "size": 24},
    {"at": 0.8, "op": "garbage", "node": 2, "votes": 2},
    {"at": 1.2, "op": "partition", "groups": [[0, 1], [2, 3, 4, 5]]},
    {"at": 2.6, "op": "heal"},
]


class _InstaPub:
    """Flooder row stub: instant verify (the noisy neighbor's load is
    queue pressure, not crypto)."""

    def verify_signature(self, msg, sig):
        return True


class _GatePub:
    """Blocker row: parks the dispatcher inside a verify until
    released, so the test can queue multi-tenant traffic behind it
    deterministically (the plane's only concurrency seam a
    single-threaded simnet never exercises)."""

    def __init__(self):
        self.busy = threading.Event()
        self.release = threading.Event()

    def verify_signature(self, msg, sig):
        self.busy.set()
        self.release.wait(timeout=10.0)
        return True


def _coalesce_demo(plane, privs, chains):
    """Drive the cross-tenant coalescing + quota-shed acceptance
    scenario through the still-running shared plane with the sim
    chains' REAL validator keys: park the dispatcher, queue BULK rows
    from BOTH chains plus a victim CONSENSUS row, shed the flooder
    past its quota, release — and read the ONE fused flush back off
    the ledger."""
    pre = plane.ledger.records()
    mark_seq = pre[-1]["seq"] if pre else -1
    gate = _GatePub()

    def rows(group, n, msg):
        out = []
        for i in range(n):
            priv = privs[group * N_PER_CHAIN + i % N_PER_CHAIN]
            out.append((priv.pub_key(), msg, priv.sign(msg)))
        return out

    plane.tenants.register(chains[0], row_quota=3)
    blocker = plane.submit_many([(gate, b"blk", b"sig")],
                                lane=LANE_BULK, block=False,
                                chain_id=chains[0])
    assert gate.busy.wait(5.0), "dispatcher never picked up the blocker"
    # dispatcher parked: everything below queues with no races
    f0 = plane.submit_many(rows(0, 2, b"bulk0"), lane=LANE_BULK,
                           block=False, chain_id=chains[0])
    f1 = plane.submit_many(rows(1, 2, b"bulk1"), lane=LANE_BULK,
                           block=False, chain_id=chains[1])
    shed = None
    try:
        plane.submit_many(rows(0, 2, b"over"), lane=LANE_BULK,
                          block=False, chain_id=chains[0])
    except TenantOverloaded as e:
        shed = {"tenant": e.tenant, "retry_after_ms": e.retry_after_ms,
                "msg": str(e), "is_overload": isinstance(
                    e, PlaneOverloaded)}
    # the victim's CONSENSUS row is outside every tenant gate
    fc = plane.submit_many(rows(1, 1, b"vote"), lane=LANE_CONSENSUS,
                           chain_id=chains[1], block=False)
    import time as _time

    _time.sleep(0.02)  # age the bulk rows past the bulk window
    gate.release.set()
    verdicts = {
        "blocker": blocker.result(5), "f0": f0.result(5),
        "f1": f1.result(5), "fc": fc.result(5),
    }
    recs = [{"rows": r["rows"], "c_rows": r["c_rows"],
             "b_rows": r["b_rows"], "tenants": r["tenants"],
             "split": r["split"]}
            for r in plane.ledger.records() if r["seq"] > mark_seq]
    return {"shed": shed, "verdicts": verdicts, "records": recs}


def _victim_commit_p99(sim, group):
    out = []
    for n in sim.net.group_nodes(group):
        if n.alive:
            s = n.node.consensus.height_ledger.summary()
            out.append(s["commit_latency_ms"]["p99"])
    return out


def _canon_registry(dump):
    """The registry dump's deterministic columns (wait quantiles ride
    the real clock and are excluded). The ISSUE-20 device-charge
    columns ARE deterministic here — host-path flushes carry zero
    comp/h2d/dev ms and zero delta bytes, and the split rule derives
    from the tenant mix alone — so a replay must reproduce them
    byte-identically too."""
    return {
        name: {k: t[k] for k in ("rows", "lane_rows", "lane_sheds",
                                 "warm_skips", "cold_evictions",
                                 "device_ms", "comp_ms", "h2d_ms",
                                 "delta_bytes")}
        for name, t in dump["tenants"].items()
    }


def _run_multichain(basedir, noisy: bool, seed: int = SEED):
    """One K-chains-one-plane run; `noisy` adds a REAL-thread flooder
    tenant hammering the shared BULK lane open-loop for the whole
    run."""
    plane = VerifyPlane(window_ms=0.5, use_device=False,
                        bulk_deadline_ms=250.0)
    plane.start()
    set_global_plane(plane)
    stop = threading.Event()
    flood_counts = {"ok": 0, "tenant_shed": 0, "queue_shed": 0}
    shed_sample = {}

    def hammer():
        while not stop.is_set():
            try:
                plane.submit_many([(_InstaPub(), b"m", b"s")] * 16,
                                  lane=LANE_BULK, block=False,
                                  chain_id="flooder")
                flood_counts["ok"] += 1
            except TenantOverloaded as e:
                flood_counts["tenant_shed"] += 1
                shed_sample.setdefault("err", {
                    "tenant": e.tenant,
                    "retry_after_ms": e.retry_after_ms,
                    "msg": str(e)})
            except PlaneOverloaded:
                flood_counts["queue_shed"] += 1
            stop.wait(0.001)

    thread = None
    try:
        with Simnet(N_PER_CHAIN, seed=seed, basedir=str(basedir),
                    n_chains=2) as sim:
            chains = list(sim.net.chain_ids)
            if noisy:
                plane.tenants.register("flooder", row_quota=24)
                thread = threading.Thread(target=hammer, daemon=True)
                thread.start()
            assert sim.run(MCHAOS, until_height=TARGET_H,
                           max_time=90.0), \
                "multichain run never reached target height"
            if thread is not None:
                stop.set()
                thread.join(timeout=5.0)
            hashes = sim.commit_hashes()
            flood_results = list(sim.flood_results)
            victim_p99 = _victim_commit_p99(sim, 1)
            heights = [n.height() for n in sim.net.nodes if n.alive]
            demo = (None if noisy else
                    _coalesce_demo(plane, list(sim.net.privs), chains))
    finally:
        stop.set()
        set_global_plane(None)
        plane.stop()
    led = [{"rows": r["rows"], "c_rows": r["c_rows"],
            "b_rows": r["b_rows"], "tenants": r["tenants"],
            "split": r["split"]}
           for r in plane.ledger.records()]
    return {
        "chains": chains, "hashes": hashes, "heights": heights,
        "flood_results": flood_results, "victim_p99": victim_p99,
        "demo": demo, "ledger": led,
        "summary": plane.ledger.summary(),
        "stats": plane.stats(), "registry": plane.tenants.dump(),
        "flood_counts": dict(flood_counts),
        "shed_sample": dict(shed_sample),
    }


def _run_solo_group1(basedir, seed: int = SEED):
    """Chain group 1, run ALONE: same keys (seed+1 derivation), same
    chain_id, no shared plane — the bit-identical control."""
    with Simnet(N_PER_CHAIN, seed=seed + 1, basedir=str(basedir),
                chain_id="simnet-1") as sim:
        assert sim.run([], until_height=TARGET_H, max_time=60.0)
        sim.assert_safety()
        return sim.commit_hashes()


@pytest.fixture(scope="module")
def tenant_runs(tmp_path_factory):
    """Lazy shared cache: "multi_a"/"multi_b" are the identical
    (seed, schedule) replay pair; "noisy" adds the real-thread
    flooder; "solo" is group 1 run alone."""
    runs = {}

    def get(kind):
        if kind not in runs:
            fp.reset()
            base = tmp_path_factory.mktemp(kind)
            if kind == "solo":
                runs[kind] = _run_solo_group1(base)
            else:
                runs[kind] = _run_multichain(base,
                                             noisy=(kind == "noisy"))
        return runs[kind]

    return get


def _group_safety(hashes):
    """Per-group agreement (the harness's assert_safety spans groups,
    which legitimately diverge): within a group, no two nodes commit
    different blocks at one height."""
    for g in range(2):
        agreed = {}
        for h in hashes[g * N_PER_CHAIN:(g + 1) * N_PER_CHAIN]:
            for height, bh in h.items():
                assert agreed.setdefault(height, bh) == bh, \
                    f"group {g} split at height {height}"


def test_multichain_one_plane_coalesces(tenant_runs):
    """K chains, ONE plane: both chain tenants flowed through it, the
    ledger's per-flush tenant attribution always sums to the flush
    total, and the parked-dispatcher demo produced ONE fused flush
    carrying BOTH chains' rows — with the over-quota flooder shed as
    an explicit retry-hinted TenantOverloaded and the victim's
    CONSENSUS row verified ungated."""
    run = tenant_runs("multi_a")
    _group_safety(run["hashes"])
    assert all(h >= TARGET_H for h in run["heights"])
    # the sim traffic itself was tenant-keyed: both chains' rows are
    # in the registry and in the ledger's per-tenant totals
    reg = run["registry"]["tenants"]
    for chain in run["chains"]:
        assert reg[chain]["rows"] > 0, reg.keys()
        assert run["summary"]["tenants"][chain] > 0
    # every flush's attribution sums exactly to its row count
    for r in run["ledger"]:
        assert sum(n for _, n in r["tenants"]) == r["rows"], r
    # the coalescing demo: one fused flush, two chains, sums exact
    demo = run["demo"]
    fused = [r for r in demo["records"] if len(r["tenants"]) >= 2]
    assert fused, demo["records"]
    split = dict(fused[0]["tenants"])
    assert split == {run["chains"][0]: 2, run["chains"][1]: 3}
    assert fused[0]["c_rows"] == 1 and fused[0]["b_rows"] == 4
    # a cross-tenant fused flush records the row-proportional rule;
    # single-tenant flushes record the exact sub-flush rule
    assert fused[0]["split"] == "rows"
    assert all(r["split"] == "exact" for r in demo["records"]
               if len(r["tenants"]) <= 1)
    assert run["summary"]["coalesced_flushes"] >= 1
    # real keys, real signatures: everything verified True
    assert demo["verdicts"]["f0"] == (True, True)
    assert demo["verdicts"]["f1"] == (True, True)
    assert demo["verdicts"]["fc"] == (True,)
    # the quota shed was explicit, attributed, and retry-hinted
    shed = demo["shed"]
    assert shed is not None, "over-quota submission was not shed"
    assert shed["tenant"] == run["chains"][0]
    assert shed["retry_after_ms"] > 0
    assert shed["is_overload"]  # mempool/lightgate arms catch it as-is
    assert "quota" in shed["msg"]
    assert reg[run["chains"][0]]["lane_sheds"][LANE_BULK] >= 1


def test_multichain_flood_is_answered_and_consensus_unshed(tenant_runs):
    """The chaos half held QoS: flooded txs got explicit verdicts,
    overloads (if any) carried retry hints, and CONSENSUS was never
    shed for ANY tenant."""
    run = tenant_runs("multi_a")
    results = run["flood_results"]
    answered = [r for r in results if r["code"] is not None]
    assert answered, "no flood tx ever reached a live mempool"
    assert any(r["code"] == abci.CODE_TYPE_OK for r in answered)
    for r in answered:
        if r["code"] == abci.CODE_TYPE_OVERLOADED:
            assert "retry_after_ms=" in r["log"], r
    assert run["stats"]["sheds"]["consensus"] == 0
    for t in run["registry"]["tenants"].values():
        assert t["lane_sheds"][LANE_CONSENSUS] == 0, t


def test_multichain_deterministic_replay(tenant_runs):
    """Same (seed, schedule) twice: identical commit hashes on every
    node of every chain, identical flood verdict stream, identical
    tenant-attributed ledger columns, and identical registry totals —
    the multi-tenant surfaces are part of the deterministic record."""
    a, b = tenant_runs("multi_a"), tenant_runs("multi_b")
    assert a["hashes"] == b["hashes"]
    assert [(r["seq"], r["code"], r["log"]) for r in a["flood_results"]] \
        == [(r["seq"], r["code"], r["log"]) for r in b["flood_results"]]
    cols = lambda led: [(r["rows"], r["c_rows"], r["b_rows"],  # noqa: E731
                         r["tenants"], r["split"]) for r in led]
    assert cols(a["ledger"]) == cols(b["ledger"])
    assert a["summary"]["tenants"] == b["summary"]["tenants"]
    assert _canon_registry(a["registry"]) == \
        _canon_registry(b["registry"])
    assert a["demo"] == b["demo"]


def test_shared_plane_group_matches_solo_run(tenant_runs):
    """Sharing the plane changes the economics, never the chain: group
    1 of the 2-chain run commits bit-identical blocks to the SAME
    chain (same keys, same chain_id) run alone with no shared plane."""
    multi = tenant_runs("multi_a")
    solo = tenant_runs("solo")
    for j in range(N_PER_CHAIN):
        shared_node = multi["hashes"][N_PER_CHAIN + j]
        solo_node = solo[j]
        common = sorted(set(shared_node) & set(solo_node))
        assert len(common) >= TARGET_H, (len(shared_node),
                                         len(solo_node))
        for h in common:
            assert shared_node[h] == solo_node[h], \
                f"node {j} diverged from solo at height {h}"


def test_noisy_neighbor_is_contained(tenant_runs):
    """A real-thread flooder tenant hammering the shared BULK lane
    open-loop for the whole run is quota-shed explicitly — and the
    victim chains never notice: all chains commit to target, consensus
    sheds stay ZERO for everyone, the victims shed nothing at all, and
    the victim chain's commit p99 holds against the flood-free run."""
    run = tenant_runs("noisy")
    base = tenant_runs("multi_a")
    _group_safety(run["hashes"])
    assert all(h >= TARGET_H for h in run["heights"])
    # the flooder really flooded, and was really quota-shed
    counts = run["flood_counts"]
    assert counts["ok"] > 0, counts
    assert counts["tenant_shed"] > 0, counts
    err = run["shed_sample"]["err"]
    assert err["tenant"] == "flooder"
    assert err["retry_after_ms"] > 0
    assert "quota" in err["msg"]
    reg = run["registry"]["tenants"]
    assert reg["flooder"]["lane_sheds"][LANE_BULK] == \
        counts["tenant_shed"]
    # containment: zero consensus sheds anywhere, zero sheds of ANY
    # kind for the victim chains
    assert run["stats"]["sheds"]["consensus"] == 0
    for chain in run["chains"]:
        assert all(v == 0 for v in reg[chain]["lane_sheds"].values()), \
            (chain, reg[chain]["lane_sheds"])
    # victim commit p99 holds vs the flooder-free run (generous floor:
    # the bound exists to catch cross-tenant starvation, not 1-core
    # scheduler jitter)
    assert run["victim_p99"] and base["victim_p99"]
    limit = max(2.0 * max(base["victim_p99"]), 100.0)
    assert max(run["victim_p99"]) <= limit, \
        (run["victim_p99"], base["victim_p99"])
    # the victim tenant's verify waits stayed sane under the flood
    wait = run["registry"]["tenants"][run["chains"][1]]["wait_ms"]
    assert wait["n"] > 0
    base_wait = base["registry"]["tenants"][base["chains"][1]]["wait_ms"]
    assert wait["p99_ms"] <= max(2.0 * base_wait["p99_ms"], 100.0), \
        (wait, base_wait)
