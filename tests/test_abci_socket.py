"""ABCI socket server/client: out-of-process application boundary.

Reference: abci/server/socket_server.go + abci/client/socket_client.go
+ abci/tests (driving kvstore over a socket).
"""
import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.server import ABCISocketClient, ABCISocketServer
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


@pytest.fixture()
def socket_app():
    server = ABCISocketServer(KVStoreApplication())
    server.start()
    client = ABCISocketClient(*server.addr)
    try:
        yield client
    finally:
        client.close()
        server.stop()


def test_roundtrip_methods(socket_app):
    app = socket_app
    info = app.info(abci.RequestInfo())
    assert info.last_block_height == 0
    assert app.check_tx(abci.RequestCheckTx(tx=b"a=1")).code == 0
    resp = app.finalize_block(abci.RequestFinalizeBlock(
        txs=[b"a=1", b"b=2"], height=1, hash=b"", proposer_address=b"",
        time_seconds=0,
    ))
    assert len(resp.tx_results) == 2 and resp.app_hash
    app.commit()
    q = app.query(abci.RequestQuery(data=b"a"))
    assert q.value == b"1"
    info2 = app.info(abci.RequestInfo())
    assert info2.last_block_height == 1


def test_node_runs_over_socket_app(tmp_path):
    """A validator whose ABCI app lives behind the socket boundary
    commits blocks and serves queries — the process-boundary analog of
    proxy_app != kvstore (node/node.go:302)."""
    server = ABCISocketServer(KVStoreApplication())
    server.start()
    client = ABCISocketClient(*server.addr)
    priv = PrivKey.generate(b"\x05" * 32)
    state = State.make_genesis(
        "sock-chain", ValidatorSet([Validator(priv.pub_key(), 10)])
    )
    node = Node(client, state, privval=FilePV(priv),
                home=str(tmp_path / "n0"), timeouts=FAST)
    node.start()
    try:
        assert node.consensus.wait_for_height(3, timeout=60)
        node.broadcast_tx(b"sock=yes")
        assert node.consensus.wait_for_height(node.height() + 2,
                                              timeout=60)
        assert node.query(b"sock").value == b"yes"
    finally:
        node.stop()
        client.close()
        server.stop()
