"""P2P: secret connection (auth + tamper), MConnection multiplexing,
switch peer lifecycle, and 4 validators reaching consensus over real TCP.

Mirrors p2p/conn/secret_connection_test.go, connection_test.go, and
switch_test.go case structure.
"""
import socket
import threading
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from cometbft_tpu.p2p.conn.secret_connection import (
    HandshakeError,
    SecretConnection,
)
from cometbft_tpu.p2p.key import NetAddress, NodeKey
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(propose=0.5, propose_delta=0.2, prevote=0.3,
                     prevote_delta=0.1, precommit=0.3, precommit_delta=0.1,
                     commit=0.02)


def socket_pair():
    a, b = socket.socketpair()
    return a, b


def handshake_pair():
    pa, pb = PrivKey.generate(b"\x01" * 32), PrivKey.generate(b"\x02" * 32)
    sa, sb = socket_pair()
    out = {}

    def side(name, sock, priv):
        out[name] = SecretConnection.handshake(sock, priv)

    ta = threading.Thread(target=side, args=("a", sa, pa))
    tb = threading.Thread(target=side, args=("b", sb, pb))
    ta.start(); tb.start(); ta.join(5); tb.join(5)
    return out["a"], out["b"], pa, pb


def test_secret_connection_roundtrip():
    ca, cb, pa, pb = handshake_pair()
    # mutual identity authentication
    assert ca.remote_pub.data == pb.pub_key().data
    assert cb.remote_pub.data == pa.pub_key().data
    ca.write_msg(b"hello")
    assert cb.read_msg() == b"hello"
    big = bytes(range(256)) * 40  # > 1 frame, exact-multiple edge nearby
    cb.write_msg(big)
    assert ca.read_msg() == big
    # exact multiple of the frame size
    exact = b"x" * 2048
    ca.write_msg(exact)
    assert cb.read_msg() == exact


def test_secret_connection_tamper_rejected():
    ca, cb, _, _ = handshake_pair()
    raw = ca._stream
    # bypass the cipher and inject garbage: reader must error, not yield
    raw.sendall(b"\x00" * (1028 + 16))
    with pytest.raises(Exception):
        cb.read_msg()


def test_mconnection_multiplex_and_priority():
    ca, cb, _, _ = handshake_pair()
    got = []
    done = threading.Event()

    def on_recv(chan, msg):
        got.append((chan, msg))
        if len(got) == 3:
            done.set()

    descs = [ChannelDescriptor(1, priority=1),
             ChannelDescriptor(2, priority=10)]
    ma = MConnection(ca, descs, on_receive=lambda c, m: None)
    mb = MConnection(cb, descs, on_receive=on_recv)
    ma.start(); mb.start()
    try:
        assert ma.send(1, b"low")
        assert ma.send(2, b"high-1")
        assert ma.send(2, b"h" * 5000)  # multi-packet message
        assert done.wait(5)
        assert sorted(m for _, m in got) == sorted(
            [b"low", b"high-1", b"h" * 5000]
        )
        chans = {c for c, _ in got}
        assert chans == {1, 2}
    finally:
        ma.stop(); mb.stop()


def test_switch_connect_and_stop_peer():
    ka, kb = NodeKey(PrivKey.generate(b"\x0a" * 32)), \
        NodeKey(PrivKey.generate(b"\x0b" * 32))
    sa, sb = Switch(ka, "net-1"), Switch(kb, "net-1")
    from cometbft_tpu.p2p.switch import Reactor

    class Echo(Reactor):
        def __init__(self):
            super().__init__("ECHO")
            self.got = []

        def channel_descriptors(self):
            return [ChannelDescriptor(0x7F)]

        def receive(self, chan_id, peer, msg):
            self.got.append(msg)

    ea, eb = Echo(), Echo()
    sa.add_reactor(ea); sb.add_reactor(eb)
    addr_a = sa.listen()
    sa.start(); sb.start()
    try:
        sb.dial_peer(addr_a, persistent=False)
        deadline = time.time() + 5
        while (sa.num_peers() < 1 or sb.num_peers() < 1):
            assert time.time() < deadline, "peers never connected"
            time.sleep(0.02)
        sb.broadcast(0x7F, b"ping-from-b")
        deadline = time.time() + 5
        while not ea.got:
            assert time.time() < deadline, "message never arrived"
            time.sleep(0.02)
        assert ea.got == [b"ping-from-b"]
        # identity mismatch: dialing a wrong ID must fail to add a peer
        bad = NetAddress("ff" * 20, addr_a.host, addr_a.port)
        sb.dial_peer(bad, persistent=False)
        time.sleep(0.3)
        assert sb.num_peers() == 1
    finally:
        sa.stop(); sb.stop()


def test_four_validators_over_tcp(tmp_path):
    """BASELINE config #1 topology over the real transport: 4 nodes, TCP
    localhost mesh, all reach height 4 and agree."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("tcp-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x40 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        # full mesh
        for i, n in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    n.dial(a)
        nodes[0].broadcast_tx(b"tcp=yes")
        for n in nodes:
            assert n.consensus.wait_for_height(4, timeout=90), \
                f"stuck at {n.height()}"
        assert all(n.query(b"tcp").value == b"yes" for n in nodes)
        h2 = {n.block_store.load_block(2).hash() for n in nodes}
        assert len(h2) == 1
    finally:
        for n in nodes:
            n.stop()


def test_fifth_node_joins_and_catches_up(tmp_path):
    """A 5th (non-validator) node joins a running 4-node TCP net from
    genesis: blocksync fetches the back-blocks over the BLOCKSYNC
    channel, then consensus keeps it at the tip (round-2 verdict item 4;
    blocksync/reactor.go:286 + :391 SwitchToConsensus)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("join-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x50 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    late = None
    try:
        for i, n in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    n.dial(a)
        # let the validators build history first
        assert nodes[0].consensus.wait_for_height(4, timeout=90)

        late = Node(KVStoreApplication(), state.copy(),
                    home=str(tmp_path / "late"), timeouts=FAST, p2p=True,
                    blocksync=True,
                    node_key=NodeKey(PrivKey.generate(b"\x77" * 32)))
        late.listen()
        late.start()
        for a in addrs:
            late.dial(a)
        target = nodes[0].height() + 2
        deadline = time.time() + 120
        while time.time() < deadline and late.height() < target:
            time.sleep(0.2)
        assert late.height() >= target, \
            f"late node stuck at {late.height()} (target {target})"
        # it agrees on history with the validators
        h2 = late.block_store.load_block(2).hash()
        assert h2 == nodes[0].block_store.load_block(2).hash()
        # and its consensus engine is live at the tip
        assert late.consensus.is_running()
    finally:
        for n in nodes:
            n.stop()
        if late is not None:
            late.stop()


def test_partitioned_node_rejoins(tmp_path):
    """A validator cut off from the net resumes after reconnection: the
    consensus reactor's catch-up push (NewRoundStep-driven commit_block)
    carries it back to the tip (round-2 verdict item 4)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("part-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x60 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        for i, n in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    n.dial(a)
        assert nodes[0].consensus.wait_for_height(2, timeout=90)
        # partition node 3: drop all its peers (and everyone drops it).
        # persistent entries are cleared FIRST — the redial loop polls
        # every 0.1s and would otherwise re-establish the link inside
        # the drop window
        victim = nodes[3]
        victim.switch.persistent.clear()
        for n in nodes[:3]:
            n.switch.persistent.clear()
        for p in list(victim.switch.peers.values()):
            victim.switch.stop_peer_for_error(p, "partition test")
        for n in nodes[:3]:
            for p in list(n.switch.peers.values()):
                if p.peer_id == victim.switch.node_key.node_id:
                    n.switch.stop_peer_for_error(p, "partition test")
        h_cut = victim.height()
        # the 3 remaining validators (power 30/40 > 2/3) keep committing
        assert nodes[0].consensus.wait_for_height(h_cut + 3, timeout=90)
        assert victim.height() <= h_cut + 1  # victim is behind
        # reconnect: catch-up pushes bring the victim to the tip
        for a in addrs[:3]:
            victim.dial(a)
        target = nodes[0].height() + 1
        deadline = time.time() + 120
        while time.time() < deadline and victim.height() < target:
            time.sleep(0.2)
        assert victim.height() >= target, \
            f"victim stuck at {victim.height()} (target {target})"
    finally:
        for n in nodes:
            n.stop()


def test_redial_backoff_grows_with_jitter():
    """ISSUE 3 satellite: the persistent-peer redial loop must back off
    exponentially with jitter — after a partition heals, a fleet
    redialing in lockstep every 0.5s thundering-herds the accept queue
    (the simnet's heal schedules exposed this)."""
    import random

    rng = random.Random(7)
    # growth: each failure at least doubles (capped), jitter adds 0-50%
    d1, b1 = Switch._next_backoff(0.0, rng)
    assert Switch.REDIAL_BASE <= d1 <= Switch.REDIAL_BASE * 1.5
    assert b1 == Switch.REDIAL_BASE
    d2, b2 = Switch._next_backoff(b1, rng)
    assert Switch.REDIAL_BASE * 2 <= d2 <= Switch.REDIAL_BASE * 3
    assert b2 == Switch.REDIAL_BASE * 2
    d3, b3 = Switch._next_backoff(Switch.REDIAL_MAX * 2, rng)
    assert Switch.REDIAL_MAX <= d3 <= Switch.REDIAL_MAX * 1.5  # capped
    assert b3 == Switch.REDIAL_MAX
    # jitter decorrelates two dialers with identical failure history
    draws = {round(Switch._next_backoff(1.0, random.Random(s))[0], 6)
             for s in range(8)}
    assert len(draws) > 1, "no jitter: herd redials stay in lockstep"


def test_redial_backoff_paces_attempts_then_recovers():
    """With dials failing, redial attempts are PACED (bounded count in a
    window) instead of hammering every loop tick; once the fault clears
    the backed-off redial still reconnects."""
    from cometbft_tpu.libs import failpoints as fp

    from cometbft_tpu.p2p.switch import Reactor

    class Chan(Reactor):
        def __init__(self):
            super().__init__("CHAN")

        def channel_descriptors(self):
            return [ChannelDescriptor(0x71)]

    fp.reset()
    ka, kb = NodeKey(PrivKey.generate(b"\x2a" * 32)), \
        NodeKey(PrivKey.generate(b"\x2b" * 32))
    sa, sb = Switch(ka, "net-bk"), Switch(kb, "net-bk")
    sa.add_reactor(Chan())
    sb.add_reactor(Chan())
    addr_a = sa.listen()
    sa.start()
    try:
        fp.arm("p2p.dial", "raise")
        sb.persistent[addr_a.node_id] = addr_a  # redial loop owns it
        sb.start()
        time.sleep(2.0)
        fails = fp.registry().stats("p2p.dial")["fires"]
        # exponential backoff: ~0 + 0.25j + 0.5j + 1.0j... -> <= 5
        # attempts in 2s (the old fixed 0.5s loop made 4+ and NEVER
        # stretched further)
        assert 1 <= fails <= 5, f"unpaced redials: {fails} in 2s"
        fp.disarm("p2p.dial")
        deadline = time.time() + 10
        while sa.num_peers() < 1 or sb.num_peers() < 1:
            assert time.time() < deadline, \
                "backed-off redial never reconnected"
            time.sleep(0.02)
    finally:
        fp.reset()
        sa.stop(); sb.stop()


def test_dial_and_handshake_failpoints_recover():
    """p2p.dial / p2p.handshake failpoints: injected dial failures and
    mid-handshake drops must not wedge the switch — once the fault
    clears (count exhausted), the same dial succeeds."""
    from cometbft_tpu.libs import failpoints as fp

    from cometbft_tpu.p2p.switch import Reactor

    class Chan(Reactor):
        def __init__(self):
            super().__init__("CHAN")

        def channel_descriptors(self):
            return [ChannelDescriptor(0x70)]

    fp.reset()
    ka, kb = NodeKey(PrivKey.generate(b"\x1a" * 32)), \
        NodeKey(PrivKey.generate(b"\x1b" * 32))
    sa, sb = Switch(ka, "net-fp"), Switch(kb, "net-fp")
    sa.add_reactor(Chan())
    sb.add_reactor(Chan())
    addr_a = sa.listen()
    sa.start(); sb.start()
    try:
        # dial failpoint: dials die before the socket op
        fp.arm("p2p.dial", "raise")
        sb.dial_peer(addr_a, persistent=False)
        sb.dial_peer(addr_a, persistent=False)
        assert sb.num_peers() == 0
        fp.disarm("p2p.dial")

        # handshake failpoint: secret conn established then dropped on
        # BOTH sides (the registry is process-global) — everybody must
        # clean up, nobody crashes
        fp.arm("p2p.handshake", "raise")
        sb.dial_peer(addr_a, persistent=False)
        time.sleep(0.5)
        assert sb.num_peers() == 0 and sa.num_peers() == 0
        fp.disarm("p2p.handshake")

        # fault cleared: the very same dial connects
        sb.dial_peer(addr_a, persistent=False)
        deadline = time.time() + 10
        while sa.num_peers() < 1 or sb.num_peers() < 1:
            assert time.time() < deadline, \
                "recovery dial never connected"
            time.sleep(0.02)
    finally:
        fp.reset()
        sa.stop(); sb.stop()
