"""Mempool post-block recheck + BFT median time + a thread-stress pass.

Reference: mempool/clist_mempool.go:631,646 (recheckTxs),
state/validation.go:123 (median-time rule), and the `-race`/go-deadlock
strategy of SURVEY §4 approximated by a concurrent hammer test.
"""
import threading
import time

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.node.node import LocalNetwork, Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types import canonical
from cometbft_tpu.types.bft_time import median_time
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


class OneShotApp(KVStoreApplication):
    """CheckTx accepts a key only while it is unset — committed state
    invalidates pending duplicates (the recheck scenario)."""

    def check_tx(self, req):
        key = req.tx.split(b"=", 1)[0]
        if self.get(key) is not None:
            return abci.ResponseCheckTx(code=7, log="key already set")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

    def get(self, key):
        resp = self.query(abci.RequestQuery(data=key))
        return resp.value if resp.value else None


def test_mempool_recheck_drops_stale():
    app = OneShotApp()
    mp = Mempool(app)
    assert mp.check_tx(b"k=1").code == 0
    # a second tx for the same key is still valid pre-commit
    assert mp.check_tx(b"k=2").code == 0
    assert mp.size() == 2
    # block commits k=1: the app's state now has k
    app.finalize_block(abci.RequestFinalizeBlock(
        txs=[b"k=1"], height=1, hash=b"", proposer_address=b"",
        time_seconds=0,
    ))
    app.commit()
    mp.update(1, [b"k=1"])
    # recheck dropped k=2 (stale: key now set); without recheck it would
    # sit in the pool and be re-proposed forever
    assert mp.size() == 0
    # and it can be resubmitted after (cache was cleared)...rejected by app
    assert mp.check_tx(b"k=2").code == 7


def _sig(idx, ts_s, flag=BLOCK_ID_FLAG_COMMIT):
    return CommitSig(flag, bytes([idx]) * 20, Timestamp(ts_s, 0),
                     b"\x00" * 64)


def test_median_time_weighted():
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
    vals = ValidatorSet([
        Validator(privs[0].pub_key(), 10),
        Validator(privs[1].pub_key(), 10),
        Validator(privs[2].pub_key(), 80),  # heavyweight
    ])
    bid = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x01" * 32))
    # ValidatorSet sorts by address: find the heavyweight's slot and give
    # it the latest timestamp; the others get earlier ones
    heavy_idx = next(i for i, v in enumerate(vals.validators)
                     if v.voting_power == 80)
    sigs = []
    light_times = iter([100, 200])
    for i, v in enumerate(vals.validators):
        t = 300 if i == heavy_idx else next(light_times)
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address,
                              Timestamp(t, 0), b"\x00" * 64))
    commit = Commit(5, 0, bid, sigs)
    # the 80-power validator's timestamp IS the weighted median
    assert median_time(commit, vals) == Timestamp(300, 0)
    # absent sigs are excluded
    sigs2 = list(sigs)
    sigs2[heavy_idx] = CommitSig.absent()
    commit2 = Commit(5, 0, bid, sigs2)
    assert median_time(commit2, vals).seconds in (100, 200)


def test_concurrent_hammer(tmp_path):
    """Race pass: 3 injector threads flood a live 4-node net with
    duplicate/invalid votes while it commits blocks; no deadlock, no
    stall, no crash (the -race + go-deadlock CI analog, SURVEY §4)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("hammer-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), home=str(tmp_path / f"n{i}"),
                    broadcast=net.broadcaster(i), timeouts=FAST)
        net.add(node)
        nodes.append(node)
    for n in nodes:
        n.start()
    stop = threading.Event()

    def hammer(seed):
        bid = BlockID(bytes([seed]) * 32, PartSetHeader(1, b"\x0a" * 32))
        k = 0
        while not stop.is_set():
            k += 1
            h = nodes[0].consensus.height
            v = Vote(
                vote_type=canonical.PREVOTE_TYPE, height=h,
                round=0, block_id=bid,
                timestamp=Timestamp(1_700_000_000 + k, 0),
                validator_address=bytes([seed]) * 20,
                validator_index=k % 7,
            )
            v.signature = b"\x11" * 64  # garbage signature
            for n in nodes:
                n.consensus.receive_vote(v)
            time.sleep(0.002)

    threads = [threading.Thread(target=hammer, args=(40 + i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        start_h = nodes[0].height()
        assert nodes[0].consensus.wait_for_height(start_h + 4, timeout=90), \
            "net stalled under hammer"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        for n in nodes:
            n.stop()
