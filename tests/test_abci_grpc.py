"""ABCI gRPC server/client: the out-of-process HTTP/2 app boundary.

Reference: abci/server/grpc_server.go + abci/client/grpc_client.go
(+ test/e2e's grpc ABCI nodes). Same 14-method surface as socket mode;
plus the gRPC-specific property the reference documents — concurrent
calls multiplex on one channel instead of serializing on a conn mutex.
"""
import subprocess
import sys
import threading
import time

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.grpc import ABCIGRPCClient, ABCIGRPCServer
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.proxy import AppConns
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


@pytest.fixture()
def grpc_app():
    server = ABCIGRPCServer(KVStoreApplication())
    server.start()
    client = ABCIGRPCClient(*server.addr)
    client.wait_ready()
    try:
        yield client
    finally:
        client.close()
        server.stop()


def test_roundtrip_methods(grpc_app):
    app = grpc_app
    info = app.info(abci.RequestInfo())
    assert info.last_block_height == 0
    assert app.check_tx(abci.RequestCheckTx(tx=b"a=1")).code == 0
    resp = app.finalize_block(abci.RequestFinalizeBlock(
        txs=[b"a=1", b"b=2"], height=1, hash=b"", proposer_address=b"",
        time_seconds=0,
    ))
    assert len(resp.tx_results) == 2 and resp.app_hash
    app.commit()
    q = app.query(abci.RequestQuery(data=b"a"))
    assert q.value == b"1"
    info2 = app.info(abci.RequestInfo())
    assert info2.last_block_height == 1


def test_snapshot_family_roundtrip(grpc_app):
    """The positional-arg snapshot methods cross the gRPC boundary too
    (ListSnapshots/Offer/Load/Apply, grpc surface parity)."""
    app = grpc_app
    assert app.list_snapshots() == []
    snap = abci.Snapshot(height=1, format=1, chunks=1, hash=b"h",
                         metadata=b"")
    assert app.offer_snapshot(snap) is True
    assert app.offer_snapshot(
        abci.Snapshot(height=1, format=9, chunks=1, hash=b"h",
                      metadata=b"")) is False


def test_app_error_surfaces_as_exception(grpc_app):
    """An app-side exception maps to a grpc INTERNAL status, raised
    client-side (grpc_client.go error propagation)."""
    with pytest.raises(Exception) as ei:
        # malformed: load_snapshot_chunk with wrong arg count
        grpc_app._stubs["load_snapshot_chunk"](b"not json")
    assert "abci app error" in str(ei.value) or "INTERNAL" in str(
        ei.value)


def test_concurrent_calls_multiplex(grpc_app):
    """20 parallel check_tx/query calls on one channel all complete —
    no ordering mutex (the reference grpc client's advantage over the
    socket client, grpc_client.go:20-28)."""
    app = grpc_app
    errs = []

    def worker(i):
        try:
            for _ in range(5):
                assert app.check_tx(
                    abci.RequestCheckTx(tx=b"k=%d" % i)).code == 0
                app.info(abci.RequestInfo())
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(20)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs


def test_node_runs_over_grpc_app_subprocess(tmp_path):
    """kvstore runs OUT-OF-PROCESS over gRPC through the node's full
    consensus path: subprocess server via the abci CLI, node built via
    AppConns.from_addr('grpc://...'), blocks commit, txs apply, queries
    answer (the e2e shape of abci/client/grpc_client.go usage)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "abci", "kvstore",
         "--port", "0", "--transport", "grpc", "--run-for", "120"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    try:
        line = proc.stdout.readline()
        assert "serving on" in line, line
        addr = line.split()[4]
        conns = AppConns.from_addr("grpc://" + addr)
        conns.query.wait_ready()
        priv = PrivKey.generate(b"\x06" * 32)
        state = State.make_genesis(
            "grpc-chain", ValidatorSet([Validator(priv.pub_key(), 10)])
        )
        node = Node(conns, state, privval=FilePV(priv),
                    home=str(tmp_path / "n0"), timeouts=FAST)
        node.start()
        try:
            assert node.consensus.wait_for_height(3, timeout=60)
            node.broadcast_tx(b"grpc=yes")
            assert node.consensus.wait_for_height(node.height() + 2,
                                                  timeout=60)
            assert node.query(b"grpc").value == b"yes"
        finally:
            node.stop()
            conns.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
