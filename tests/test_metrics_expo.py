"""/metrics exposition coverage (ISSUE 5 satellites).

A promtext-parser round-trip over a fully-populated NodeMetrics
(HELP/TYPE pairing, label escaping, histogram bucket monotonicity),
the idle-histogram zero-row fix, the scrape-time sampling of the
previously-invisible internals (failpoint trigger counts, WAL fsync
latency, staging pool, breaker transitions), and the metric naming
lint wired as a fast tier-1 gate.
"""
import re

import pytest

from cometbft_tpu.libs.metrics import Histogram, NodeMetrics, Registry

# ---------------------------------------------------------------------------
# a small prometheus text-format 0.0.4 parser (the round-trip oracle)
# ---------------------------------------------------------------------------

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? (?P<value>[^ ]+)$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_promtext(text: str):
    """Parse an exposition into {family: {type, help, samples}} and
    VALIDATE structure: every sample belongs to a family whose HELP and
    TYPE were declared first, label blocks parse completely, values are
    floats."""
    families = {}
    current = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            families[name] = {"help": help_, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, typ = rest.partition(" ")
            assert name in families, f"TYPE before HELP: {line!r}"
            assert name == current, f"TYPE not paired with HELP: {line!r}"
            families[name]["type"] = typ
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line {line!r}"
        sname = m.group("name")
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            if sname.endswith(suffix) and sname[: -len(suffix)] in families:
                base = sname[: -len(suffix)]
        assert base in families, f"sample {sname} has no HELP/TYPE"
        assert families[base]["type"] is not None, f"{base} missing TYPE"
        labels = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL.finditer(raw):
                labels[lm.group(1)] = _unescape(lm.group(2))
                consumed = lm.end()
            rest = raw[consumed:].strip(", ")
            assert not rest, f"unparsed label residue {rest!r} in {line!r}"
        value = float(m.group("value")) if m.group("value") != "+Inf" \
            else float("inf")
        families[base]["samples"].append((sname, labels, value))
    return families


def _check_histogram(fam_name: str, fam: dict) -> None:
    """Bucket monotonicity + _sum/_count presence per label set."""
    by_key = {}
    for sname, labels, value in fam["samples"]:
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k != "le"))
        slot = by_key.setdefault(key, {"buckets": [], "sum": None,
                                       "count": None})
        if sname.endswith("_bucket"):
            slot["buckets"].append((float(labels["le"]), value))
        elif sname.endswith("_sum"):
            slot["sum"] = value
        elif sname.endswith("_count"):
            slot["count"] = value
    assert by_key, f"{fam_name}: histogram family exposed no samples"
    for key, slot in by_key.items():
        assert slot["sum"] is not None, f"{fam_name}{key}: no _sum"
        assert slot["count"] is not None, f"{fam_name}{key}: no _count"
        buckets = sorted(slot["buckets"])
        assert buckets, f"{fam_name}{key}: no buckets"
        cum = [v for _, v in buckets]
        assert cum == sorted(cum), \
            f"{fam_name}{key}: buckets not monotonic: {buckets}"
        assert buckets[-1][0] == float("inf"), \
            f"{fam_name}{key}: missing +Inf bucket"
        assert buckets[-1][1] == slot["count"], \
            f"{fam_name}{key}: +Inf bucket != _count"


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------


def _populated_node_metrics() -> NodeMetrics:
    m = NodeMetrics()
    m.height.set(7)
    m.rounds.set(1)
    m.validators.set(4)
    m.block_interval.observe(0.8)
    m.num_txs.set(3)
    m.total_txs.inc(3)
    m.block_size.set(512)
    m.step_duration.observe(0.01, step="propose")
    m.step_duration.observe(0.002, step="prevote")
    m.verify_batches.inc()
    m.verify_sigs.inc(128)
    m.verify_seconds.observe(0.02)
    m.plane_queue_depth.set(2)
    m.plane_batch_size.observe(64)
    m.plane_wait_seconds.observe(0.003)
    m.plane_padding_waste.inc(4)
    m.plane_pack_seconds.observe(0.0004)
    # split by path since the device-stamping PR: "device" = per-row
    # delta buffers, "host" = full packed rows
    m.plane_h2d_bytes.inc(4096, path="host")
    m.plane_h2d_bytes.inc(80, path="device")
    m.mempool_size.set(9)
    m.peers.set(3)
    m.blocksync_syncing.set(0)
    return m


def test_full_nodemetrics_promtext_roundtrip():
    text = _populated_node_metrics().expose_text()
    fams = parse_promtext(text)
    # every registered family made it out with HELP+TYPE
    for name in ("cometbft_consensus_height",
                 "cometbft_consensus_txs_total",
                 "cometbft_consensus_step_duration_seconds",
                 "cometbft_verifyplane_batch_rows",
                 "cometbft_verifyplane_shard_flushes_total",
                 "cometbft_verifyplane_shard_rows_total",
                 "cometbft_verifyplane_shard_devices",
                 "cometbft_crypto_valset_table_cache_total",
                 "cometbft_parallel_mesh_step_cache_total",
                 "cometbft_crypto_staging_pool_total",
                 "cometbft_crypto_breaker_transitions_total",
                 "cometbft_failpoints_fires_total",
                 "cometbft_wal_fsync_total",
                 "cometbft_wal_fsync_seconds_total"):
        assert name in fams, f"{name} missing from exposition"
    for name, fam in fams.items():
        assert fam["type"] in ("counter", "gauge", "histogram"), name
        assert fam["samples"], f"{name}: no sample rows at all"
        if fam["type"] == "histogram":
            _check_histogram(name, fam)
    # labeled histogram kept its label through the round trip
    steps = {s[1].get("step") for s in
             fams["cometbft_consensus_step_duration_seconds"]["samples"]}
    assert {"propose", "prevote"} <= steps
    # the h2d counter's path split (device stamping PR) survives the
    # round trip with both series intact
    h2d = {s[1].get("path"): s[2] for s in
           fams["cometbft_verifyplane_h2d_bytes_total"]["samples"]}
    assert h2d == {"host": 4096, "device": 80}


def test_idle_histograms_expose_zero_rows():
    """Satellite fix: a registered-but-never-observed histogram must
    still scrape with zero buckets/_sum/_count (previously the family
    vanished entirely — an idle plane had NO latency metrics)."""
    text = NodeMetrics().expose_text()
    fams = parse_promtext(text)
    fam = fams["cometbft_verifyplane_submit_to_result_seconds"]
    assert fam["type"] == "histogram"
    _check_histogram("cometbft_verifyplane_submit_to_result_seconds", fam)
    names = dict((s[0], s[2]) for s in fam["samples"])
    assert names["cometbft_verifyplane_submit_to_result_seconds_sum"] == 0
    assert names["cometbft_verifyplane_submit_to_result_seconds_count"] == 0


def test_label_escaping_roundtrip():
    r = Registry()
    c = r.counter("test", "weird_total", "label escaping")
    hostile = 'a"b\\c\nd'
    c.inc(3, reason=hostile)
    fams = parse_promtext(r.expose_text())
    samples = fams["cometbft_test_weird_total"]["samples"]
    labeled = [s for s in samples if s[1]]
    assert labeled and labeled[0][1]["reason"] == hostile
    assert labeled[0][2] == 3.0


def test_histogram_zero_rows_direct():
    h = Histogram("x_seconds", "h", buckets=(0.1, 1))
    lines = h.expose()
    assert "x_seconds_count 0" in lines
    assert "x_seconds_sum 0" in lines
    assert any("_bucket" in ln and ln.endswith(" 0") for ln in lines)


def test_scrape_samples_failpoints_and_wal(tmp_path):
    """The previously-unreachable internals land on /metrics: per-point
    failpoint trigger counts and WAL fsync latency, sampled at scrape
    time."""
    from cometbft_tpu.consensus import wal as walmod
    from cometbft_tpu.libs import failpoints as fp

    fp.reset()
    fp.register("expo.test.point", "test seam")
    fp.arm("expo.test.point", "raise", count=1)
    with pytest.raises(fp.FailpointError):
        fp.fail_point("expo.test.point")

    w = walmod.WAL(str(tmp_path / "t.wal"))
    before = walmod.fsync_stats()["count"]
    w.write_sync(walmod.MSG_INFO, b"hello")
    w.close()

    try:
        text = NodeMetrics().expose_text()
        fams = parse_promtext(text)
        fires = {s[1].get("point"): s[2]
                 for s in fams["cometbft_failpoints_fires_total"]["samples"]
                 if s[1]}
        assert fires.get("expo.test.point") == 1.0
        wal_count = fams["cometbft_wal_fsync_total"]["samples"][0][2]
        assert wal_count >= before + 1
        secs = fams["cometbft_wal_fsync_seconds_total"]["samples"][0][2]
        assert secs >= 0.0
    finally:
        fp.reset()


def test_scrape_samples_breaker_and_staging():
    from cometbft_tpu.crypto import batch as cbatch

    brk = cbatch.device_breaker()
    pool = cbatch.staging_pool()
    pool.get("expo.test", (4,), "int32")
    pool.get("expo.test", (4,), "int32")
    pool.get("expo.test", (4,), "int32")  # 2 misses (slots) + 1 hit
    text = NodeMetrics().expose_text()
    fams = parse_promtext(text)
    kinds = {s[1].get("kind"): s[2] for s in
             fams["cometbft_crypto_staging_pool_total"]["samples"] if s[1]}
    assert kinds.get("misses", 0) >= 2
    assert kinds.get("hits", 0) >= 1
    trans = {s[1].get("kind"): s[2] for s in
             fams["cometbft_crypto_breaker_transitions_total"]["samples"]
             if s[1]}
    assert trans.get("open", -1) == float(brk.trips)
    assert trans.get("close", -1) == float(brk.closes)
    res = fams["cometbft_crypto_staging_pool_resident_bytes"]["samples"]
    assert res[0][2] >= 16  # the 4x int32 test buffers are resident


def test_scrape_staging_stats_move_under_flush_traffic():
    """ISSUE 6 satellite: the scrape-time pool stats (hits/misses/
    resident bytes) MOVE correctly as flush traffic rotates buffers —
    including the verify plane's PRIVATE pool, which only the scrape
    aggregation can see."""
    from cometbft_tpu.crypto import batch as cbatch
    from cometbft_tpu.verifyplane import (
        VerifyPlane,
        clear_global_plane,
        set_global_plane,
    )

    def pool_kinds(text):
        fams = parse_promtext(text)
        kinds = {s[1].get("kind"): s[2] for s in
                 fams["cometbft_crypto_staging_pool_total"]["samples"]
                 if s[1]}
        res = fams["cometbft_crypto_staging_pool_resident_bytes"]
        return kinds, res["samples"][0][2]

    m = NodeMetrics()
    plane = VerifyPlane(window_ms=0.5, use_device=False)
    plane.start()
    set_global_plane(plane)
    try:
        before, res_before = pool_kinds(m.expose_text())
        # rotate the plane's PRIVATE pool like concurrent device
        # flushes would: slots misses to warm a fresh shape, then hits
        for _ in range(5):
            plane._staging.get("expo.flush", (8, 4), "int32")
        # and the process-global pool (blocksync/bench path)
        cbatch.staging_pool().get("expo.flush2", (2, 2), "int32")
        after, res_after = pool_kinds(m.expose_text())
        # the private pool's 2 slots were allocation misses, the other
        # 3 gets were rotation hits; the global pool added 1 miss
        assert after.get("misses", 0) >= before.get("misses", 0) + 3
        assert after.get("hits", 0) >= before.get("hits", 0) + 3
        # resident bytes grew by exactly the new buffers: 2 slots of
        # 8x4 int32 (private pool) + the single allocated 2x2 int32
        # slot (global pool lazily allocates per get)
        assert res_after - res_before == 2 * 8 * 4 * 4 + 1 * 2 * 2 * 4
    finally:
        clear_global_plane(plane)
        plane.stop()


def test_metrics_lint_nodemetrics_clean():
    """CI gate: the full node metric set obeys the naming conventions
    (counters _total, histograms seconds/bytes/rows, no dupes)."""
    from tools.metrics_lint import lint_node_metrics

    assert lint_node_metrics() == []


def test_metrics_lint_sample_coverage_detects_undeclared():
    """The registry cross-check (ISSUE 13 satellite): a _sample body
    writing into a family never declared in NodeMetrics.__init__ must
    be flagged — its AttributeError would otherwise be swallowed by
    the sampler's fault isolation and the family would silently never
    scrape. The real _sample must pass clean (covered by the
    lint_node_metrics test above, which now includes this check)."""
    from tools.metrics_lint import _sample_coverage

    out = _sample_coverage(
        "self.ghost_family.set(1.0)\nself.height_stage.set(0.0)")
    assert any("ghost_family" in v for v in out), out
    assert not any("height_stage" in v for v in out), out


def test_metrics_lint_catches_violations():
    from tools.metrics_lint import lint_registry

    r = Registry()
    r.counter("bad", "requests", "counter missing _total")
    r.gauge("bad", "depth_total", "gauge with counter suffix")
    r.histogram("bad", "latency_ms", "histogram off base unit")
    r.counter("bad", "dup_total", "first")
    r.counter("bad", "dup_total", "second")
    r.gauge("bad", "nohelp")
    out = lint_registry(r)
    assert any("must end _total" in v for v in out)
    assert any("must not end _total" in v for v in out)
    assert any("base unit" in v for v in out)
    assert any("duplicate" in v for v in out)
    assert any("empty HELP" in v for v in out)
