"""Shared shape-faithful stub of ops.ed25519_cached's fused kernel.

The real ``_verify_tally_cached`` is a Pallas program (minutes of
interpret compile on CPU); this stub keeps its CONTRACT — validity =
precheck flag & ok[row mod M] with M derived from the table shape,
voting power tiled by the same local-index map, counted/commit-id flag
decoding, tally via the real ``tally_core`` — so sharding tests
exercise the layout/psum/memo plumbing against the exact local-index
semantics the kernel implements. The quorum output is zeros: every
sharded caller discards the in-rows quorum and recomputes it from
replicated thresholds.

One copy, used by tests/test_mesh.py (in-process 8-device mesh) and
tests/_shardplane_prog.py (forced 4-device subprocess), so the
contract cannot drift between them.
"""
import jax
import jax.numpy as jnp

from cometbft_tpu.ops import ed25519_cached as ec
from cometbft_tpu.ops import ed25519_kernel as ek


def fake_verify_tally_cached(rows, tab, ok, power5, base, n_commits):
    rows = jnp.asarray(rows)
    B = rows.shape[1]
    M = tab.shape[0] // ec.ENT_BLOCK * 128
    vidx = jax.lax.broadcasted_iota(jnp.int32, (B,), 0) % M
    valid = ((rows[ec.V_FLAGS] >> 1) & 1 != 0) \
        & jnp.take(ok, vidx, axis=0)
    pw = jnp.tile(power5, (-(-B // M), 1))[:B]
    counted = (rows[ec.V_FLAGS] >> 2) & 1 != 0
    commit_ids = rows[ec.V_FLAGS] >> 3
    tally = ek.tally_core(valid, pw, counted, commit_ids, n_commits)
    return valid, tally, jnp.zeros((n_commits,), bool)


fake_verify_tally_cached.__wrapped__ = fake_verify_tally_cached
