"""Scalable vote gossip: HasVote bitmaps, lack-based sends, VoteSetBits.

Reference: consensus/reactor.go:737 gossipVotesRoutine (send only what
the peer lacks), :404 broadcastHasVote, :896-960 queryMaj23Routine /
VoteSetBits. Unit tests drive the reactor with fake peers; the TCP test
asserts the network-wide duplicate-delivery bound that flooding could
never meet.
"""
import json
import os
import queue
import time

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.reactor import (
    STATE_CHANNEL,
    VOTE_CHANNEL,
    ConsensusReactor,
    _bits_from_hex,
)
from cometbft_tpu.consensus.state import ConsensusState, VoteMsg
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import State, StateStore
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types import canonical, serde
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

CHAIN = "gossip-chain"

FAST = TimeoutParams(
    propose=0.5, propose_delta=0.15,
    prevote=0.25, prevote_delta=0.1,
    precommit=0.25, precommit_delta=0.1,
    commit=0.02,
)


class FakePeer:
    def __init__(self, name):
        self.peer_id = name
        self.sent = []

    def send(self, chan, data):
        self.sent.append((chan, data))
        return True

    def votes_sent(self):
        return [serde.vote_from_j(json.loads(d.decode()))
                for c, d in self.sent if c == VOTE_CHANNEL]


def make_cs(n_vals=4):
    privs = [PrivKey.generate(bytes([i + 70]) * 32) for i in range(n_vals)]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis(CHAIN, vs)
    exec_ = BlockExecutor(KVStoreApplication(), StateStore(":memory:"))
    cs = ConsensusState(state, exec_, BlockStore(":memory:"),
                        privval=FilePV(privs[0]), manual_ticker=True)
    cs._started = True
    return cs, privs, vs


def add_prevote(cs, priv, vs, bid=None):
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader

    addr = priv.pub_key().address()
    idx, _ = vs.get_by_address(addr)
    v = Vote(vote_type=canonical.PREVOTE_TYPE, height=cs.height, round=0,
             block_id=bid or BlockID(b"\xaa" * 32,
                                     PartSetHeader(1, b"\xbb" * 32)),
             timestamp=Timestamp(1_700_000_100, 0),
             validator_address=addr, validator_index=idx)
    v.signature = priv.sign(v.sign_bytes(CHAIN))
    cs._handle(("vote", VoteMsg(v)), write_wal=False)
    while True:
        try:
            cs._handle(cs.internal_queue.get_nowait(), write_wal=False)
        except queue.Empty:
            break
    return v


def _step_msg(cs):
    return json.dumps({"t": "step", "h": cs.height, "r": 0,
                       "s": 4}).encode()


def test_lack_based_gossip_sends_each_vote_once():
    cs, privs, vs = make_cs()
    r = ConsensusReactor(cs)
    r.GOSSIP_GRACE = 0.0
    for p in privs[:3]:
        add_prevote(cs, p, vs)
    peer = FakePeer("p1")
    r.receive(STATE_CHANNEL, peer, _step_msg(cs))
    r._gossip_votes()
    first = peer.votes_sent()
    assert len(first) == 3, [v.validator_index for v in first]
    # second pass: nothing new to send — the bitarray bounds traffic
    r._gossip_votes()
    assert len(peer.votes_sent()) == 3


def test_has_vote_suppresses_resend():
    cs, privs, vs = make_cs()
    r = ConsensusReactor(cs)
    r.GOSSIP_GRACE = 0.0
    votes = [add_prevote(cs, p, vs) for p in privs[:3]]
    peer = FakePeer("p2")
    r.receive(STATE_CHANNEL, peer, _step_msg(cs))
    # the peer announces it already holds vote[0]
    r.receive(STATE_CHANNEL, peer, json.dumps({
        "t": "has_vote", "h": cs.height, "r": 0,
        "vt": canonical.PREVOTE_TYPE, "i": votes[0].validator_index,
    }).encode())
    r._gossip_votes()
    got = {v.validator_index for v in peer.votes_sent()}
    assert votes[0].validator_index not in got
    assert len(got) == 2


def test_maj23_answers_with_vote_set_bits():
    cs, privs, vs = make_cs()
    r = ConsensusReactor(cs)
    r.GOSSIP_GRACE = 0.0
    votes = [add_prevote(cs, p, vs) for p in privs[:3]]  # 3/4 = +2/3
    bid = votes[0].block_id
    vsur = cs.votes.prevotes(0)
    assert vsur.two_thirds_majority() is not None
    peer = FakePeer("p3")
    r.receive(STATE_CHANNEL, peer, _step_msg(cs))
    r.receive(STATE_CHANNEL, peer, json.dumps({
        "t": "maj23", "h": cs.height, "r": 0,
        "vt": canonical.PREVOTE_TYPE, "bid": serde.bid_to_j(bid),
    }).encode())
    vsbs = [json.loads(d.decode()) for c, d in peer.sent
            if c == STATE_CHANNEL and b'"vsb"' in d]
    assert vsbs, "no VoteSetBits reply"
    bits = _bits_from_hex(vsbs[0]["bits"], len(vs))
    assert sorted(bits) == sorted(v.validator_index for v in votes)


def test_vote_set_bits_fills_peer_bitmap():
    cs, privs, vs = make_cs()
    r = ConsensusReactor(cs)
    r.GOSSIP_GRACE = 0.0
    votes = [add_prevote(cs, p, vs) for p in privs[:3]]
    peer = FakePeer("p4")
    r.receive(STATE_CHANNEL, peer, _step_msg(cs))
    # peer reports (via VoteSetBits) that it holds ALL these votes
    raw = bytearray(1)
    for v in votes:
        raw[0] |= 1 << v.validator_index
    r.receive(STATE_CHANNEL, peer, json.dumps({
        "t": "vsb", "h": cs.height, "r": 0,
        "vt": canonical.PREVOTE_TYPE, "bits": bytes(raw).hex(),
    }).encode())
    r._gossip_votes()
    assert peer.votes_sent() == []


def _wait_mesh(nodes, want_peers, timeout=90.0):
    """Deflake (host-load resilience): dials are ephemeral-port TCP
    with pure-Python handshakes — under parallel host load a dial can
    time out. node.dial registers the peer as persistent, so the
    switch's redial loop retries with backoff; this just waits
    (generously) until every node sees the full mesh before the test
    starts expecting consensus progress."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(n.switch.num_peers() >= want_peers for n in nodes):
            return True
        time.sleep(0.25)
    return False


def test_tcp_net_converges_with_bounded_duplicates(tmp_path):
    """5 validators over real TCP reach height 4; lack-based gossip
    keeps duplicate vote deliveries far below flood levels (flooding a
    full mesh re-delivers every vote ~N-2 times; assert < 60% dups)."""
    privs = [PrivKey.generate(bytes([i + 80]) * 32) for i in range(5)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("gossip-tcp", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x50 + i]) * 32)))
        addrs.append(n.listen())  # port=0: ephemeral, no reuse races
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        # bounded retries: a failed first dial is retried by the
        # persistent-peer redial loop; only the mesh-up wait is bounded
        for i, n in enumerate(nodes):
            for j, a in enumerate(addrs):
                if i != j:
                    n.dial(a)
        assert _wait_mesh(nodes, want_peers=len(nodes) - 1), \
            f"mesh never formed: {[n.switch.num_peers() for n in nodes]}"
        for n in nodes:
            assert n.consensus.wait_for_height(4, timeout=120), \
                f"stuck at {n.height()}"
        received = sum(n.consensus_reactor.votes_received for n in nodes)
        dups = sum(n.consensus_reactor.votes_duplicate for n in nodes)
        assert received > 0
        assert dups < 0.6 * received, \
            f"{dups} duplicates of {received} received — gossip not " \
            f"bounding traffic"
    finally:
        for n in nodes:
            n.stop()
