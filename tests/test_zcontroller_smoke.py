"""Control-plane tier-1 wiring (ISSUE 16): GET+JSON-RPC
/dump_controller over a live server with a mounted controller,
post-stop history (the _LAST pattern), /metrics controller families
riding a real scrape, the incident-snapshot controller tail, and the
controller_report --diff regression detector (including the miswired
--fail-on-regression gate).

Late in the alphabet on purpose (tier-1 ordering note in ROADMAP).
Host-only: the whole file must run with NO jax import (asserted).
"""
import copy
import json
import sys
import urllib.request

import pytest

from cometbft_tpu.libs import controller as cp
from cometbft_tpu.libs import incidents

_JAX_LOADED_BEFORE = "jax" in sys.modules


class _Ledger:
    def __init__(self, p99=0.0):
        self.p99 = p99

    def __len__(self):
        return 1

    def summary(self):
        return {"commit_latency_ms": {"p99": self.p99}}


class _Admission:
    def __init__(self):
        self.high_watermark = 0.9
        self.low_watermark = 0.7
        self._fill_fn = lambda: 0.0

    def set_watermarks(self, high, low):
        self.high_watermark, self.low_watermark = high, low
        return (high, low)


def _decided_controller(n_moves=2):
    """A controller with real decisions on the ring, driven against
    fakes (decision_interval=1 so every poke evaluates)."""
    led = _Ledger(p99=500.0)
    ctl = cp.Controller(slo_commit_p99_ms=100.0, decision_interval=1,
                        cooldown=0)
    ctl.attach(admission=_Admission(), height_ledger=led,
               bounds={cp.ACT_ADMISSION: (0.2, 0.9)})
    for h in range(1, n_moves + 1):
        ctl.poke(h, 0)
    assert ctl.dump()["state"]["decisions_total"] >= 1
    return ctl


def _mini_net(n_nodes=2):
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.consensus.ticker import TimeoutParams
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.node.node import LocalNetwork, Node
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.state.state import State
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    fast = TimeoutParams(propose=0.4, propose_delta=0.1, prevote=0.2,
                         prevote_delta=0.1, precommit=0.2,
                         precommit_delta=0.1, commit=0.05)
    privs = [PrivKey.generate(bytes([120 + i]) * 32)
             for i in range(n_nodes)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("zctl-chain", vals)
    net = LocalNetwork()
    nodes = []
    for i, priv in enumerate(privs):
        node = Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(priv), broadcast=net.broadcaster(i),
                    timeouts=fast)
        net.add(node)
        nodes.append(node)
    return nodes


def test_dump_controller_over_real_rpc():
    """GET /dump_controller and the JSON-RPC form over a live server
    (the curl surface), /metrics controller families on a real scrape,
    and post-stop history via the module global (_LAST)."""
    old_global, old_last = cp._GLOBAL, cp._LAST
    nodes = _mini_net(2)
    try:
        for n in nodes:
            n.start()
        # mount a decided controller on the serving node (the simnet
        # op and node lifecycle do the same wiring)
        ctl = _decided_controller()
        nodes[0].controller = ctl
        cp.set_global_controller(ctl)
        expected = ctl.dump()["state"]["decisions_total"]
        url = nodes[0].rpc_listen("127.0.0.1", 0)
        assert nodes[0].consensus.wait_for_height(1, timeout=30.0)
        with urllib.request.urlopen(url + "/dump_controller",
                                    timeout=10) as r:
            doc = json.loads(r.read().decode())
        # the live node's step seam keeps poking the mounted
        # controller, so totals only grow past the mount-time snapshot
        assert doc["state"]["decisions_total"] >= expected
        assert doc["actuators"]["admission_high_watermark"]["moves"] \
            >= 1
        assert doc["decisions"][0]["trigger"]["p99_ms"] == 500.0
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "dump_controller",
                           "params": {}}).encode()
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            rpc = json.loads(r.read().decode())
        assert rpc["result"]["state"]["decisions_total"] >= expected
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for fam in ("cometbft_controller_decisions_total",
                    "cometbft_controller_actuator_value",
                    "cometbft_controller_slo_violation_seconds_total"):
            assert fam in text, fam
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("cometbft_controller_decisions_total{")
            and 'actuator="admission_high_watermark"' in ln
            and 'direction="down"' in ln)
        assert float(line.split()[-1]) >= 1.0
    finally:
        for n in nodes:
            n.stop()
        cp._GLOBAL, cp._LAST = old_global, old_last
    # history after the node stopped: _LAST still serves (within the
    # try the globals were live; re-register to assert the pattern)
    cp.set_global_controller(ctl)
    cp.clear_global_controller(ctl)
    try:
        assert cp.dump_controller()["state"]["decisions_total"] \
            >= expected
    finally:
        cp._GLOBAL, cp._LAST = old_global, old_last


def test_incident_snapshot_carries_controller_tail():
    """A controller move inside an incident's window rides the frozen
    snapshot (the flight-recorder join)."""
    old_global, old_last = cp._GLOBAL, cp._LAST
    rec = incidents.IncidentRecorder(commit_stall_s=0.0, window_s=60.0,
                                     cooldown_s=0.0)
    old_rec = incidents.install(rec)
    try:
        ctl = _decided_controller()
        cp.set_global_controller(ctl)
        snap = rec._snapshot("forced", 1, 0, 5, 0, {})
        assert snap["controller_tail"], snap
        assert "admission_high_watermark" in snap["controller_tail"][0]
        assert " down " in snap["controller_tail"][0]
    finally:
        incidents.install(old_rec)
        cp._GLOBAL, cp._LAST = old_global, old_last


def test_controller_report_diff_detects_synthetic_regression(
        tmp_path, capsys):
    """The --diff CLI path flags injected violation/flap/displacement
    regressions (exit 1 under --fail-on-regression), stays quiet on
    identical dumps, and errors on a miswired gate
    (--fail-on-regression without --diff)."""
    from tools import controller_report

    ctl = _decided_controller()
    dump = ctl.dump()
    a_path = tmp_path / "a.json"
    a_path.write_text(json.dumps(dump))
    doctored = copy.deepcopy(dump)
    doctored["state"]["slo_violation_s"] += 7.5
    doctored["state"]["decisions_total"] += 200
    doctored["actuators"]["admission_high_watermark"]["value"] = 0.3
    b_path = tmp_path / "b.json"
    b_path.write_text(json.dumps(doctored))

    rc = controller_report.main([str(a_path), str(a_path), "--diff",
                                 "--fail-on-regression"])
    assert rc == 0
    capsys.readouterr()
    rc = controller_report.main([str(a_path), str(b_path), "--diff",
                                 "--fail-on-regression"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "slo_violation_s" in out and "decisions_total" in out
    assert "displacement_total" in out
    # ANY violation growth flags — holding the SLO is the loop's one
    # job; a big baseline must not excuse new violation seconds
    small = copy.deepcopy(dump)
    small["state"]["slo_violation_s"] = 100.0
    more = copy.deepcopy(small)
    more["state"]["slo_violation_s"] = 100.5
    (tmp_path / "sm.json").write_text(json.dumps(small))
    (tmp_path / "mo.json").write_text(json.dumps(more))
    capsys.readouterr()
    rc = controller_report.main([str(tmp_path / "sm.json"),
                                 str(tmp_path / "mo.json"),
                                 "--diff", "--fail-on-regression"])
    assert rc == 1
    with pytest.raises(SystemExit):
        controller_report.main([str(a_path), "--fail-on-regression"])
    # the single-dump report renders the actuator table + timeline
    capsys.readouterr()
    assert controller_report.main([str(a_path)]) == 0
    out = capsys.readouterr().out
    assert "admission_high_watermark" in out
    assert "decision timeline" in out
    # bench --json-out evidence files are a first-class input shape
    wrapped = {"results": {"cfg16_smoke": {
        "metric": "x", "value": 1.0,
        "extra": {"controller_dump": dump}}}}
    w_path = tmp_path / "bench.json"
    w_path.write_text(json.dumps(wrapped))
    loaded = controller_report.load_controller(str(w_path))
    assert loaded["state"]["decisions_total"] \
        == dump["state"]["decisions_total"]
    junk = tmp_path / "junk.json"
    junk.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError):
        controller_report.load_controller(str(junk))


def test_no_jax_import():
    """The whole file ran host-only: nothing here may pull jax in."""
    if not _JAX_LOADED_BEFORE:
        assert "jax" not in sys.modules
