"""psql event sink: schema parity with state/indexer/sink/psql.

The reference contract: blocks/tx_results/events/attributes tables +
event_attributes/block_events/tx_events views; search is NOT served by
the sink (reads are plain SQL). Runs on the sqlite dialect shim (no
postgres server in the image) — the SQL text and table/view names are
the schema.sql ones.
"""
import hashlib

import pytest

from cometbft_tpu.abci.types import ExecTxResult
from cometbft_tpu.state.psql_sink import PsqlEventSink, PsqlSinkError


@pytest.fixture()
def sink(tmp_path):
    s = PsqlEventSink.sqlite(str(tmp_path / "sink.db"), "psql-chain")
    yield s
    s.close()


def test_tx_events_schema_parity(sink):
    tx = b"k=v"
    res = ExecTxResult(code=0, data=b"\x01", log="ok")
    sink.index_tx_events(3, 0, tx, res,
                         {"transfer.amount": ["100"],
                          "transfer.sender": ["alice"]})
    cur = sink.conn.cursor()
    # blocks row (height, chain_id) unique
    rows = cur.execute(
        "SELECT height, chain_id FROM blocks").fetchall()
    assert rows == [(3, "psql-chain")]
    # tx_results row with hex hash + result payload
    h = hashlib.sha256(tx).hexdigest().upper()
    rows = cur.execute(
        'SELECT "index", tx_hash FROM tx_results').fetchall()
    assert rows == [(0, h)]
    # the tx_events VIEW joins blocks + tx_results + attributes
    got = dict(
        (ck, v) for (ck, v) in cur.execute(
            "SELECT composite_key, value FROM tx_events "
            "WHERE height = 3").fetchall()
    )
    assert got["tx.height"] == "3"
    assert got["tx.hash"] == h
    assert got["transfer.amount"] == "100"
    assert got["transfer.sender"] == "alice"
    # attributes carry split (type, key) like abci events
    t = cur.execute(
        "SELECT type FROM events WHERE tx_id IS NOT NULL "
        "AND type='transfer'").fetchall()
    assert t, "event type not split from composite key"

    # re-index of the same (block, index) is a no-op (upsert)
    sink.index_tx_events(3, 0, tx, res)
    assert cur.execute(
        "SELECT COUNT(*) FROM tx_results").fetchone()[0] == 1


def test_block_events_view_and_search_unsupported(sink):
    sink.index_block_events(7, {"block.proposer": ["AA" * 20]})
    cur = sink.conn.cursor()
    got = dict(cur.execute(
        "SELECT composite_key, value FROM block_events "
        "WHERE height = 7").fetchall())
    assert got["block.height"] == "7"
    assert got["block.proposer"] == "AA" * 20
    # block events have tx_id NULL by definition of the view
    assert cur.execute(
        "SELECT COUNT(*) FROM events WHERE tx_id IS NULL"
    ).fetchone()[0] >= 1
    with pytest.raises(PsqlSinkError):
        sink.search("tx.height=7")


def test_indexer_service_feeds_extra_sink(tmp_path):
    """IndexerService fans out to the psql sink alongside the kv
    indexers (txindex/indexer_service.go multi-sink)."""
    import time

    from cometbft_tpu.state.indexer import (
        BlockIndexer,
        IndexerService,
        TxIndexer,
    )
    from cometbft_tpu.types.event_bus import EventBus

    bus = EventBus()
    sink = PsqlEventSink.sqlite(str(tmp_path / "s.db"), "svc-chain")
    svc = IndexerService(bus, TxIndexer(), BlockIndexer(),
                         extra_sinks=[sink])
    try:
        bus.publish_tx(5, b"a=1", ExecTxResult(code=0, data=b"", log=""))
        deadline = time.time() + 10
        while time.time() < deadline:
            if sink.conn.cursor().execute(
                    "SELECT COUNT(*) FROM tx_results").fetchone()[0]:
                break
            time.sleep(0.05)
        cur = sink.conn.cursor()
        assert cur.execute(
            "SELECT COUNT(*) FROM tx_results").fetchone()[0] == 1
        assert cur.execute(
            "SELECT height FROM blocks").fetchone()[0] == 5
    finally:
        svc.stop()
        sink.close()
