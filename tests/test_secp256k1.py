"""secp256k1: host oracle vs OpenSSL, device curve vs oracle, batched
ECDSA kernel edge cases.

Differential strategy mirrors tests/test_ed25519_kernel.py: the pure-
Python oracle (crypto/secp256k1_ref.py) is validated against OpenSSL,
then the device kernel is validated against the oracle — including the
malleability (high-S) and malformed-encoding paths the reference enforces
in crypto/secp256k1/secp256k1.go:192-220.
"""
import hashlib
import random

import numpy as np
import pytest

from cometbft_tpu.crypto import secp256k1_ref as ref
from cometbft_tpu.ops import ecdsa_kernel as ek
from cometbft_tpu.ops import secp256k1 as curve
from cometbft_tpu.ops.field import FSECP, limbs_to_int

F = FSECP
rng = random.Random(7)


def rand_point():
    return ref.pt_mul(rng.randrange(1, ref.N), (ref.GX, ref.GY))


def to_affine(p):
    X, Y, Z = [np.asarray(F.canonical(c)) for c in curve.unstack(p)]
    xs = np.atleast_1d(limbs_to_int(X))
    ys = np.atleast_1d(limbs_to_int(Y))
    zs = np.atleast_1d(limbs_to_int(Z))
    out = []
    for x, y, z in zip(xs, ys, zs):
        if int(z) == 0:
            out.append(None)
            continue
        zi = pow(int(z), ref.P - 2, ref.P)
        out.append((int(x) * zi % ref.P, int(y) * zi % ref.P))
    return out


def test_oracle_vs_openssl():
    """Oracle verify accepts OpenSSL signatures; oracle pubkeys match.
    Needs the `cryptography` wheel, which this container does not ship
    (ROADMAP container limits; the pure-Python fallbacks are the
    load-bearing path here) — skip rather than fail where the
    differential oracle simply cannot run."""
    ec = pytest.importorskip(
        "cryptography.hazmat.primitives.asymmetric.ec",
        reason="cryptography wheel unavailable (container constraint); "
               "OpenSSL differential needs it",
    )

    for i in range(4):
        d = rng.randrange(1, ref.N)
        sk = ec.derive_private_key(d, ec.SECP256K1())
        pn = sk.public_key().public_numbers()
        assert ref.pubkey_from_secret(d) == ref.compress(pn.x, pn.y)
        msg = b"oracle-%d" % i
        sig = ref.sign(d, msg)
        assert ref.verify(ref.pubkey_from_secret(d), msg, sig)
        assert not ref.verify(ref.pubkey_from_secret(d), msg + b"x", sig)


def test_decompress_roundtrip():
    for _ in range(4):
        x, y = rand_point()
        assert ref.decompress(ref.compress(x, y)) == (x, y)
    assert ref.decompress(b"\x04" + b"\x00" * 32) is None  # bad prefix
    assert ref.decompress(b"\x02" + ref.P.to_bytes(32, "big")) is None
    # x with no curve point (x=5 -> 132 is a QNR mod p)
    assert pow(132, (ref.P - 1) // 2, ref.P) != 1
    assert ref.decompress(b"\x02" + (5).to_bytes(32, "big")) is None


def test_device_add_double_vs_oracle():
    pts = [rand_point() for _ in range(6)]
    dev = np.stack([curve.from_affine_int(x, y) for x, y in pts])
    got = to_affine(curve.add(dev[:3], dev[3:]))
    want = [ref.pt_add(pts[i], pts[i + 3]) for i in range(3)]
    assert got == want
    got = to_affine(curve.double(dev))
    want = [ref.pt_add(p, p) for p in pts]
    assert got == want


def test_complete_formula_edge_cases():
    """Complete formulas: P + P, P + (-P) -> inf, inf + P, inf + inf."""
    x, y = rand_point()
    p = curve.from_affine_int(x, y)[None]
    minus = curve.from_affine_int(x, ref.P - y)[None]
    ident = np.asarray(curve.identity((1,)))
    assert to_affine(curve.add(p, p)) == [ref.pt_add((x, y), (x, y))]
    assert to_affine(curve.add(p, minus)) == [None]
    assert to_affine(curve.add(ident, p)) == [(x, y)]
    assert to_affine(curve.add(ident, ident)) == [None]
    assert to_affine(curve.double(ident)) == [None]


def test_scalar_mul_matches_oracle():
    ks = [1, 2, 0xDEADBEEF, ref.N - 1, (1 << 255) % ref.N]
    digs = np.stack([
        ek.nibbles(np.frombuffer(k.to_bytes(32, "little"), np.uint8))
        for k in ks
    ])
    g = np.broadcast_to(
        curve.from_affine_int(ref.GX, ref.GY), (len(ks), 3, 20)
    )
    got = to_affine(curve.scalar_mul_windowed(digs, np.ascontiguousarray(g)))
    want = [ref.pt_mul(k, (ref.GX, ref.GY)) for k in ks]
    assert got == want
    got = to_affine(curve.base_scalar_mul(digs))
    assert got == want


def test_ecdsa_batch_valid_and_blame():
    n = 8
    secrets = [rng.randrange(1, ref.N) for _ in range(n)]
    pubs = [ref.pubkey_from_secret(d) for d in secrets]
    msgs = [b"tx-%d" % i for i in range(n)]
    sigs = [ref.sign(d, m) for d, m in zip(secrets, msgs)]
    assert ek.verify_batch(pubs, msgs, sigs).all()

    # tampered sig, wrong key, wrong msg — each invalid, others unaffected
    bad_sig = bytearray(sigs[1]); bad_sig[40] ^= 0x10
    sigs2 = list(sigs); sigs2[1] = bytes(bad_sig)
    pubs2 = list(pubs); pubs2[3] = pubs[4]
    msgs2 = list(msgs); msgs2[5] = b"evil"
    valid = ek.verify_batch(pubs2, msgs2, sigs2)
    assert list(valid) == [True, False, True, False, True, False, True, True]


def test_ecdsa_malleability_and_malformed():
    d = rng.randrange(1, ref.N)
    pub = ref.pubkey_from_secret(d)
    msg = b"malleate"
    sig = ref.sign(d, msg)
    r = sig[:32]
    s = int.from_bytes(sig[32:], "big")
    high_s = r + (ref.N - s).to_bytes(32, "big")
    zero_s = r + b"\x00" * 32
    big_r = ref.N.to_bytes(32, "big") + sig[32:]
    bad_len = sig[:63]
    bad_prefix = b"\x05" + pub[1:]
    cases_pub = [pub, pub, pub, pub, bad_prefix]
    cases_sig = [high_s, zero_s, big_r, bad_len, sig]
    valid = ek.verify_batch(cases_pub, [msg] * 5, cases_sig)
    assert not valid.any()
    # oracle agrees on every case
    assert not any(
        ref.verify(p, msg, s_) for p, s_ in zip(cases_pub, cases_sig)
    )


def test_address():
    """RIPEMD160(SHA256(pub)) (secp256k1.go:131)."""
    pub = ref.pubkey_from_secret(42)
    addr = ref.address(pub)
    assert len(addr) == 20
    assert addr == hashlib.new(
        "ripemd160", hashlib.sha256(pub).digest()
    ).digest()


@pytest.mark.slow  # ~75 s: compiles two kernels for one commit;
# ecdsa_batch_valid_and_blame keeps the quick-gate batch coverage
def test_mixed_key_commit_verification():
    """A commit signed by a mix of ed25519 and secp256k1 validators
    verifies in one batch call — capability the reference lacks entirely
    (crypto/batch/batch.go:12-21 has no secp256k1 arm; mixed commits fall
    back to serial verifyCommitSingle there)."""
    from cometbft_tpu.crypto.keys import PrivKey, Secp256k1PrivKey
    from cometbft_tpu.types import canonical, validation
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    chain_id, height, round_ = "secp-chain", 5, 0
    privs = [
        PrivKey.generate(bytes([i + 1]) * 32) if i % 2 == 0
        else Secp256k1PrivKey.generate(bytes([i + 1]) * 32)
        for i in range(6)
    ]
    vs = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32))
    sigs = []
    for idx, v in enumerate(vs.validators):
        ts = Timestamp(1700000000 + idx, 0)
        sb = canonical.canonical_vote_bytes(
            chain_id, canonical.PRECOMMIT_TYPE, height, round_, bid, ts
        )
        sigs.append(CommitSig(
            BLOCK_ID_FLAG_COMMIT, v.address, ts, by_addr[v.address].sign(sb)
        ))
    commit = Commit(height, round_, bid, sigs)
    for mk in (validation.oracle_batch_fn,
               lambda: validation.device_batch_fn(use_pallas=False)):
        validation.verify_commit(chain_id, vs, bid, height, commit, mk())

    # corrupt one secp sig: blame lands on the right index
    secp_idx = next(
        i for i, v in enumerate(vs.validators)
        if v.pub_key.key_type == "secp256k1"
    )
    bad = bytearray(sigs[secp_idx].signature)
    bad[8] ^= 1
    sigs2 = list(sigs)
    sigs2[secp_idx] = CommitSig(
        BLOCK_ID_FLAG_COMMIT, vs.validators[secp_idx].address,
        sigs[secp_idx].timestamp, bytes(bad),
    )
    commit2 = Commit(height, round_, bid, sigs2)
    with pytest.raises(validation.InvalidSignatureError) as ei:
        validation.verify_commit(
            chain_id, vs, bid, height, commit2,
            validation.device_batch_fn(use_pallas=False),
        )
    assert ei.value.idx == secp_idx


@pytest.mark.slow  # >8 min interpret-mode ECDSA Pallas on CPU —
# the single biggest tier-1 budget sink before it was marked
def test_ecdsa_pallas_matches_oracle():
    """Pallas ECDSA kernel vs the pure-Python oracle (interpret mode on
    CPU; Mosaic on TPU) — one tile incl. malformed/corrupt rows."""
    import numpy as np

    from cometbft_tpu.crypto import secp256k1_ref as sref
    from cometbft_tpu.crypto.keys import Secp256k1PrivKey
    from cometbft_tpu.ops import ecdsa_pallas as cp

    ks = [Secp256k1PrivKey.generate(bytes([i + 1]) * 32) for i in range(8)]
    n = 24
    msgs = [b"pallas-ecdsa-%d" % i for i in range(n)]
    pubs = [ks[i % 8].pub_key().data for i in range(n)]
    sigs = [ks[i % 8].sign(m) for i, m in enumerate(msgs)]
    sigs[2] = sigs[2][:9] + bytes([sigs[2][9] ^ 1]) + sigs[2][10:]
    sigs[5] = b"\x00" * 64                        # r = 0
    pubs[7] = b"\x07" + pubs[7][1:]               # bad prefix
    # high-S malleated twin of row 8 must be rejected (low-S rule)
    r8 = sigs[8][:32]
    s8 = int.from_bytes(sigs[8][32:], "big")
    sigs[8] = r8 + (sref.N - s8).to_bytes(32, "big")
    got = cp.verify_batch(pubs, msgs, sigs)
    exp = np.asarray(
        [sref.verify_py(p, m, s) for p, m, s in zip(pubs, msgs, sigs)]
    )
    np.testing.assert_array_equal(got, exp)
    assert not exp[2] and not exp[5] and not exp[7] and not exp[8]
    assert exp[0]
