"""PEX + address book: peer discovery over real TCP.

Reference: p2p/pex/pex_reactor_test.go + addrbook_test.go shapes.
"""
import time

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NetAddress, NodeKey
from cometbft_tpu.p2p.pex import AddrBook
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def test_addrbook_persistence_and_caps(tmp_path):
    path = str(tmp_path / "book.json")
    book = AddrBook(path, max_per_source=2)
    a = NetAddress("aa" * 20, "127.0.0.1", 1)
    assert book.add(a, source="s1")
    assert not book.add(a, source="s1")  # dedupe
    assert book.add(NetAddress("bb" * 20, "127.0.0.1", 2), source="s1")
    # per-source cap: s1 may not add a third
    assert not book.add(NetAddress("cc" * 20, "127.0.0.1", 3), source="s1")
    assert book.add(NetAddress("cc" * 20, "127.0.0.1", 3), source="s2")
    book.mark_bad("aa" * 20)
    picked = {book.pick().node_id for _ in range(20)}
    assert "aa" * 20 not in picked
    book.save()
    book2 = AddrBook(path)
    assert book2.size() == 3


def test_pex_discovers_third_node(tmp_path):
    """A dials only B; B knows C; PEX teaches A about C and the ensure
    routine dials it — a full mesh emerges from one seed edge
    (pex_reactor.go:130's purpose)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("pex-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 pex=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x70 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        # seed topology: A-B and B-C only
        nodes[0].dial(addrs[1])
        nodes[2].dial(addrs[1])
        # generous (host-load deflake, like test_vote_gossip): each
        # pure-Python TCP handshake can take seconds on the loaded
        # 1-core CI host, and discovery needs dial->PEX->redial cycles
        deadline = time.time() + 90
        while time.time() < deadline:
            if nodes[0].switch.num_peers() >= 2 and \
                    nodes[2].switch.num_peers() >= 2:
                break
            time.sleep(0.2)
        assert nodes[0].switch.num_peers() >= 2, \
            f"A peers: {nodes[0].switch.num_peers()}"
        # A's book learned C's address via PEX
        c_id = nodes[2].switch.node_key.node_id
        assert c_id in nodes[0].switch.peers
        # and the net still commits (generous: 3 TCP nodes that spent
        # the dial phase burning rounds alone need several round-trips
        # per height on a loaded host — fails at HEAD with 60 s, and
        # intermittently at 150 s when the whole suite runs slow: the
        # per-round timeouts the solo phase escalated to take minutes
        # to converge back under pure-Python crypto on a contended
        # core. Two heights prove the post-PEX mesh commits; the
        # deadline pays only on failure)
        assert nodes[0].consensus.wait_for_height(2, timeout=280), \
            f"heights: {[n.height() for n in nodes]}"
    finally:
        for n in nodes:
            n.stop()


def test_addrbook_buckets_promote_demote(tmp_path):
    """addrbook.go new/old tiers: mark_good promotes (and persists
    eagerly); repeated failed attempts demote old->new but NEVER delete
    (delete-on-failure was the round-5 advisory bug: transient total
    unreachability emptied the persisted book)."""
    path = str(tmp_path / "book.json")
    book = AddrBook(path)
    aid, bid = "aa" * 20, "bb" * 20
    book.add(NetAddress(aid, "127.0.0.1", 1), source="s")
    book.add(NetAddress(bid, "127.0.0.1", 2), source="s")
    assert book._addrs[aid]["bucket"] == "new"
    book.mark_good(aid)
    assert book._addrs[aid]["bucket"] == "old"
    # eager persistence on promote: a crash right now still redials A
    assert AddrBook(path)._addrs[aid]["bucket"] == "old"

    # old demotes to new after MAX_ATTEMPTS failures
    for _ in range(AddrBook.MAX_ATTEMPTS + 1):
        book.mark_attempt(aid)
    assert book._addrs[aid]["bucket"] == "new"
    # new entries survive any number of failures (backed off, capped)
    for _ in range(AddrBook.MAX_ATTEMPTS * 3):
        book.mark_attempt(bid)
    assert bid in book._addrs
    assert book._addrs[bid]["attempts"] == AddrBook.MAX_ATTEMPTS


def test_addrbook_backoff_and_seed_retention(tmp_path):
    """ISSUE acceptance: the book retains operator seeds and redials
    after transient total unreachability — failures back entries off,
    cooldown lapse makes them pickable again, and seeds survive both
    failure storms and new-tier eviction pressure."""
    book = AddrBook(str(tmp_path / "book.json"))
    seed_id = "ee" * 20
    plain_id = "ab" * 20
    book.add(NetAddress(seed_id, "127.0.0.1", 9), seed=True)
    book.add(NetAddress(plain_id, "127.0.0.1", 10), source="s")

    # total unreachability: everything fails over and over
    for _ in range(20):
        book.mark_attempt(seed_id)
        book.mark_attempt(plain_id)
    assert seed_id in book._addrs and plain_id in book._addrs
    # backed off: not pickable right now
    assert book.pick() is None
    # ...but after the cooldown both become dialable again
    for e in book._addrs.values():
        e["next_dial"] = time.time() - 1
    picked = {book.pick().node_id for _ in range(20)}
    assert seed_id in picked
    # a success resets the backoff entirely
    book.mark_good(seed_id)
    assert book._addrs[seed_id]["attempts"] == 0
    assert book._addrs[seed_id]["next_dial"] == 0.0

    # persisted backoff does not wedge a restart: cooldowns reset on load
    book.mark_attempt(plain_id)
    book.save()
    book2 = AddrBook(book.path)
    assert book2._addrs[plain_id]["next_dial"] == 0.0
    assert book2._addrs[seed_id]["seed"] is True

    # eviction pressure cannot displace the seed even from the new tier
    # (non-seed gossip entries MAY be evicted under capacity pressure —
    # that is the one legitimate eviction path)
    book.MAX_NEW = 2
    for i in range(8):
        book.add(NetAddress(f"{i:02x}" * 20, "127.0.0.1", 1000 + i),
                 source=f"s{i}")
    assert seed_id in book._addrs


def test_addrbook_pick_bias_and_new_eviction(tmp_path):
    book = AddrBook(None)
    book.MAX_NEW = 8
    for i in range(4):
        nid = f"{i:02x}" * 20
        book.add(NetAddress(nid, "127.0.0.1", 1000 + i), source="")
        book.mark_good(nid)
    for i in range(4, 16):
        book.add(NetAddress(f"{i:02x}" * 20, "127.0.0.1", 1000 + i),
                 source=f"s{i}")
    # new tier evicted down to MAX_NEW; old tier untouched
    news = [e for e in book._addrs.values() if e["bucket"] == "new"]
    olds = [e for e in book._addrs.values() if e["bucket"] == "old"]
    assert len(news) == 8 and len(olds) == 4
    # bias_new=0 always picks tried addresses
    for _ in range(10):
        picked = book.pick(bias_new=0.0)
        assert book._addrs[picked.node_id]["bucket"] == "old"
    # bias_new=1 always picks gossip addresses
    for _ in range(10):
        picked = book.pick(bias_new=1.0)
        assert book._addrs[picked.node_id]["bucket"] == "new"


def test_node_redials_from_persisted_book(tmp_path):
    """Restart redial (VERDICT r4 gap): node A connects to B (book
    persists B as tried), A restarts with NO dial calls and NO inbound
    peers, and the PEX ensure routine redials B from the book."""
    privs = [PrivKey.generate(bytes([i + 40]) * 32) for i in range(2)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("redial-chain", vals)

    def mk(i):
        return Node(KVStoreApplication(), state.copy(),
                    privval=FilePV(privs[i]),
                    home=str(tmp_path / f"n{i}"), timeouts=FAST,
                    p2p=True, pex=True,
                    node_key=NodeKey(
                        PrivKey.generate(bytes([0x90 + i]) * 32)))

    a, b = mk(0), mk(1)
    addr_b = b.listen()
    a.listen()
    a.start()
    b.start()
    try:
        a.dial(addr_b)
        deadline = time.time() + 15
        while time.time() < deadline and a.switch.num_peers() < 1:
            time.sleep(0.1)
        assert a.switch.num_peers() >= 1
        # B's id was promoted to tried and persisted eagerly
        assert a.addr_book._addrs[addr_b.node_id]["bucket"] == "old"
    finally:
        a.stop()

    # restart A: same home -> same book; no dial() call at all. The
    # ensure routine must redial B from the persisted book. B kept
    # listening on the same port.
    a2 = mk(0)
    a2.pex_reactor.ensure_interval = 0.3
    a2.listen()
    a2.start()
    try:
        assert a2.addr_book.size() >= 1  # reloaded from disk
        deadline = time.time() + 20
        while time.time() < deadline and a2.switch.num_peers() < 1:
            time.sleep(0.1)
        assert a2.switch.num_peers() >= 1, "restarted node did not redial"
        assert addr_b.node_id in a2.switch.peers
    finally:
        a2.stop()
        b.stop()
