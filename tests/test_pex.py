"""PEX + address book: peer discovery over real TCP.

Reference: p2p/pex/pex_reactor_test.go + addrbook_test.go shapes.
"""
import time

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.consensus.ticker import TimeoutParams
from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.key import NetAddress, NodeKey
from cometbft_tpu.p2p.pex import AddrBook
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.state.state import State
from cometbft_tpu.types.validator import Validator, ValidatorSet

FAST = TimeoutParams(
    propose=0.4, propose_delta=0.1,
    prevote=0.2, prevote_delta=0.1,
    precommit=0.2, precommit_delta=0.1,
    commit=0.01,
)


def test_addrbook_persistence_and_caps(tmp_path):
    path = str(tmp_path / "book.json")
    book = AddrBook(path, max_per_source=2)
    a = NetAddress("aa" * 20, "127.0.0.1", 1)
    assert book.add(a, source="s1")
    assert not book.add(a, source="s1")  # dedupe
    assert book.add(NetAddress("bb" * 20, "127.0.0.1", 2), source="s1")
    # per-source cap: s1 may not add a third
    assert not book.add(NetAddress("cc" * 20, "127.0.0.1", 3), source="s1")
    assert book.add(NetAddress("cc" * 20, "127.0.0.1", 3), source="s2")
    book.mark_bad("aa" * 20)
    picked = {book.pick().node_id for _ in range(20)}
    assert "aa" * 20 not in picked
    book.save()
    book2 = AddrBook(path)
    assert book2.size() == 3


def test_pex_discovers_third_node(tmp_path):
    """A dials only B; B knows C; PEX teaches A about C and the ensure
    routine dials it — a full mesh emerges from one seed edge
    (pex_reactor.go:130's purpose)."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    state = State.make_genesis("pex-chain", vals)
    nodes, addrs = [], []
    for i, priv in enumerate(privs):
        n = Node(KVStoreApplication(), state.copy(), privval=FilePV(priv),
                 home=str(tmp_path / f"n{i}"), timeouts=FAST, p2p=True,
                 pex=True,
                 node_key=NodeKey(PrivKey.generate(bytes([0x70 + i]) * 32)))
        addrs.append(n.listen())
        nodes.append(n)
    for n in nodes:
        n.start()
    try:
        # seed topology: A-B and B-C only
        nodes[0].dial(addrs[1])
        nodes[2].dial(addrs[1])
        deadline = time.time() + 30
        while time.time() < deadline:
            if nodes[0].switch.num_peers() >= 2 and \
                    nodes[2].switch.num_peers() >= 2:
                break
            time.sleep(0.2)
        assert nodes[0].switch.num_peers() >= 2, \
            f"A peers: {nodes[0].switch.num_peers()}"
        # A's book learned C's address via PEX
        c_id = nodes[2].switch.node_key.node_id
        assert c_id in nodes[0].switch.peers
        # and the net still commits
        assert nodes[0].consensus.wait_for_height(3, timeout=60)
    finally:
        for n in nodes:
            n.stop()
