"""VerifyCommit family: differential tests (oracle vs XLA device path) and
reference-semantics cases (blame path, quorum math, trusting mode).

Mirrors types/validation_test.go's case structure.
"""
import numpy as np
import pytest

from cometbft_tpu.crypto.keys import PrivKey
from cometbft_tpu.types import canonical, validation
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote

CHAIN_ID = "test_chain"
HEIGHT = 10


def make_commit(n_vals=6, power=100, invalid=(), absent=(), nil=(),
                height=HEIGHT, round_=2):
    """Build a valset + commit with n_vals validators, each signing a real
    precommit; indices in `invalid` get corrupted sigs, `absent` no sig,
    `nil` a nil-vote."""
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n_vals)]
    vals = [Validator(p.pub_key(), power) for p in privs]
    vs = ValidatorSet(vals)
    # sort privs to match the sorted set
    addr_to_priv = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\xab" * 32, PartSetHeader(2, b"\xcd" * 32))
    sigs = []
    for idx, v in enumerate(vs.validators):
        p = addr_to_priv[v.address]
        if idx in absent:
            sigs.append(CommitSig.absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if idx in nil else BLOCK_ID_FLAG_COMMIT
        ts = Timestamp(1700000000 + idx, idx)
        vote_bid = BlockID() if idx in nil else bid
        sb = canonical.canonical_vote_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, height, round_, vote_bid, ts
        )
        sig = p.sign(sb)
        if idx in invalid:
            sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
        sigs.append(CommitSig(flag, v.address, ts, sig))
    return vs, Commit(height, round_, bid, sigs), bid


BATCH_FNS = [
    ("oracle", validation.oracle_batch_fn),
    ("device-xla", lambda: validation.device_batch_fn(use_pallas=False)),
]


@pytest.mark.parametrize("name,mk_fn", BATCH_FNS)
def test_verify_commit_all_good(name, mk_fn):
    vs, commit, bid = make_commit()
    validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn())
    validation.verify_commit_light(
        CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn()
    )
    validation.verify_commit_light_trusting(
        CHAIN_ID, vs, commit, (1, 3), mk_fn()
    )


@pytest.mark.parametrize("name,mk_fn", BATCH_FNS)
def test_verify_commit_blame_path(name, mk_fn):
    vs, commit, bid = make_commit(invalid=(3,))
    with pytest.raises(validation.InvalidSignatureError) as ei:
        validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn())
    assert ei.value.idx == 3


@pytest.mark.parametrize("name,mk_fn", BATCH_FNS)
def test_verify_commit_insufficient_power(name, mk_fn):
    # 3 of 6 absent -> exactly 50% < 2/3
    vs, commit, bid = make_commit(absent=(0, 1, 2))
    with pytest.raises(validation.NotEnoughPowerError):
        validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn())


@pytest.mark.parametrize("name,mk_fn", BATCH_FNS)
def test_nil_votes_not_counted_but_verified(name, mk_fn):
    # VerifyCommit (full): nil votes ARE verified but NOT counted.
    # 5 commit + 1 nil of 6 -> 5/6 > 2/3 passes (4/6 would be exactly
    # 2/3, which the strict > rejects)
    vs, commit, bid = make_commit(nil=(5,))
    validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn())
    # but an invalid nil-vote signature fails full verification
    vs2, commit2, bid2 = make_commit(nil=(5,), invalid=(5,))
    with pytest.raises(validation.InvalidSignatureError):
        validation.verify_commit(
            CHAIN_ID, vs2, bid2, HEIGHT, commit2, mk_fn()
        )
    # ...while light verification ignores non-commit sigs entirely
    validation.verify_commit_light(
        CHAIN_ID, vs2, bid2, HEIGHT, commit2, mk_fn()
    )


def test_verify_commit_wrong_height_block_id():
    vs, commit, bid = make_commit()
    with pytest.raises(validation.VerificationError):
        validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT + 1, commit)
    other = BlockID(b"\x11" * 32, PartSetHeader(2, b"\xcd" * 32))
    with pytest.raises(validation.VerificationError):
        validation.verify_commit(CHAIN_ID, vs, other, HEIGHT, commit)


def test_trusting_mode_by_address_subset():
    """Old set = subset of signers: lookups by address, 1/3 threshold."""
    vs, commit, bid = make_commit(n_vals=9)
    # old set = 4 of the 9 validators -> all 4 signed -> 4/4 > 1/3
    old = ValidatorSet(vs.validators[:4])
    validation.verify_commit_light_trusting(
        CHAIN_ID, old, commit, (1, 3), validation.oracle_batch_fn()
    )
    # trust 1/1 (100%): 4/4 power still passes only if > total*1//1...
    with pytest.raises(validation.NotEnoughPowerError):
        validation.verify_commit_light_trusting(
            CHAIN_ID, old, commit, (1, 1), validation.oracle_batch_fn()
        )


@pytest.mark.parametrize("name,mk_fn", BATCH_FNS)
def test_light_early_break_skips_trailing_invalid(name, mk_fn):
    """VerifyCommitLight stops collecting at 2/3 (validation.go:223-225):
    an invalid signature AFTER quorum is never examined — but full
    VerifyCommit (count_all) must reject it."""
    vs, commit, bid = make_commit(n_vals=6, invalid=(5,))
    validation.verify_commit_light(
        CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn()
    )  # quorum from sigs 0-4 (5/6); sig 5 never touched
    with pytest.raises(validation.InvalidSignatureError):
        validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, mk_fn())
    # and an invalid signature BEFORE quorum still fails light verify
    vs2, commit2, bid2 = make_commit(n_vals=6, invalid=(0,))
    with pytest.raises(validation.InvalidSignatureError):
        validation.verify_commit_light(
            CHAIN_ID, vs2, bid2, HEIGHT, commit2, mk_fn()
        )


def test_power_precheck_before_verification():
    """Underpowered commits fail on power BEFORE signatures are verified
    (validation.go:230-233) — even when signatures are also invalid."""
    vs, commit, bid = make_commit(absent=(0, 1, 2), invalid=(3,))
    calls = []

    def spy_fn(pubs, msgs, sigs):
        calls.append(len(pubs))
        return np.ones(len(pubs), bool)

    with pytest.raises(validation.NotEnoughPowerError):
        validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, spy_fn)
    assert calls == []  # batch_fn never invoked


def test_single_path_matches_batch():
    """No batch_fn -> single-verify loop; same outcomes."""
    vs, commit, bid = make_commit()
    validation.verify_commit(CHAIN_ID, vs, bid, HEIGHT, commit, None)
    vs2, commit2, _ = make_commit(invalid=(2,))
    with pytest.raises(validation.InvalidSignatureError) as ei:
        validation.verify_commit(CHAIN_ID, vs2, commit2.block_id, HEIGHT,
                                 commit2, None)
    assert ei.value.idx == 2


def test_vote_verify_roundtrip():
    priv = PrivKey.generate(b"\x07" * 32)
    bid = BlockID(b"\xab" * 32, PartSetHeader(1, b"\xcd" * 32))
    v = Vote(
        vote_type=canonical.PRECOMMIT_TYPE,
        height=3, round=0, block_id=bid,
        timestamp=Timestamp(1700000001, 42),
        validator_address=priv.pub_key().address(),
        validator_index=0,
    )
    v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
    v.verify(CHAIN_ID, priv.pub_key())
    v.validate_basic()
    other = PrivKey.generate(b"\x08" * 32)
    with pytest.raises(Exception):
        v.verify(CHAIN_ID, other.pub_key())
