"""Native hostaccel: differential tests against hashlib.

The C++ SHA-512 (cometbft_tpu/native/hostaccel.cpp) must agree with
OpenSSL byte-for-byte on every length class (empty, sub-block,
block-boundary, multi-block) — padding bugs live at the boundaries.
"""
import hashlib
import os
import random

import numpy as np
import pytest

from cometbft_tpu import native


@pytest.fixture(scope="module")
def have_native():
    if not native.available():
        pytest.skip("no g++ / native module unavailable "
                    "(fallback path is exercised elsewhere)")
    return True


def test_batch_sha512_differential(have_native):
    rng = random.Random(3)
    # boundary lengths around the 128-byte block and the 112-byte
    # padding threshold, plus random sizes
    lengths = [0, 1, 63, 64, 111, 112, 113, 127, 128, 129, 255, 256,
               1000] + [rng.randrange(0, 5000) for _ in range(40)]
    rows = [os.urandom(n) for n in lengths]
    out = native.batch_sha512(rows)
    for i, r in enumerate(rows):
        assert out[i].tobytes() == hashlib.sha512(r).digest(), \
            f"mismatch at len {len(r)}"


def test_ed25519_batch_digest_differential(have_native):
    rng = random.Random(9)
    n = 64
    r_raw = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
    a_raw = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
    msgs = [os.urandom(rng.randrange(0, 300)) for _ in range(n)]
    out = native.ed25519_batch_digest(r_raw, a_raw, msgs)
    for i in range(n):
        want = hashlib.sha512(
            r_raw[i].tobytes() + a_raw[i].tobytes() + msgs[i]
        ).digest()
        assert out[i].tobytes() == want


def test_pack_batch_uses_native_and_agrees(have_native):
    """pack_batch output must be identical native vs fallback."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_kernel as ek

    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(16)]
    msgs = [b"msg-%d" % i for i in range(16)]
    pubs = [p.pub_key().data for p in privs]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    pb1 = ek.pack_batch(pubs, msgs, sigs)

    real_load = native._load
    try:
        native._load = lambda: None  # force fallback
        pb2 = ek.pack_batch(pubs, msgs, sigs)
    finally:
        native._load = real_load
    for f in ("ay", "asign", "ry", "rsign", "sdig", "hdig", "precheck"):
        np.testing.assert_array_equal(getattr(pb1, f), getattr(pb2, f),
                                      err_msg=f)


def test_empty_rows(have_native):
    out = native.batch_sha512([b"", b""])
    assert out[0].tobytes() == hashlib.sha512(b"").digest()


L = 2**252 + 27742317777372353535851937790883648493


def test_reduce_mod_l_differential(have_native):
    """The 512->253-bit reduction vs Python bigints, incl. adversarial
    extremes (all-0xff, values just above/below multiples of L)."""
    rng = random.Random(17)
    cases = [b"\x00" * 64, b"\xff" * 64,
             (L - 1).to_bytes(64, "little"),
             L.to_bytes(64, "little"),
             (L + 1).to_bytes(64, "little"),
             (L * (2**259 // L)).to_bytes(64, "little")]
    cases += [rng.getrandbits(512).to_bytes(64, "little")
              for _ in range(200)]
    digs = np.frombuffer(b"".join(cases), np.uint8).reshape(-1, 64)
    out = native.batch_reduce_mod_l(digs)
    assert out is not None
    for i, c in enumerate(cases):
        want = int.from_bytes(c, "little") % L
        got = int.from_bytes(out[i].tobytes(), "little")
        assert got == want, f"case {i}: got {got}, want {want}"


def test_batch_challenge_matches_fallback(have_native):
    rng = random.Random(23)
    n = 32
    r_raw = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
    a_raw = np.frombuffer(os.urandom(32 * n), np.uint8).reshape(n, 32)
    msgs = [os.urandom(rng.randrange(0, 200)) for _ in range(n)]
    out = native.ed25519_batch_challenge(r_raw, a_raw, msgs)
    assert out is not None
    for i in range(n):
        d = hashlib.sha512(r_raw[i].tobytes() + a_raw[i].tobytes()
                           + msgs[i]).digest()
        want = int.from_bytes(d, "little") % L
        assert int.from_bytes(out[i].tobytes(), "little") == want


def test_pack_commits_matches_pack_batch(have_native):
    """The fused template+timestamp native pack must equal the
    msgs-list pipeline byte-for-byte (sign-bytes templating included)."""
    from cometbft_tpu.crypto.keys import PrivKey
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.timestamp import Timestamp

    rng = random.Random(41)
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(8)]
    templates, row_tmpl, row_secs, row_nanos = [], [], [], []
    pubs, sigs, msgs = [], [], []
    for c in range(3):  # three "commits" with distinct templates
        bid = BlockID(bytes([c]) * 32, PartSetHeader(1, bytes([c]) * 32))
        enc = canonical.CanonicalVoteEncoder(
            "pc-chain", canonical.PRECOMMIT_TYPE, 100 + c, c, bid)
        templates.append(enc.template)
        for r in range(20):
            # adversarial timestamps: zeros, negatives, huge values
            secs = rng.choice([0, 1, -1, 2**40, -(2**40),
                               rng.randrange(2**33)])
            nanos = rng.choice([0, 1, 999999999, rng.randrange(10**9)])
            ts = Timestamp(secs, nanos)
            sb = enc.bytes_for(ts)
            k = privs[r % 8]
            pubs.append(k.pub_key().data)
            sigs.append(k.sign(sb))
            msgs.append(sb)
            row_tmpl.append(c)
            row_secs.append(secs)
            row_nanos.append(nanos)
    pad = 64
    packed = native.ed25519_pack_commits(
        b"".join(pubs), b"".join(sigs), templates,
        np.asarray(row_tmpl, np.int32), np.asarray(row_secs, np.int64),
        np.asarray(row_nanos, np.int64), pad,
    )
    assert packed is not None
    want = ek.pack_batch(pubs, msgs, sigs, pad_to=pad)
    names = ("ay", "asign", "ry", "rsign", "sdig", "hdig", "precheck")
    for name, got in zip(names, packed):
        np.testing.assert_array_equal(got, getattr(want, name),
                                      err_msg=name)


def test_batch_keccak_f1600_differential(have_native):
    from cometbft_tpu.crypto.keccak import keccak_f1600_np

    rng = np.random.default_rng(7)
    states = rng.integers(0, 2**64, size=(33, 25), dtype=np.uint64)
    out = native.batch_keccak_f1600(states)
    assert out is not None
    np.testing.assert_array_equal(out, keccak_f1600_np(states.copy()))
    # and the all-zero state (SHA-3 theta/iota sanity)
    z = np.zeros((1, 25), np.uint64)
    np.testing.assert_array_equal(
        native.batch_keccak_f1600(z), keccak_f1600_np(z.copy())
    )


def test_native_sr25519_challenges_match_batchstrobe():
    """The C transcript walker is byte-identical to the numpy
    BatchStrobe route AND the scalar reference transcripts, across
    message lengths (incl. rate-crossing >166-byte messages)."""
    import numpy as np

    from cometbft_tpu import native
    from cometbft_tpu.crypto import merlin
    from cometbft_tpu.crypto import sr25519_ref as sr

    if not native.available():
        import pytest

        pytest.skip("no native toolchain")
    rng = np.random.default_rng(3)
    for ln in (1, 32, 110, 166, 167, 400):
        n = 17
        msgs = rng.integers(0, 256, (n, ln), dtype=np.uint8)
        pks = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        rs = rng.integers(0, 256, (n, 32), dtype=np.uint8)
        prefix = sr._signing_prefix()
        s = prefix.strobe
        got = native.sr25519_batch_challenges(
            bytes(s.st), s.pos, s.pos_begin, s.cur_flags, msgs, pks, rs)
        # numpy batch route
        bt = merlin.BatchTranscript(n, prefix)
        bt.append_message_batch(b"sign-bytes", msgs)
        bt.append_message_shared(b"proto-name", b"Schnorr-sig")
        bt.append_message_batch(b"sign:pk", pks)
        bt.append_message_batch(b"sign:R", rs)
        exp = bt.challenge_bytes_batch(b"sign:c", 64)
        np.testing.assert_array_equal(got, exp)
        # scalar reference for row 0
        t = prefix.clone()
        t.append_message(b"sign-bytes", msgs[0].tobytes())
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pks[0].tobytes())
        t.append_message(b"sign:R", rs[0].tobytes())
        assert t.challenge_bytes(b"sign:c", 64) == got[0].tobytes()
