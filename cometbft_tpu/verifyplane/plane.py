"""The verify plane: a continuous-batching scheduler for the device.

Before this subsystem, only bulk callers (blocksync StreamVerifier,
commit verification) reached the device in batches; each gossiped vote
and each vote-extension signature still single-verified serially on the
host — exactly the hot path under consensus load. EdDSA committee-
consensus measurements (arXiv:2302.00418) put the win in batch
verification, and FPGA verification engines for permissioned chains
(arXiv:2112.02229) use the same shape: one shared hardware queue that
coalesces independent requests into a single device pass.

Architecture (inference-style continuous batching):

  callers ──submit(pub,msg,sig[,power,group])──► pending queue
                                                    │
                 dispatcher thread: flush when the oldest submission is
                 window_ms old OR max_batch rows are pending
                                                    │
                                    one padded bucket-shaped pass
                         (device kernels under the CircuitBreaker, or
                          the inline host ed25519_ref path when the
                          breaker is open / no accelerator exists)
                                                    │
              per-item verdict futures  +  per-group power tallies
              (a QuorumGroup's quorum event fires inside the flush —
               VoteSet learns "2/3 reached" directly from the plane)

Knobs ([verify_plane] config): window_ms bounds added latency,
max_batch bounds device batch size (bucket padding reuses the compiled
kernel shapes from ops/), max_queue bounds memory and provides
backpressure — a full queue blocks submitters (or raises PlaneQueueFull
for non-blocking callers, who then verify inline on the host).

Failure injection: the `verifyplane.dispatch` failpoint fires at the
top of every flush; a raised fault must degrade that flush to the
inline host path — futures always resolve, submitters never hang.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing

_log = logging.getLogger(__name__)

fp.register("verifyplane.dispatch",
            "top of a verify-plane flush (raise = dispatch fault; the "
            "flush must degrade to the inline host path, futures must "
            "still resolve)")

DISPATCH_LOG_MAX = 64       # flush-composition ring kept for tests/ops

# Process-global flush ids: flight b/e trace events pair by (name, cat,
# id), so two planes alive in one process (multi-node tests, simnet)
# must never reuse an id — perfetto and trace_report would pair plane
# A's begin with plane B's end. next() on itertools.count is atomic.
_FLUSH_IDS = itertools.count()
DEFAULT_RESULT_TIMEOUT = 30.0
# stop()-time leftover drain budget: rows host-verified synchronously
# before remaining futures fail fast (a few seconds worst-case on the
# pure-Python path, not minutes)
STOP_DRAIN_MAX_ROWS = 2048


class PlaneError(Exception):
    """Base for plane-side failures; callers fall back to host verify."""


class PlaneQueueFull(PlaneError):
    """Backpressure: the pending queue is at max_queue."""


class PlaneStopped(PlaneError):
    """Submitted to a plane that is not running."""


class VerifyFuture:
    """Resolves to a tuple of per-item bool verdicts (one submission may
    carry several signatures, e.g. a vote + its extension)."""

    __slots__ = ("_ev", "_verdicts", "_err")

    def __init__(self):
        self._ev = threading.Event()
        self._verdicts: Optional[Tuple[bool, ...]] = None
        self._err: Optional[BaseException] = None

    def _resolve(self, verdicts: Sequence[bool]) -> None:
        self._verdicts = tuple(bool(v) for v in verdicts)
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Tuple[bool, ...]:
        if not self._ev.wait(DEFAULT_RESULT_TIMEOUT
                             if timeout is None else timeout):
            raise PlaneError("verify plane result timed out")
        if self._err is not None:
            raise PlaneError(str(self._err)) from self._err
        return self._verdicts


class QuorumGroup:
    """A fused voting-power tally target.

    Counted submissions tagged with a group add their power to the
    group's tally inside the dispatch pass (all signatures of the
    submission must verify). The quorum event fires the moment the
    tally crosses the threshold — the caller (VoteSet) learns quorum
    from the plane instead of re-tallying verdicts itself."""

    def __init__(self, threshold: int, name: str = "",
                 valset_pubs: Optional[tuple] = None,
                 valset_powers: Optional[tuple] = None):
        self.threshold = int(threshold)
        self.name = name
        # optional valset backing (pubkey bytes + powers, index-aligned):
        # lets the device flush reuse the cached window table and fuse
        # this group's tally into the verify kernel (fused.try_fused)
        self.valset_pubs = valset_pubs
        self.valset_powers = valset_powers
        self._lock = threading.Lock()
        self._tally = 0
        self._quorum = threading.Event()

    @property
    def tally(self) -> int:
        with self._lock:
            return self._tally

    @property
    def quorum_reached(self) -> bool:
        return self._quorum.is_set()

    def wait_quorum(self, timeout: Optional[float] = None) -> bool:
        return self._quorum.wait(timeout)

    def add(self, power: int) -> bool:
        """Add verified power; returns True when this add crossed the
        threshold."""
        with self._lock:
            old = self._tally
            self._tally += int(power)
            crossed = old < self.threshold <= self._tally
        if crossed:
            self._quorum.set()
        return crossed

    def retract(self, power: int) -> None:
        """Undo a tallied contribution (the caller's admission step
        found the vote inadmissible after all — duplicate race or
        equivocation). A retraction that drops the tally back below
        the threshold also clears the quorum event: the crossing was
        a transient double-count, not a real 2/3 (maj23 itself only
        flips on a genuine bv.sum crossing, so consensus never acted
        on the phantom signal)."""
        with self._lock:
            self._tally -= int(power)
            if self._tally < self.threshold:
                self._quorum.clear()


class _Submission:
    __slots__ = ("rows", "future", "group", "power", "counted",
                 "vidx", "t_submit", "t_submit_trace", "tid")

    def __init__(self, rows, group, power, counted, vidx=None):
        self.rows = rows                      # [(PubKey, msg, sig), ...]
        self.future = VerifyFuture()
        self.group = group
        self.power = int(power)
        self.counted = bool(counted)
        self.vidx = tuple(vidx) if vidx is not None else None
        self.t_submit = time.perf_counter()
        # trace-clock stamp for the pack span's queued_ms: rides the
        # TRACE clock (virtual under simnet) so traces of the same
        # (seed, schedule) stay byte-identical; None when tracing off
        self.t_submit_trace = tracing.clock_ns()
        self.tid = threading.get_ident()


def _host_verdicts(rows) -> List[bool]:
    """Inline host path: per-row single verify via the reference-path
    PubKey.verify_signature (ed25519_ref and friends)."""
    out = []
    for pub, msg, sig in rows:
        try:
            out.append(bool(pub.verify_signature(msg, sig)))
        except ValueError:
            out.append(False)
    return out


class VerifyPlane:
    """Always-on background scheduler turning the device into a shared
    verification service. Start/stop with the node lifecycle."""

    def __init__(self, window_ms: float = 1.5, max_batch: int = 1024,
                 max_queue: int = 8192, metrics=None,
                 kernels: Optional[dict] = None, breaker=None,
                 use_device: Optional[bool] = None):
        from cometbft_tpu.crypto import batch as cbatch
        from cometbft_tpu.libs.staging import StagingPool

        self.window = max(0.0, window_ms) / 1000.0
        self.max_batch = max(1, int(max_batch))
        self.max_queue = max(1, int(max_queue))
        self.metrics = metrics
        self._kernels = kernels
        self._breaker = breaker if breaker is not None \
            else cbatch.device_breaker()
        # device dispatch only when a kernel set was injected (tests) or
        # an accelerator actually exists — the XLA/interpret kernels on
        # CPU cost minutes of compile, so the CPU plane coalesces and
        # verifies on the inline host path instead
        self._use_device = (use_device if use_device is not None
                            else kernels is not None
                            or cbatch._accel_backend())
        self._cv = threading.Condition()
        self._pending: deque = deque()
        self._pending_rows = 0
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # observability (also mirrored into NodeMetrics when attached)
        self.dispatch_log: deque = deque(maxlen=DISPATCH_LOG_MAX)
        self.batches = 0
        self.rows_verified = 0
        self.padding_waste = 0
        self.pack_seconds = 0.0   # host staging time (template pack etc.)
        self.h2d_bytes = 0        # bytes staged to the device
        self.overlapped = 0       # flushes packed while another flew
        # PRIVATE staging pool: the rotation contract (one writer per
        # key) only holds per dispatcher thread — two planes in one
        # process (multi-node tests, simnet) must never share slots
        self._staging = StagingPool(slots=2)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="verify-plane", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            if not self._running:
                return
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # resolve anything the dispatcher didn't drain (dispatcher died,
        # or the join timed out mid-flush) so no submitter ever hangs on
        # a stopped plane — and resolve with REAL verdicts via the inline
        # host path, not an error: callers that already passed submit()
        # successfully treat the future as authoritative. The host pass
        # is BUDGETED (pure-Python ed25519 costs ms/row on wheel-less
        # hosts): past the budget, remaining futures fail fast with
        # PlaneStopped rather than pinning shutdown for minutes.
        leftovers = []
        with self._cv:
            while self._pending:
                leftovers.append(self._pending.popleft())
            self._pending_rows = 0
        budget = STOP_DRAIN_MAX_ROWS
        settle, fail = [], []
        for sub in leftovers:
            if budget >= len(sub.rows):
                budget -= len(sub.rows)
                settle.append(sub)
            else:
                fail.append(sub)
        if settle:
            rows = [r for sub in settle for r in sub.rows]
            self._settle(settle, _host_verdicts(rows))
        for sub in fail:
            sub.future._fail(PlaneStopped(
                "verify plane stopped with queue over the drain budget"
            ))

    def is_running(self) -> bool:
        return self._running

    def in_dispatcher(self) -> bool:
        """True on the dispatcher thread (recursion guard: the
        dispatcher's own verify calls must not re-enter the plane)."""
        return threading.current_thread() is self._thread

    # -- submission --------------------------------------------------------

    def submit(self, pub, msg: bytes, sig: bytes, power: int = 0,
               group: Optional[QuorumGroup] = None, counted: bool = False,
               vidx: Optional[int] = None,
               block: bool = True) -> VerifyFuture:
        """Submit one (pubkey, msg, sig); the future resolves to a
        1-tuple verdict."""
        return self.submit_many(
            [(pub, msg, sig)], power=power, group=group, counted=counted,
            vidx=None if vidx is None else (vidx,), block=block,
        )

    def submit_many(self, rows, power: int = 0,
                    group: Optional[QuorumGroup] = None,
                    counted: bool = False,
                    vidx: Optional[Sequence[int]] = None,
                    block: bool = True) -> VerifyFuture:
        """Submit several signatures as ONE unit (e.g. a vote and its
        extension): one future, per-row verdicts, and — when counted —
        the group tally credits `power` only if EVERY row verifies.
        vidx (one validator index per row) enables the fused cached-
        table device path for valset-backed groups; row 0 must be the
        power-bearing signature (the vote; extensions follow)."""
        rows = list(rows)
        if not rows:
            raise ValueError("empty submission")
        if not self._running or self.in_dispatcher():
            raise PlaneStopped("verify plane not accepting submissions")
        sub = _Submission(rows, group, power, counted, vidx)
        deadline = time.monotonic() + DEFAULT_RESULT_TIMEOUT
        with self._cv:
            # backpressure gates on what is already queued — a lone
            # submission larger than max_queue still enters an empty
            # queue (it dispatches alone) instead of deadlocking
            while self._running and self._pending_rows and \
                    self._pending_rows + len(rows) > self.max_queue:
                if not block:
                    raise PlaneQueueFull(
                        f"verify plane queue full ({self.max_queue} rows)"
                    )
                if not self._cv.wait(timeout=deadline - time.monotonic()) \
                        and time.monotonic() >= deadline:
                    raise PlaneQueueFull(
                        "verify plane backpressure wait timed out"
                    )
            if not self._running:
                raise PlaneStopped("verify plane stopped")
            self._pending.append(sub)
            self._pending_rows += len(rows)
            if self.metrics is not None:
                self.metrics.plane_queue_depth.set(self._pending_rows)
            self._cv.notify_all()
        if tracing.enabled():
            tracing.instant("plane.submit", cat="verifyplane",
                            rows=len(rows), depth=self._pending_rows)
        return sub.future

    def submit_and_wait(self, pubs, msgs, sigs,
                        timeout: Optional[float] = None) -> np.ndarray:
        """crypto.batch.verify_batch shape: (n,) bool validity through
        the plane (one submission, one flush slot)."""
        fut = self.submit_many(list(zip(pubs, msgs, sigs)))
        if timeout is None:
            # scale with batch size: a 10k-row host-path flush on a
            # 1-core box legitimately outlives the default window
            timeout = max(DEFAULT_RESULT_TIMEOUT, 0.05 * len(pubs))
        return np.asarray(fut.result(timeout), np.bool_)

    # -- dispatcher --------------------------------------------------------

    def _run(self) -> None:
        """Double-buffered dispatch loop: while flush k flies on the
        device, the dispatcher drains and PACKS flush k+1 into the
        rotated staging buffers (libs/staging.py), settling k only
        after k+1's dispatch is in flight — the blocksync pipeline's
        overlap (pipeline.py "host packs chunk k+1 while the device
        works"), generalized to every caller of the plane. With a
        flush already in flight the window wait is skipped: the
        in-flight pass IS the coalescing amortization the window
        exists to provide."""
        inflight = None  # airborne (batch, finish, True, flush_id)
        while True:
            batch: List[_Submission] = []
            with self._cv:
                while self._running:
                    if self._pending:
                        age = time.perf_counter() - \
                            self._pending[0].t_submit
                        if (inflight is not None
                                or age >= self.window
                                or self._pending_rows >= self.max_batch):
                            break
                        self._cv.wait(timeout=self.window - age)
                    elif inflight is not None:
                        break  # nothing to pack: settle the flight now
                    else:
                        self._cv.wait(timeout=0.25)
                if not self._running and not self._pending:
                    break
                # drain whole submissions up to max_batch rows (a lone
                # oversized submission still dispatches alone)
                rows = 0
                while self._pending:
                    nxt = len(self._pending[0].rows)
                    if batch and rows + nxt > self.max_batch:
                        break
                    sub = self._pending.popleft()
                    rows += nxt
                    batch.append(sub)
                self._pending_rows -= rows
                if self.metrics is not None:
                    self.metrics.plane_queue_depth.set(self._pending_rows)
                self._cv.notify_all()  # wake backpressured submitters
            flight = self._stage(batch) if batch else None
            if inflight is not None:
                # real overlap only: the previous flight was airborne on
                # the device while this flush packed on the host
                if flight is not None:
                    self.overlapped += 1
                self._finish_flight(inflight)
                inflight = None
            if flight is not None:
                if flight[2]:
                    inflight = flight  # device pass in flight: defer
                else:
                    # synchronous flush (host path / grouped device):
                    # verdicts are already final — settle NOW, deferring
                    # would add a whole flush of latency for no overlap
                    self._finish_flight(flight)
        if inflight is not None:
            self._finish_flight(inflight)

    def _finish_flight(self, flight) -> None:
        batch, finish, airborne, fid = flight
        if airborne:
            with tracing.span("plane.collect", cat="verifyplane",
                              flush=fid):
                verdicts, fused_tallies = finish()
            tracing.flight_end("plane.flight", fid, cat="verifyplane")
        else:
            # synchronous flush: the deferred host/grouped verification
            # happens here, attributed to its own stage
            with tracing.span("plane.verify", cat="verifyplane",
                              flush=fid):
                verdicts, fused_tallies = finish()
        with tracing.span("plane.settle", cat="verifyplane", flush=fid):
            self._settle(batch, verdicts, fused_tallies=fused_tallies)

    def _observe_pack(self, seconds: float, h2d_bytes: int = 0) -> None:
        self.pack_seconds += seconds
        self.h2d_bytes += h2d_bytes
        if self.metrics is not None:
            self.metrics.plane_pack_seconds.observe(seconds)
            if h2d_bytes:
                self.metrics.plane_h2d_bytes.inc(h2d_bytes)

    def _stage(self, batch: List[_Submission]):
        """Pack one flush and (when eligible) launch it on the device
        WITHOUT waiting for results. Returns (batch, finish, airborne,
        flush_id) where finish() blocks for the verdicts — the seam
        that lets the dispatcher pack the next flush while this one
        flies. The whole host-side staging is one "plane.pack" trace
        span keyed by flush id, so pack(k+1) visibly overlaps
        device-flight(k) in the exported timeline."""
        fid = next(_FLUSH_IDS)
        if not tracing.enabled():
            # disabled fast path: no O(batch) span-arg computation on
            # the dispatcher hot path
            batch, finish, airborne = self._stage_inner(batch, fid)
            return batch, finish, airborne, fid
        now_ns = tracing.clock_ns()
        stamps = [s.t_submit_trace for s in batch
                  if s.t_submit_trace is not None]
        args = {"flush": fid, "rows": sum(len(s.rows) for s in batch),
                "subs": len(batch)}
        if stamps and now_ns is not None:
            args["queued_ms"] = round((now_ns - min(stamps)) / 1e6, 3)
        with tracing.span("plane.pack", cat="verifyplane", **args):
            batch, finish, airborne = self._stage_inner(batch, fid)
        return batch, finish, airborne, fid

    def _stage_inner(self, batch: List[_Submission], fid: int):
        """The breaker's allow() — which consumes the single half-open
        probe slot when the breaker is open — is only asked once a
        fused plan exists, i.e. when a device attempt will actually
        happen; an ineligible flush must not burn the probe the
        generic path needs to recover."""
        rows = [r for sub in batch for r in sub.rows]
        t0 = time.perf_counter()
        try:
            fp.fail_point("verifyplane.dispatch")
        except Exception:  # noqa: BLE001 - dispatch fault, not verdicts
            _log.exception(
                "verify plane dispatch fault (%d rows); degrading this "
                "flush to the inline host path", len(rows),
            )
            # verdict work is deferred into finish() so the pack span
            # measures staging only (the finish runs immediately for
            # synchronous flushes — same thread, same ordering)
            return batch, (lambda: (_host_verdicts(rows), None)), False
        plan = None
        if self._use_device and self._kernels is None:
            from cometbft_tpu.verifyplane import fused as fz

            try:
                plan = fz.plan_fused(batch, pool=self._staging)
            except Exception:  # noqa: BLE001 - staging bug, not device
                _log.exception("fused flush staging failed; grouped path")
                plan = None
            if plan is not None and not self._breaker.allow():
                plan = None
        if plan is not None:
            try:
                # [tracing] profile_dir: bracket the device flight with
                # a jax.profiler capture so device traces line up with
                # the host spans (no-op unless configured)
                prof = tracing.profiler_stop if tracing.profiler_start() \
                    else None
                fz.dispatch_fused(plan)
                tracing.flight_begin("plane.flight", fid,
                                     cat="verifyplane", rows=len(rows))
                self._observe_pack(time.perf_counter() - t0,
                                   fz.plan_h2d_bytes(plan))

                def finish():
                    try:
                        out = fz.collect_fused(plan)
                    except Exception:  # noqa: BLE001 - device fault
                        self._breaker.record_failure()
                        _log.exception(
                            "fused verify-plane flush failed in flight; "
                            "host fallback for this flush"
                        )
                        return _host_verdicts(rows), None
                    finally:
                        if prof is not None:
                            prof()
                    self._breaker.record_success()
                    return out

                return batch, finish, True
            except Exception:  # noqa: BLE001 - device fault at dispatch
                if prof is not None:
                    prof()  # un-bracket a failed dispatch
                self._breaker.record_failure()
                _log.exception(
                    "fused verify-plane dispatch failed; falling back "
                    "to the grouped path"
                )
        self._observe_pack(time.perf_counter() - t0)
        # deferred like the failpoint arm: pack_seconds (and the
        # plane.pack span) cover staging; the host/grouped verify runs
        # inside finish() under its own plane.verify span
        return batch, (lambda: (self._verify_rows(rows), None)), False

    def _verify_rows(self, rows) -> List[bool]:
        """One padded device pass under the circuit breaker, or the
        inline host path when no accelerator exists. verify_batch_direct
        itself degrades to the host path when the breaker is open or the
        device faults mid-flush."""
        if not self._use_device:
            return _host_verdicts(rows)
        from cometbft_tpu.crypto import batch as cbatch
        from cometbft_tpu.ops import ed25519_kernel as ek

        n = len(rows)
        try:
            waste = ek.bucket_size(n) - n
        except ValueError:
            waste = 0
        self.padding_waste += waste
        if self.metrics is not None:
            self.metrics.plane_padding_waste.inc(waste)
        pubs = [r[0] for r in rows]
        msgs = [r[1] for r in rows]
        sigs = [r[2] for r in rows]
        valid = cbatch.verify_batch_direct(
            pubs, msgs, sigs, kernels=self._kernels, breaker=self._breaker
        )
        return [bool(v) for v in np.asarray(valid)[:n]]

    def _settle(self, batch: List[_Submission], verdicts,
                fused_tallies=None) -> None:
        """Scatter verdicts to futures + fuse the per-group tallies —
        one pass over the flush, so a VoteSet's quorum event fires
        before any submitter even wakes. With fused_tallies (the device
        pass computed the per-group sums) the host adds those instead
        of re-reducing verdicts."""
        now = time.perf_counter()
        if fused_tallies is not None:
            for g, t in fused_tallies.items():
                if t:
                    g.add(t)
        off = 0
        tids = set()
        for sub in batch:
            sl = verdicts[off:off + len(sub.rows)]
            off += len(sub.rows)
            tids.add(sub.tid)
            if fused_tallies is None and sub.counted \
                    and sub.group is not None and all(sl):
                sub.group.add(sub.power)
            if self.metrics is not None:
                self.metrics.plane_wait_seconds.observe(now - sub.t_submit)
            sub.future._resolve(sl)
        self.batches += 1
        self.rows_verified += off
        if self.metrics is not None:
            self.metrics.plane_batch_size.observe(off)
            # breaker_open is sampled at scrape time by
            # NodeMetrics.expose_text (it must stay fresh with the
            # plane idle too), so no push here
        self.dispatch_log.append({
            "rows": off,
            "submissions": len(batch),
            "tids": tids,
        })

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            depth = self._pending_rows
        return {
            "running": self._running,
            "queue_depth": depth,
            "batches": self.batches,
            "rows_verified": self.rows_verified,
            "padding_waste": self.padding_waste,
            "breaker_state": self._breaker.state,
            "use_device": self._use_device,
            "pack_seconds": self.pack_seconds,
            "h2d_bytes": self.h2d_bytes,
            "overlapped": self.overlapped,
        }


# --------------------------------------------------------------------------
# the process-global plane (node lifecycle owns it)
# --------------------------------------------------------------------------

_GLOBAL: Optional[VerifyPlane] = None
_GLOBAL_LOCK = threading.Lock()


def set_global_plane(plane: Optional[VerifyPlane]) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = plane


def clear_global_plane(plane: VerifyPlane) -> None:
    """Unregister `plane` if (and only if) it is the current global —
    a stopping node must not tear down another node's plane."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is plane:
            _GLOBAL = None


def global_plane() -> Optional[VerifyPlane]:
    """The running global plane, or None. Returns None on the plane's
    own dispatcher thread (callers there must verify directly)."""
    p = _GLOBAL
    if p is None or not p.is_running() or p.in_dispatcher():
        return None
    return p


def plane_batch_fn() -> Optional[Callable]:
    """A batch_fn(pubs, msgs, sigs) -> (n,) bool routed through the
    running global plane, or None when no plane is running — callers
    keep their existing direct path in that case."""
    if global_plane() is None:
        return None

    def fn(pubs, msgs, sigs):
        p = global_plane()
        if p is not None:
            try:
                return p.submit_and_wait(pubs, msgs, sigs)
            except PlaneError:
                pass  # stopped/overflowed mid-call: verify directly
        from cometbft_tpu.crypto import batch as cbatch

        return cbatch.verify_batch_direct(pubs, msgs, sigs)

    return fn
