"""The verify plane: a continuous-batching scheduler for the device.

Before this subsystem, only bulk callers (blocksync StreamVerifier,
commit verification) reached the device in batches; each gossiped vote
and each vote-extension signature still single-verified serially on the
host — exactly the hot path under consensus load. EdDSA committee-
consensus measurements (arXiv:2302.00418) put the win in batch
verification, and FPGA verification engines for permissioned chains
(arXiv:2112.02229) use the same shape: one shared hardware queue that
coalesces independent requests into a single device pass.

Architecture (inference-style continuous batching):

  callers ──submit(pub,msg,sig[,power,group])──► pending queue
                                                    │
                 dispatcher thread: flush when the oldest submission is
                 window_ms old OR max_batch rows are pending
                                                    │
                                    one padded bucket-shaped pass
                         (device kernels under the CircuitBreaker, or
                          the inline host ed25519_ref path when the
                          breaker is open / no accelerator exists)
                                                    │
              per-item verdict futures  +  per-group power tallies
              (a QuorumGroup's quorum event fires inside the flush —
               VoteSet learns "2/3 reached" directly from the plane)

Knobs ([verify_plane] config): window_ms bounds added latency,
max_batch bounds device batch size (bucket padding reuses the compiled
kernel shapes from ops/), max_queue bounds memory and provides
backpressure — a full queue blocks submitters (or raises PlaneQueueFull
for non-blocking callers, who then verify inline on the host). The
mesh knobs (mesh / mesh_devices / mesh_min_rows) shard eligible fused
flushes across the local device mesh: per-shard device-resident valset
tables, tally psum-reduced on device, quorum still a kernel output —
one cross-chip pass for commits past a single chip's valset ceiling
(fused.py "Multichip").

Flight deck (pipeline_flights > 1): the dispatcher keeps up to K
flushes airborne at once instead of a single in-flight slot. With a
>=4-device mesh the flush mesh splits into two DISJOINT halves
(fused.half_meshes) and alternating flushes fly on alternating halves
— while flush k verifies on one half, flush k+1 packs on the host AND
dispatches on the other half, so no chip idles between collect(k) and
dispatch(k+1). Landing is out-of-order (fused.plan_ready probes
readiness; flight k+1 finishing first never blocks behind k), and the
size-aware policy in fused.plan_fused sends a flush past one half's
budget (or the half_mesh_rows knob) to the full mesh after draining
the deck. The private staging pool is flights+1 deep per shape so
pack(k+2) never waits on a buffer still pinned under flight k.

QoS lanes (overload resilience): every submission rides one of three
priority classes.  CONSENSUS (the default: gossiped votes, commits,
the node's own light-client headers) owns the flush window — its
oldest submission's age is what triggers a flush, and its rows drain
first.  GATEWAY (the light-client gateway's header verifies on behalf
of RPC clients — cometbft_tpu.lightgate) drains after CONSENSUS and
ahead of BULK: client-serving traffic must never delay the node's own
liveness, but it outranks mempool throughput.  BULK (today mempool
CheckTx; blocksync backfill keeps its own pinned pipeline and does not
ride the plane) fills whatever capacity a flush has left.  Each
non-consensus lane gets a small guaranteed anti-starvation quantum and
coalesces under its own longer window when no higher-priority traffic
is pending.  GATEWAY and BULK queues are separately bounded and
deadline-aware: a submission that cannot be served before its lane
deadline is SHED with an explicit PlaneOverloaded verdict (never a
silent drop) carrying a retry-after hint, so a CheckTx flood — or a
thundering herd of light clients — degrades into fast, honest
rejections instead of an unbounded queue that starves vote
verification.  CONSENSUS submissions are never shed.

Failure injection: the `verifyplane.dispatch` failpoint fires at the
top of every flush; a raised fault must degrade that flush to the
inline host path — futures always resolve, submitters never hang.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from cometbft_tpu.libs import controller as controlplane
from cometbft_tpu.libs import deviceledger
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing

_log = logging.getLogger(__name__)

fp.register("verifyplane.dispatch",
            "top of a verify-plane flush (raise = dispatch fault; the "
            "flush must degrade to the inline host path, futures must "
            "still resolve)")

DISPATCH_LOG_MAX = 64       # flush-composition ring kept for tests/ops

# -- QoS lanes --------------------------------------------------------------
# CONSENSUS: liveness-critical verification (votes, commits, the node's
# own light headers) — owns the flush window, drains first, never shed.
# GATEWAY: light-client-gateway header verifies on behalf of RPC
# clients (cometbft_tpu.lightgate) — drains after CONSENSUS, ahead of
# BULK; separately bounded, shed past its deadline.
# BULK: throughput traffic (today: mempool CheckTx) — fills leftover
# flush capacity, separately bounded, shed past its deadline.
LANE_CONSENSUS = "consensus"
LANE_GATEWAY = "gateway"
LANE_BULK = "bulk"
LANES = (LANE_CONSENSUS, LANE_GATEWAY, LANE_BULK)
# lanes that may be answered with an explicit Overloaded shed verdict
# (CONSENSUS is never shed by construction)
SHEDDABLE_LANES = (LANE_GATEWAY, LANE_BULK)
# the tenant submissions fall to when no chain_id is given — a
# single-chain node never needs to know the tenancy layer exists
# (verifyplane/tenants.py owns the registry; the constant lives here
# so the hot submit path and the registry share one spelling without
# a circular import)
DEFAULT_TENANT = "default"
# anti-starvation: even a flush filled to max_batch with CONSENSUS rows
# carries up to max_batch // BULK_QUANTUM_DIV extra rows PER lower
# lane, so a sustained consensus storm degrades GATEWAY/BULK to a
# guaranteed slice of capacity instead of zero (weighted priority, not
# absolute)
BULK_QUANTUM_DIV = 8
LANE_WAIT_WINDOW = 4096     # per-lane submit-to-result samples kept

# Process-global flush ids: flight b/e trace events pair by (name, cat,
# id), so two planes alive in one process (multi-node tests, simnet)
# must never reuse an id — perfetto and trace_report would pair plane
# A's begin with plane B's end. next() on itertools.count is atomic.
_FLUSH_IDS = itertools.count()

# -- flush ledger ----------------------------------------------------------
# The trace plane (PR 5) can reconstruct one run in full detail, but it
# is OFF by default — so the r05-style question "what did the last few
# hundred flushes actually cost" had no answer on a production node.
# The ledger is the always-on counterpart: one compact tuple per flush
# in a bounded ring, cheap enough to never turn off. The ring slot is
# the only per-flush allocation; every stamp rides
# tracing.monotonic_ns(), which the simnet swaps for its virtual clock
# — same (seed, schedule) => identical ledger.

LEDGER_CAPACITY = 256

# flush dispatch paths (interned module constants — the ledger must not
# build strings per flush)
PATH_FUSED = "fused"                # cached-table device pass, airborne
PATH_FUSED_SHARDED = "fused_sharded"  # cross-chip mesh pass, airborne
PATH_GROUPED = "grouped"            # generic device pass (sync)
PATH_HOST = "host"                  # no accelerator: inline host verify
PATH_FAILPOINT = "failpoint_host"   # dispatch failpoint degraded flush
PATH_FUSED_FALLBACK = "fused_host_fallback"  # in-flight device fault
PATH_STOP_DRAIN = "stop_drain"      # settled by stop()'s drain budget
PATH_SHED_ONLY = "shed_only"        # drain cycle that only shed (no flush)

# row-assembly attribution for the fused paths (the ledger's `stamp`
# column): device = the stamping prologue expanded per-row deltas next
# to a resident template (ISSUE 19); host = full rows packed host-side
# (the legacy path, still bit-live as the differential oracle and the
# fallback for non-template-eligible flushes). Non-fused paths record
# STAMP_HOST — their rows are host-assembled by definition.
STAMP_DEVICE = "device"
STAMP_HOST = "host"

# per-flush tenant split rule (the ledger's ``split`` column): how the
# flush's device-time columns (comp_ms/h2d_ms/dev_ms/delta_bytes) were
# charged to its ``tenants`` — "exact" when one tenant owned every row
# (the sub-flush boundary case: the fair-share drain's per-tenant row
# slices make the charge exact by construction), "rows" when a fused
# batch coalesced several tenants and the charge is row-proportional
# (the only defensible split inside ONE device pass). Recorded per
# flush so an operator reading /dump_tenants device columns knows
# which rule produced each number.
SPLIT_EXACT = "exact"
SPLIT_ROWS = "rows"

# Record-field indices. A flush's record is ONE list allocated at stage
# time in FIELDS order (plus two trailing internal ns stamps the readers
# never see); the dispatcher mutates it in place as stages land and the
# very same list becomes the ring slot — "no allocation per flush beyond
# the ring slot" is literal, not approximate.
(_L_SEQ, _L_TS, _L_ROWS, _L_SUBS, _L_QUEUED, _L_PACK, _L_FLIGHT,
 _L_COLLECT, _L_SETTLE, _L_AIR, _L_PATH, _L_STAMP, _L_BRK, _L_SMISS,
 _L_DEPTH, _L_CROWS, _L_GROWS, _L_BROWS, _L_SHED, _L_NDEV,
 _L_NHOST, _L_DEV0, _L_WARM, _L_COMP, _L_H2D, _L_DBYTES, _L_DEV,
 _L_UTIL, _L_TEN, _L_SPLIT) = range(30)
# internal slots past the FIELDS window: ns stamps + the clock
# generation they were taken under + the first-ready probe stamp
# (readers never see these)
_L_T0NS, _L_TPACKED, _L_GEN, _L_READY = 30, 31, 32, 33


def ms_to_us(ms) -> int:
    """Ledger-ms (rounded to 3 decimals) -> exact integer microseconds.

    The per-tenant device accounting and its conservation cross-check
    (tenants.reconcile_device) run on INTEGER microseconds so the
    exact-accounting contract holds with no float tolerance band — a
    3-decimal ms value is a whole number of us by construction."""
    return int(round(float(ms) * 1000.0))


def split_device_columns(tenants: tuple, rows: int, comp_ms, h2d_ms,
                         dev_ms, delta_bytes: int):
    """Split one flush's device-time columns across its tenant pairs.

    Returns (rule, [(chain, comp_us, h2d_us, dev_us, delta_bytes)]):
    one tenant (or an empty/rowless flush) is charged EXACTLY; a fused
    multi-tenant batch splits row-proportionally with the LAST tenant
    taking the integer residual, so the shares always sum back to the
    flush totals with zero drift (the HBM _split_exact discipline
    applied to time). Pure arithmetic — cfg20's smoke drives it with
    no jax in the process."""
    comp_us = ms_to_us(comp_ms)
    h2d_us = ms_to_us(h2d_ms)
    dev_us = ms_to_us(dev_ms)
    dbytes = int(delta_bytes)
    if not tenants:
        return SPLIT_EXACT, []
    if len(tenants) == 1 or rows <= 0:
        chain = tenants[0][0]
        return SPLIT_EXACT, [(chain, comp_us, h2d_us, dev_us, dbytes)]
    # unrolled columns (no per-share tuple comprehensions): this runs
    # inside the per-flush hook budget bench.cost_hooks_bookkeeping_us
    # asserts, so the constant factor matters
    out = []
    c_acc = h_acc = d_acc = b_acc = 0
    last = len(tenants) - 1
    for i, (chain, t_rows) in enumerate(tenants):
        if i == last:
            out.append((chain, comp_us - c_acc, h2d_us - h_acc,
                        dev_us - d_acc, dbytes - b_acc))
        else:
            c = comp_us * t_rows // rows
            h = h2d_us * t_rows // rows
            d = dev_us * t_rows // rows
            b = dbytes * t_rows // rows
            c_acc += c
            h_acc += h
            d_acc += d
            b_acc += b
            out.append((chain, c, h, d, b))
    return SPLIT_ROWS, out


def _tenant_rows(col) -> dict:
    """Aggregate the ledger's per-flush tenant splits into {chain_id:
    rows} over the window (summary/read time only)."""
    out: dict = {}
    for pairs in col:
        for chain, rows in pairs:
            out[chain] = out.get(chain, 0) + rows
    return out


def _tenant_split(batch) -> tuple:
    """The ledger's per-tenant row attribution for one flush: sorted
    ((chain_id, rows), ...) pairs summing to the flush total. A sorted
    tuple of pairs, not a dict — the record is a flat list mutated in
    place, and replay comparisons need a deterministic, hashable
    value."""
    d: dict = {}
    for s in batch:
        d[s.tenant] = d.get(s.tenant, 0) + len(s.rows)
    return tuple(sorted(d.items()))


def _device_block(cols: dict) -> dict:
    """The summary's device-time attribution over the ring's columns:
    compile ms total (and which flushes paid it), plus h2d/dev/util
    percentiles over the FUSED flushes that actually measured them
    (host-path zeros would drown the signal)."""
    from cometbft_tpu.libs.quantiles import nearest_rank

    fused = [i for i, p in enumerate(cols["path"])
             if p in (PATH_FUSED, PATH_FUSED_SHARDED)]

    def pcts(name):
        xs = sorted(cols[name][i] for i in fused)
        if not xs:
            return {"p50": 0.0, "p90": 0.0, "max": 0.0}
        return {"p50": nearest_rank(xs, 0.5),
                "p90": nearest_rank(xs, 0.9), "max": xs[-1]}

    return {
        "comp_ms": round(sum(cols["comp_ms"]), 3),
        "comp_flushes": sum(1 for c in cols["comp_ms"] if c),
        "fused_flushes": len(fused),
        "h2d_ms": pcts("h2d_ms"),
        "dev_ms": pcts("dev_ms"),
        "util": pcts("util"),
    }


class FlushLedger:
    """Bounded ring of per-flush records.

    Record fields (see ``FIELDS``): per-plane sequence number, flush
    timestamp (ms on the ledger clock), row/submission counts, the
    per-stage costs (queued/pack/flight/collect/settle ms), how many
    OTHER flights were airborne when this flush dispatched (``airborne``
    — the flight-deck generalization of the old boolean overlap flag;
    records() still derives the legacy ``overlapped`` bool from it),
    the dispatch path taken, the breaker state observed at stage time,
    staging-pool misses charged to this flush, the queue depth left
    behind, the per-lane row split (c_rows CONSENSUS / g_rows GATEWAY /
    b_rows BULK), how many sheddable-lane submissions were shed at
    this drain, the flush's sub-mesh attribution: n_dev (1 =
    single-device/host pass, >1 = the cross-chip sharded mesh pass),
    n_host (always 1 today — pre-plumbed for the multi-host DCN round)
    and dev0 (first device id of the flush's sub-mesh, so two deck
    flights on disjoint halves are visibly disjoint in /dump_flushes)
    — and ``warm``: 1 when a fused flush found its valset window table
    already cached (LRU hit), 0 when it paid the build/patch inline
    (the cold first-commit-after-rotation stall the next-epoch table
    warmer exists to kill; non-table paths record 0) — and the
    DEVICE-TIME split (the device observatory, libs/deviceledger):
    ``comp_ms`` = jax backend-compile ms attributed to THIS flush
    (cold post-rotation compiles become visible on the flush that
    paid them; a nonzero value on a steady flush is the round-5
    regression class), ``h2d_ms`` = the host-side dispatch wall
    (device_put staging + kernel enqueue) net of comp_ms, ``dev_ms``
    = the estimated on-device time (dispatch -> first true readiness
    probe when the deck observed one, else dispatch -> fetch
    complete, an upper bound including d2h), and ``util`` = real rows
    / padded device slots staged (the rows-x-cost utilization of the
    pass; 0 on non-fused paths). comp_ms and h2d_ms decompose part
    of pack_ms (dispatch runs inside the pack span); dev_ms overlaps
    flight+collect. ``stamp`` attributes the flush's row assembly:
    STAMP_DEVICE when the fused path shipped per-row deltas and the
    device stamping prologue rebuilt the rows, STAMP_HOST when full
    rows were packed host-side (legacy fused fallback and every
    non-fused path). ``delta_bytes`` is the staged delta footprint of
    a device-stamped flush (0 on host-packed flushes) — read next to
    h2d_ms to see the shipped-bytes shrink the stamp bought.
    ``tenants`` is the multi-tenant row attribution:
    sorted ((chain_id, rows), ...) pairs summing to the flush total —
    the ledger evidence that ONE flush coalesced rows from MANY
    chains (verifyplane/tenants.py; empty on shed-only cycles).
    ``split`` is the tenant split RULE this flush's device-time
    columns were charged under (SPLIT_EXACT = one tenant owned every
    row, the charge is exact; SPLIT_ROWS = a fused multi-tenant batch,
    charged row-proportionally with the integer residual on the last
    tenant — see split_device_columns); the per-tenant accumulators
    /dump_tenants serves are fed from exactly this rule, so the
    conservation cross-check (tenants.reconcile_device) is an
    identity, not an estimate. Written by the dispatcher even when
    tracing is off; read by /dump_flushes, the scrape-time /metrics
    percentiles, and simnet replay blobs."""

    FIELDS = ("seq", "ts_ms", "rows", "subs", "queued_ms", "pack_ms",
              "flight_ms", "collect_ms", "settle_ms", "airborne",
              "path", "stamp", "breaker", "staging_miss", "depth",
              "c_rows", "g_rows", "b_rows", "shed", "n_dev",
              "n_host", "dev0", "warm", "comp_ms", "h2d_ms",
              "delta_bytes", "dev_ms", "util", "tenants", "split")

    __slots__ = ("_ring",)

    def __init__(self, capacity: int = LEDGER_CAPACITY):
        self._ring = deque(maxlen=max(16, int(capacity)))

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, rec: list) -> None:
        self._ring.append(rec)

    def records(self) -> List[dict]:
        """The ring as dicts, oldest first (dict construction happens
        at READ time — dump/scrape — never on the flush path)."""
        # list(deque) snapshots atomically under the GIL (one C call);
        # zip(FIELDS, r) stops at the FIELDS window, so the two internal
        # ns stamps trailing each record never leak into a dump
        out = []
        for r in list(self._ring):
            d = dict(zip(self.FIELDS, r))
            # legacy key: "overlapped" was a bool before the deck
            # widened it to the airborne count — derived at READ time
            # so /dump_flushes consumers keep working
            d["overlapped"] = bool(d["airborne"])
            out.append(d)
        return out

    def tail(self, n: int = 8) -> List[str]:
        """The last n flushes as compact strings — small enough to ride
        a simnet replay blob."""
        out = []
        for r in list(self._ring)[-n:]:
            out.append(
                f"#{r[_L_SEQ]} rows={r[_L_ROWS]} {r[_L_PATH]} "
                f"queued={r[_L_QUEUED]}ms pack={r[_L_PACK]}ms "
                f"flight={r[_L_FLIGHT]}ms collect={r[_L_COLLECT]}ms "
                f"settle={r[_L_SETTLE]}ms"
                + (f" x{r[_L_NDEV]}dev" if r[_L_NDEV] > 1 else "")
                + (f" air={r[_L_AIR]}" if r[_L_AIR] else "")
                + (" cold" if r[_L_PATH] in (PATH_FUSED,
                                             PATH_FUSED_SHARDED)
                   and not r[_L_WARM] else "")
                + (f" comp={r[_L_COMP]}ms" if r[_L_COMP] else "")
            )
        return out

    def summary(self) -> dict:
        """Percentile summary over the ring (computed at read time)."""
        recs = list(self._ring)
        if not recs:
            return {"flushes": 0}
        cols = {name: [r[i] for r in recs]
                for i, name in enumerate(self.FIELDS)}

        from cometbft_tpu.libs.quantiles import nearest_rank

        def pcts(xs):
            s = sorted(xs)
            return {"p50": nearest_rank(s, 0.5),
                    "p90": nearest_rank(s, 0.9), "max": s[-1]}

        pack_total = sum(cols["pack_ms"])
        pack_over = sum(p for p, o in zip(cols["pack_ms"],
                                          cols["airborne"]) if o)
        paths: dict = {}
        for p in cols["path"]:
            paths[p] = paths.get(p, 0) + 1
        return {
            "flushes": len(recs),
            "rows": int(sum(cols["rows"])),
            "stage_ms": {k: pcts(cols[f"{k}_ms"])
                         for k in ("queued", "pack", "flight", "collect",
                                   "settle")},
            "rows_per_flush": pcts(cols["rows"]),
            "overlap_frac": round(pack_over / pack_total, 3)
            if pack_total else 0.0,
            "paths": paths,
            "staging_miss": int(sum(cols["staging_miss"])),
            "host_fallback": sum(
                paths.get(p, 0) for p in (PATH_FAILPOINT,
                                          PATH_FUSED_FALLBACK)),
            "lanes": {LANE_CONSENSUS: int(sum(cols["c_rows"])),
                      LANE_GATEWAY: int(sum(cols["g_rows"])),
                      LANE_BULK: int(sum(cols["b_rows"]))},
            "shed": int(sum(cols["shed"])),
            # multi-tenant attribution: per-chain rows over the window
            # plus the coalescing evidence — flushes whose tenant
            # split names >1 chain (one device pass, many chains)
            "tenants": _tenant_rows(cols["tenants"]),
            "coalesced_flushes": sum(
                1 for t in cols["tenants"] if len(t) > 1),
            # cross-chip attribution: flushes/rows that rode the
            # sharded mesh pass, and the widest fan-out seen
            "shard": {
                "flushes": sum(1 for d in cols["n_dev"] if d > 1),
                "rows": int(sum(r for r, d in zip(cols["rows"],
                                                  cols["n_dev"])
                                if d > 1)),
                "n_dev_max": int(max(cols["n_dev"], default=0)),
            },
            # flight-deck attribution: how deep the deck actually got
            # (airborne = flights already in the air at dispatch time,
            # so airborne_max == 1 means two flights flew at once)
            "deck": {
                "airborne_max": int(max(cols["airborne"], default=0)),
                "overlapped_flushes": sum(
                    1 for a in cols["airborne"] if a),
            },
            # device-time attribution (the device observatory,
            # /dump_devices): total backend-compile ms charged to
            # flushes in the window (nonzero on a steady stream = the
            # round-5 class), and the h2d/on-device/utilization
            # figures over the fused flushes that measured them
            "device": _device_block(cols),
            # row-assembly attribution: device-stamped vs host-packed
            # flushes over the window, plus the staged delta bytes the
            # stamped flushes shipped instead of full rows
            "stamp": {
                "device": sum(1 for s in cols["stamp"]
                              if s == STAMP_DEVICE),
                "host": sum(1 for s in cols["stamp"]
                            if s == STAMP_HOST),
                "delta_bytes": int(sum(cols["delta_bytes"])),
            },
            # valset-table attribution over the fused paths: cold = a
            # flush that paid the table build/patch inline (the
            # post-rotation stall /dump_flushes localizes; the warmer
            # exists to keep this 0 across epochs)
            "tables": {
                "warm": sum(1 for p, w in zip(cols["path"], cols["warm"])
                            if w and p in (PATH_FUSED,
                                           PATH_FUSED_SHARDED)),
                "cold": sum(1 for p, w in zip(cols["path"], cols["warm"])
                            if not w and p in (PATH_FUSED,
                                               PATH_FUSED_SHARDED)),
            },
        }
DEFAULT_RESULT_TIMEOUT = 30.0
# stop()-time leftover drain budget: rows host-verified synchronously
# before remaining futures fail fast (a few seconds worst-case on the
# pure-Python path, not minutes)
STOP_DRAIN_MAX_ROWS = 2048


class PlaneError(Exception):
    """Base for plane-side failures; callers fall back to host verify."""


class PlaneQueueFull(PlaneError):
    """Backpressure: the pending queue is at max_queue."""


class PlaneOverloaded(PlaneError):
    """Explicit BULK-lane shed verdict: the plane cannot serve this
    submission inside its deadline (queue past its bound, or the
    submission aged out before a flush reached it). Never raised for
    CONSENSUS-lane submissions. Carries a retry-after hint so RPC
    callers can surface honest backoff to clients."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


class PlaneStopped(PlaneError):
    """Submitted to a plane that is not running."""


class VerifyFuture:
    """Resolves to a tuple of per-item bool verdicts (one submission may
    carry several signatures, e.g. a vote + its extension).

    ``flush_seq`` is the flush-ledger seq of the flush that served this
    submission (stamped at stage time, before the future resolves) —
    None until staged, and forever None for shed/failed submissions.
    The consensus height ledger joins it against /dump_flushes to
    attribute per-height verify-plane milliseconds."""

    __slots__ = ("_ev", "_verdicts", "_err", "flush_seq")

    def __init__(self):
        self.flush_seq: Optional[int] = None
        self._ev = threading.Event()
        self._verdicts: Optional[Tuple[bool, ...]] = None
        self._err: Optional[BaseException] = None

    def _resolve(self, verdicts: Sequence[bool]) -> None:
        self._verdicts = tuple(bool(v) for v in verdicts)
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Tuple[bool, ...]:
        if not self._ev.wait(DEFAULT_RESULT_TIMEOUT
                             if timeout is None else timeout):
            raise PlaneError("verify plane result timed out")
        if self._err is not None:
            if isinstance(self._err, PlaneError):
                # preserve the concrete type: a dispatcher deadline
                # shed stores PlaneOverloaded (+ retry hint), and the
                # mempool's explicit-verdict arm dispatches on it —
                # flattening to PlaneError would silently re-route shed
                # txs into the inline host-verify fallback
                raise self._err
            raise PlaneError(str(self._err)) from self._err
        return self._verdicts


class QuorumGroup:
    """A fused voting-power tally target.

    Counted submissions tagged with a group add their power to the
    group's tally inside the dispatch pass (all signatures of the
    submission must verify). The quorum event fires the moment the
    tally crosses the threshold — the caller (VoteSet) learns quorum
    from the plane instead of re-tallying verdicts itself."""

    def __init__(self, threshold: int, name: str = "",
                 valset_pubs: Optional[tuple] = None,
                 valset_powers: Optional[tuple] = None):
        self.threshold = int(threshold)
        self.name = name
        # optional valset backing (pubkey bytes + powers, index-aligned):
        # lets the device flush reuse the cached window table and fuse
        # this group's tally into the verify kernel (fused.try_fused)
        self.valset_pubs = valset_pubs
        self.valset_powers = valset_powers
        self._lock = threading.Lock()
        self._tally = 0
        self._quorum = threading.Event()

    @property
    def tally(self) -> int:
        with self._lock:
            return self._tally

    @property
    def quorum_reached(self) -> bool:
        return self._quorum.is_set()

    def wait_quorum(self, timeout: Optional[float] = None) -> bool:
        return self._quorum.wait(timeout)

    def add(self, power: int) -> bool:
        """Add verified power; returns True when this add crossed the
        threshold."""
        with self._lock:
            old = self._tally
            self._tally += int(power)
            crossed = old < self.threshold <= self._tally
        if crossed:
            self._quorum.set()
        return crossed

    def retract(self, power: int) -> None:
        """Undo a tallied contribution (the caller's admission step
        found the vote inadmissible after all — duplicate race or
        equivocation). A retraction that drops the tally back below
        the threshold also clears the quorum event: the crossing was
        a transient double-count, not a real 2/3 (maj23 itself only
        flips on a genuine bv.sum crossing, so consensus never acted
        on the phantom signal)."""
        with self._lock:
            self._tally -= int(power)
            if self._tally < self.threshold:
                self._quorum.clear()


class _Submission:
    __slots__ = ("rows", "future", "group", "power", "counted",
                 "vidx", "t_submit", "t_submit_led", "clock_gen", "tid",
                 "lane", "tenant", "stamp")

    def __init__(self, rows, group, power, counted, vidx=None,
                 lane=LANE_CONSENSUS, tenant=None, stamp=None):
        self.rows = rows                      # [(PubKey, msg, sig), ...]
        self.future = VerifyFuture()
        self.group = group
        self.power = int(power)
        self.counted = bool(counted)
        self.vidx = tuple(vidx) if vidx is not None else None
        self.lane = lane
        # device-stamp metadata: per-row (VoteRowTemplate, secs, nanos)
        # tuples aligned with rows (None entries — e.g. extension rows
        # — make the flush fall back to host packing). Attached by the
        # vote-set submitter when the msg was built from the template,
        # so metadata and bytes agree by construction.
        self.stamp = stamp
        # tenancy key: which chain this work belongs to (DEFAULT_TENANT
        # when the caller predates the multi-tenant plane) — drives the
        # ledger's per-tenant attribution, the fair-share drain, and
        # the quota gates (verifyplane/tenants.py)
        self.tenant = tenant if tenant else DEFAULT_TENANT
        self.t_submit = time.perf_counter()
        # ledger/trace-clock stamp for queued_ms: rides the ledger
        # clock (== the trace clock when tracing is on; virtual under
        # simnet) so ledgers AND traces of the same (seed, schedule)
        # stay byte-identical. Always stamped — the flush ledger needs
        # it with tracing off too.
        self.t_submit_led = tracing.monotonic_ns()
        # the stamp is only comparable to a flush-time reading taken
        # under the same clock generation (simnet clock install/restore
        # between submit and flush would difference two domains)
        self.clock_gen = tracing.clock_gen()
        self.tid = threading.get_ident()


class _Flight:
    """One staged flush on the dispatcher's deck: the submissions, the
    deferred finish() that blocks for verdicts, whether a device pass
    is genuinely airborne, the flush id, the ledger scratch record,
    the device ids the pass occupies (None = single-device/host — the
    deck's disjoint-halves bookkeeping), and an optional non-blocking
    readiness probe for out-of-order landing."""

    __slots__ = ("batch", "finish", "airborne", "fid", "led", "devs",
                 "ready", "pack_idx")

    def __init__(self, batch, finish, airborne, fid, led, devs=None,
                 ready=None, pack_idx=0):
        self.batch = batch
        self.finish = finish
        self.airborne = airborne
        self.fid = fid
        self.led = led
        self.devs = devs
        self.ready = ready
        # per-plane pack ordinal: the staging pool rotates flights+1
        # slots round-robin, so pack m reuses pack m-(flights+1)'s
        # buffers — the dispatcher force-lands any flight that old
        # before packing (the rotation-window safety bound on
        # out-of-order landing)
        self.pack_idx = pack_idx


def _ready_index(deck) -> Optional[int]:
    """Index of the first deck flight whose readiness probe says its
    results are fetchable without blocking, or None. The probe is how
    the deck lands out of order: when flight k+1 finishes first, it
    settles first — no head-of-line blocking behind flight k."""
    for i, f in enumerate(deck):
        if f.ready is not None and f.ready():
            return i
    return None


def _host_verdicts(rows) -> List[bool]:
    """Inline host path: per-row single verify via the reference-path
    PubKey.verify_signature (ed25519_ref and friends)."""
    out = []
    for pub, msg, sig in rows:
        try:
            out.append(bool(pub.verify_signature(msg, sig)))
        except ValueError:
            out.append(False)
    return out


class VerifyPlane:
    """Always-on background scheduler turning the device into a shared
    verification service. Start/stop with the node lifecycle."""

    def __init__(self, window_ms: float = 1.5, max_batch: int = 1024,
                 max_queue: int = 8192, metrics=None,
                 kernels: Optional[dict] = None, breaker=None,
                 use_device: Optional[bool] = None,
                 bulk_window_ms: Optional[float] = None,
                 bulk_max_queue: Optional[int] = None,
                 bulk_deadline_ms: float = 250.0,
                 gateway_window_ms: Optional[float] = None,
                 gateway_max_queue: Optional[int] = None,
                 gateway_deadline_ms: float = 500.0,
                 mesh_devices: Optional[int] = None,
                 mesh_min_rows: int = 256,
                 pipeline_flights: int = 1,
                 pipeline_flights_max: Optional[int] = None,
                 half_mesh_rows: int = 0,
                 tenants=None):
        from cometbft_tpu.crypto import batch as cbatch
        from cometbft_tpu.libs.staging import StagingPool

        self.window = max(0.0, window_ms) / 1000.0
        self.max_batch = max(1, int(max_batch))
        self.max_queue = max(1, int(max_queue))
        # BULK lane QoS knobs: a longer coalescing window (bulk cares
        # about batch fullness, not latency), its own queue bound, and
        # the shed deadline (0 disables deadline shedding)
        self.bulk_window = (self.window * 4 if bulk_window_ms is None
                            else max(0.0, bulk_window_ms) / 1000.0)
        self.bulk_max_queue = (self.max_queue if bulk_max_queue is None
                               else max(1, int(bulk_max_queue)))
        self.bulk_deadline = max(0.0, bulk_deadline_ms) / 1000.0
        # GATEWAY lane QoS knobs: client-facing header verifies — a
        # shorter window than BULK (an RPC caller is waiting) but still
        # coalescing-friendly, its own bound, and a more generous shed
        # deadline (a light-client sync tolerates more latency than a
        # CheckTx; 0 disables deadline shedding)
        self.gateway_window = (self.window * 2
                               if gateway_window_ms is None
                               else max(0.0, gateway_window_ms) / 1000.0)
        self.gateway_max_queue = (
            self.max_queue if gateway_max_queue is None
            else max(1, int(gateway_max_queue)))
        self.gateway_deadline = max(0.0, gateway_deadline_ms) / 1000.0
        # per-lane views the dispatcher and submit path index by lane
        self.lane_window = {LANE_CONSENSUS: self.window,
                            LANE_GATEWAY: self.gateway_window,
                            LANE_BULK: self.bulk_window}
        self.lane_limit = {LANE_CONSENSUS: self.max_queue,
                           LANE_GATEWAY: self.gateway_max_queue,
                           LANE_BULK: self.bulk_max_queue}
        self.lane_deadline = {LANE_GATEWAY: self.gateway_deadline,
                              LANE_BULK: self.bulk_deadline}
        self.metrics = metrics
        self._kernels = kernels
        self._breaker = breaker if breaker is not None \
            else cbatch.device_breaker()
        # device dispatch only when a kernel set was injected (tests) or
        # an accelerator actually exists — the XLA/interpret kernels on
        # CPU cost minutes of compile, so the CPU plane coalesces and
        # verifies on the inline host path instead
        self._use_device = (use_device if use_device is not None
                            else kernels is not None
                            or cbatch._accel_backend())
        self._cv = threading.Condition()
        # per-lane pending queues + row counts (QoS: CONSENSUS drains
        # first; BULK is separately bounded and sheddable)
        self._pending: dict = {lane: deque() for lane in LANES}
        self._pending_rows: dict = {lane: 0 for lane in LANES}
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # observability (also mirrored into NodeMetrics when attached)
        self.dispatch_log: deque = deque(maxlen=DISPATCH_LOG_MAX)
        self.batches = 0
        self.rows_verified = 0
        self.padding_waste = 0
        self.pack_seconds = 0.0   # host staging time (template pack etc.)
        self.h2d_bytes = 0        # bytes staged to the device
        self.overlapped = 0       # flushes packed while another flew
        # QoS accounting: per-lane verified rows, sheds (CONSENSUS is
        # structurally always 0 — the soak harness asserts it), and a
        # bounded window of recent per-lane submit-to-result wall
        # latencies (real clock, powers the p99-under-flood assertions)
        self.lane_rows = {lane: 0 for lane in LANES}
        self.sheds = {lane: 0 for lane in LANES}
        self._shed_lock = threading.Lock()
        self.lane_waits = {lane: deque(maxlen=LANE_WAIT_WINDOW)
                           for lane in LANES}
        # multi-tenant plane (verifyplane/tenants.py): the registry
        # owning quotas, the fair-share rotation cursor, and the
        # per-tenant accounting /dump_tenants serves. Injected for
        # tests; every plane gets one — a single-chain node just never
        # registers a second tenant. _pending_tenant_rows is the O(1)
        # per-(lane, tenant) pending-row split the quota gate and the
        # fair-share fast path read under _cv (a dict per lane:
        # tenant -> rows, entries removed at zero so the common
        # single-tenant case stays a one-key dict).
        if tenants is None:
            from cometbft_tpu.verifyplane.tenants import TenantRegistry

            tenants = TenantRegistry()
        self.tenants = tenants
        self._pending_tenant_rows: dict = {lane: {} for lane in LANES}
        # multichip sharded dispatch ([verify_plane] mesh knobs):
        # mesh_devices None = single-device; 0 = shard fused flushes
        # over ALL local devices; N = cap at N. mesh_min_rows keeps
        # tiny flushes on one chip — a cross-chip pass only pays off
        # once the per-device slice is worth its psum.
        self._mesh_devices = (None if mesh_devices is None
                              else max(0, int(mesh_devices)))
        self.mesh_min_rows = max(0, int(mesh_min_rows))
        self._mesh = None          # resolved lazily, once
        self._mesh_resolved = False
        self.shard_flushes = 0     # flushes dispatched cross-chip
        self.shard_rows = 0        # rows those flushes carried
        self.mesh_ndev = 0         # resolved fan-out (0 = single-dev)
        # flight deck (pipelined mesh halves): up to `flights` flushes
        # airborne at once; with a >=4-device mesh they alternate over
        # disjoint halves (resolved with the mesh). half_mesh_rows is
        # the policy knob: a flush over it takes the full mesh.
        self.flights = max(1, int(pipeline_flights))
        # controller ceiling: the deck may GROW to flights_max at
        # runtime (libs/controller), so everything sized at
        # construction (staging pool, mesh halves) must be sized for
        # the ceiling, not the starting value — a live grow must never
        # alias staging buffers
        self.flights_max = max(self.flights,
                               int(pipeline_flights_max or 0))
        self.half_mesh_rows = max(0, int(half_mesh_rows))
        self._halves: list = []    # resolved with the mesh
        self.deck_airborne = 0     # flights airborne right now
        self.deck_peak = 0         # deepest the deck ever got
        self._packs = 0            # pack ordinal (rotation-window bound)
        # device observatory: successful fused collects before this
        # plane declares the process steady (deviceledger.mark_steady),
        # and whether the compile listener armed yet (start() may be
        # refused pre-jax; the dispatch seam re-arms lazily)
        self._steady_flushes = 0
        self._listener_armed = False
        # always-on flush ledger (bounded ring; survives stop() — it is
        # read-only history, never cleared by the lifecycle)
        self.ledger = FlushLedger()
        self._flush_seq = itertools.count()  # per-plane, deterministic
        # PRIVATE staging pool: the rotation contract (one writer per
        # key) only holds per dispatcher thread — two planes in one
        # process (multi-node tests, simnet) must never share slots.
        # Depth tracks the deck: up to `flights` flushes pin their
        # buffers under airborne flights while the next one packs, so
        # flights+1 slots keep pack(k+2) off flight k's memory (the
        # old hardcoded 2 silently aliased the third pack's buffers).
        # Sized at the CEILING: the controller may grow flights live,
        # and the pool depth cannot change under airborne flights.
        self._staging = StagingPool(slots=self.flights_max + 1)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        if self._use_device:
            # device observatory: a device-dispatching plane means jax
            # is (or is about to be) live in this process — arm the
            # process-global compile listener so every compile this
            # plane's flushes trigger lands in /dump_devices (refused
            # before jax imports; the dispatch seam re-arms lazily)
            self._listener_armed = deviceledger.arm_compile_listener()
        self._thread = threading.Thread(
            target=self._run, name="verify-plane", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            if not self._running:
                return
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # resolve anything the dispatcher didn't drain (dispatcher died,
        # or the join timed out mid-flush) so no submitter ever hangs on
        # a stopped plane — and resolve with REAL verdicts via the inline
        # host path, not an error: callers that already passed submit()
        # successfully treat the future as authoritative. The host pass
        # is BUDGETED (pure-Python ed25519 costs ms/row on wheel-less
        # hosts): past the budget, remaining futures fail fast with
        # PlaneStopped rather than pinning shutdown for minutes.
        leftovers = []
        with self._cv:
            # CONSENSUS first: the drain budget must favor the lane
            # that is never shed
            for lane in LANES:
                q = self._pending[lane]
                while q:
                    leftovers.append(q.popleft())
                self._pending_rows[lane] = 0
                self._pending_tenant_rows[lane].clear()
        budget = STOP_DRAIN_MAX_ROWS
        settle, fail = [], []
        for sub in leftovers:
            if budget >= len(sub.rows):
                budget -= len(sub.rows)
                settle.append(sub)
            else:
                fail.append(sub)
        if settle:
            rows = [r for sub in settle for r in sub.rows]
            t0 = tracing.monotonic_ns()
            drain_seq = next(self._flush_seq)
            for sub in settle:
                sub.future.flush_seq = drain_seq
            verdicts = _host_verdicts(rows)
            t1 = tracing.monotonic_ns()
            self._settle(settle, verdicts)
            # the drain is a flush too: the ledger must explain where
            # shutdown time went (and survive into post-stop dumps)
            c_rows = sum(len(s.rows) for s in settle
                         if s.lane == LANE_CONSENSUS)
            g_rows = sum(len(s.rows) for s in settle
                         if s.lane == LANE_GATEWAY)
            drain_tens = _tenant_split(settle)
            self.ledger.record([
                drain_seq, round(t0 / 1e6, 3), len(rows),
                len(settle), 0.0, 0.0, 0.0,
                round((t1 - t0) / 1e6, 3),
                round((tracing.monotonic_ns() - t1) / 1e6, 3),
                0, PATH_STOP_DRAIN, STAMP_HOST, self._breaker.state,
                0, 0,
                c_rows, g_rows, len(rows) - c_rows - g_rows, 0, 1,
                1, 0, 0, 0.0, 0.0, 0, 0.0, 0.0, drain_tens,
                SPLIT_EXACT if len(drain_tens) <= 1 else SPLIT_ROWS,
            ])
        for sub in fail:
            sub.future._fail(PlaneStopped(
                "verify plane stopped with queue over the drain budget"
            ))

    def is_running(self) -> bool:
        return self._running

    def in_dispatcher(self) -> bool:
        """True on the dispatcher thread (recursion guard: the
        dispatcher's own verify calls must not re-enter the plane)."""
        return threading.current_thread() is self._thread

    # -- submission --------------------------------------------------------

    def submit(self, pub, msg: bytes, sig: bytes, power: int = 0,
               group: Optional[QuorumGroup] = None, counted: bool = False,
               vidx: Optional[int] = None,
               block: bool = True, lane: str = LANE_CONSENSUS,
               chain_id: Optional[str] = None) -> VerifyFuture:
        """Submit one (pubkey, msg, sig); the future resolves to a
        1-tuple verdict."""
        return self.submit_many(
            [(pub, msg, sig)], power=power, group=group, counted=counted,
            vidx=None if vidx is None else (vidx,), block=block,
            lane=lane, chain_id=chain_id,
        )

    def submit_many(self, rows, power: int = 0,
                    group: Optional[QuorumGroup] = None,
                    counted: bool = False,
                    vidx: Optional[Sequence[int]] = None,
                    block: bool = True,
                    lane: str = LANE_CONSENSUS,
                    chain_id: Optional[str] = None,
                    stamp=None) -> VerifyFuture:
        """Submit several signatures as ONE unit (e.g. a vote and its
        extension): one future, per-row verdicts, and — when counted —
        the group tally credits `power` only if EVERY row verifies.
        vidx (one validator index per row) enables the fused cached-
        table device path for valset-backed groups; row 0 must be the
        power-bearing signature (the vote; extensions follow).

        `lane` picks the QoS class. GATEWAY/BULK submissions over the
        lane's queue bound raise PlaneOverloaded immediately when
        non-blocking (the explicit shed verdict, with a retry-after
        hint) instead of PlaneQueueFull, and may later be shed by the
        dispatcher if they age past the lane's deadline before a flush
        can take them. A blocking sheddable-lane submission whose
        backpressure wait times out is shed the same explicit way.

        `chain_id` keys the submission to its tenant
        (verifyplane/tenants.py): the ledger attributes the rows, the
        fair-share drain rotates between queued tenants, and a tenant
        past its pending-row quota on a sheddable lane is shed
        immediately with a TenantOverloaded verdict — a hard quota,
        not backpressure, so waiting is never offered. CONSENSUS is
        structurally outside every tenant gate.

        `stamp` (optional, aligned with rows) carries per-row
        (VoteRowTemplate, secs, nanos) metadata so the fused path can
        stage only deltas and stamp sign-bytes on device; None entries
        (extensions, non-votes) force host packing for the flush."""
        if lane not in LANES:
            raise ValueError(f"unknown verify-plane lane {lane!r}")
        rows = list(rows)
        if not rows:
            raise ValueError("empty submission")
        if not self._running or self.in_dispatcher():
            raise PlaneStopped("verify plane not accepting submissions")
        sub = _Submission(rows, group, power, counted, vidx, lane=lane,
                          tenant=chain_id, stamp=stamp)
        limit = self.lane_limit[lane]
        quota = (self.tenants.row_quota(sub.tenant)
                 if lane in SHEDDABLE_LANES else 0)
        deadline = time.monotonic() + DEFAULT_RESULT_TIMEOUT
        with self._cv:
            if quota:
                pend = self._pending_tenant_rows[lane].get(sub.tenant, 0)
                if pend and pend + len(rows) > quota:
                    self._shed_count(1, lane)
                    self.tenants.note_shed(sub.tenant, lane)
                    from cometbft_tpu.verifyplane.tenants import \
                        TenantOverloaded

                    raise TenantOverloaded(
                        f"tenant {sub.tenant!r} past its {quota}-row "
                        f"{lane} quota",
                        retry_after_ms=self._retry_hint_ms(lane),
                        tenant=sub.tenant,
                    )
            # backpressure gates on what is already queued in THIS lane
            # — a lone submission larger than the bound still enters an
            # empty queue (it dispatches alone) instead of deadlocking
            while self._running and self._pending_rows[lane] and \
                    self._pending_rows[lane] + len(rows) > limit:
                if not block:
                    if lane in SHEDDABLE_LANES:
                        self._shed_count(1, lane)
                        raise PlaneOverloaded(
                            f"verify plane {lane} lane full "
                            f"({limit} rows)",
                            retry_after_ms=self._retry_hint_ms(lane),
                        )
                    raise PlaneQueueFull(
                        f"verify plane queue full ({limit} rows)"
                    )
                if not self._cv.wait(timeout=deadline - time.monotonic()) \
                        and time.monotonic() >= deadline:
                    if lane in SHEDDABLE_LANES:
                        self._shed_count(1, lane)
                        raise PlaneOverloaded(
                            f"verify plane {lane} backpressure wait "
                            f"timed out",
                            retry_after_ms=self._retry_hint_ms(lane),
                        )
                    raise PlaneQueueFull(
                        "verify plane backpressure wait timed out"
                    )
            if not self._running:
                raise PlaneStopped("verify plane stopped")
            self._pending[lane].append(sub)
            self._pending_rows[lane] += len(rows)
            tpend = self._pending_tenant_rows[lane]
            tpend[sub.tenant] = tpend.get(sub.tenant, 0) + len(rows)
            depth = self._depth_locked()
            if self.metrics is not None:
                self.metrics.plane_queue_depth.set(depth)
            self._cv.notify_all()
        if tracing.enabled():
            tracing.instant("plane.submit", cat="verifyplane",
                            rows=len(rows), depth=depth, lane=lane)
        return sub.future

    def _depth_locked(self) -> int:
        return sum(self._pending_rows[lane] for lane in LANES)

    def _tenant_unpend(self, lane: str, sub: "_Submission") -> None:
        """_cv held: release a dequeued submission's rows from the
        per-(lane, tenant) pending split (entries drop at zero so the
        dict never grows with retired tenants)."""
        tpend = self._pending_tenant_rows[lane]
        n = tpend.get(sub.tenant, 0) - len(sub.rows)
        if n > 0:
            tpend[sub.tenant] = n
        else:
            tpend.pop(sub.tenant, None)

    def _retry_hint_ms(self, lane: str = LANE_BULK) -> float:
        """Honest backoff hint for shed callers: the lane's deadline is
        the time scale on which its backlog either clears or sheds, so
        retrying sooner than that is guaranteed wasted work."""
        return round(max(self.lane_deadline.get(lane, 0.0),
                         self.lane_window[lane]) * 1000, 1)

    def _shed_count(self, n: int, lane: str = LANE_BULK) -> None:
        # dedicated lock: the submit path sheds while HOLDING _cv and
        # the dispatcher sheds outside it — an unguarded += would lose
        # increments exactly during the overload bursts this counts
        with self._shed_lock:
            self.sheds[lane] += n
        if self.metrics is not None:
            self.metrics.plane_shed.inc(n, lane=lane)
        # incident watchdog: sheds feed the storm window (counted here,
        # evaluated at the next deterministic poke — libs/incidents)
        from cometbft_tpu.libs import incidents

        incidents.note_shed(n)

    def submit_and_wait(self, pubs, msgs, sigs,
                        timeout: Optional[float] = None,
                        lane: str = LANE_CONSENSUS,
                        chain_id: Optional[str] = None) -> np.ndarray:
        """crypto.batch.verify_batch shape: (n,) bool validity through
        the plane (one submission, one flush slot)."""
        fut = self.submit_many(list(zip(pubs, msgs, sigs)), lane=lane,
                               chain_id=chain_id)
        if timeout is None:
            # scale with batch size: a 10k-row host-path flush on a
            # 1-core box legitimately outlives the default window
            timeout = max(DEFAULT_RESULT_TIMEOUT, 0.05 * len(pubs))
        return np.asarray(fut.result(timeout), np.bool_)

    # -- dispatcher --------------------------------------------------------

    def _run(self) -> None:
        """Flight-deck dispatch loop: while up to `flights` flushes fly
        on the device (on DISJOINT sub-mesh halves when the mesh and
        pipeline_flights allow), the dispatcher drains and PACKS the
        next flush into the rotated staging buffers (libs/staging.py)
        and dispatches it onto a free half — the blocksync pipeline's
        overlap (pipeline.py "host packs chunk k+1 while the device
        works"), generalized to every caller AND to device parallelism.
        Airborne flights land out of order via the readiness probe, so
        flight k+1 finishing early never waits behind k. With any
        flight airborne the window wait is skipped: the in-flight pass
        IS the coalescing amortization the window exists to provide.
        pipeline_flights=1 is exactly the classic single-slot double
        buffer."""
        deck: List[_Flight] = []  # airborne flights, dispatch order
        while True:
            # self-tuning seam: one controller poke per drain cycle,
            # OUTSIDE the cv (the controller may call actuator setters
            # that take it). No-op when no controller is mounted.
            controlplane.poke_drain()
            batch: List[_Submission] = []
            shed: List[_Submission] = []
            depth = 0
            with self._cv:
                while self._running:
                    cq = self._pending[LANE_CONSENSUS]
                    waitq = wait_lane = None
                    if not cq:
                        # highest-priority sheddable lane with traffic
                        # coalesces under its own longer window
                        for lane in SHEDDABLE_LANES:
                            if self._pending[lane]:
                                waitq, wait_lane = \
                                    self._pending[lane], lane
                                break
                    if cq:
                        # CONSENSUS owns the flush window: full GATEWAY
                        # or BULK queues can never delay a consensus
                        # flush past its deadline — their rows only
                        # ride along
                        age = time.perf_counter() - cq[0].t_submit
                        if (deck
                                or age >= self.window
                                or self._pending_rows[LANE_CONSENSUS]
                                >= self.max_batch):
                            break
                        self._cv.wait(timeout=self.window - age)
                    elif waitq is not None:
                        win = self.lane_window[wait_lane]
                        age = time.perf_counter() - waitq[0].t_submit
                        if (deck
                                or age >= win
                                or self._pending_rows[wait_lane]
                                >= self.max_batch):
                            break
                        self._cv.wait(timeout=win - age)
                    elif deck:
                        break  # nothing to pack: land a flight
                    else:
                        self._cv.wait(timeout=0.25)
                if not self._running \
                        and not any(self._pending[lane]
                                    for lane in LANES):
                    break
                # deadline sheds first: an aged-out GATEWAY/BULK
                # submission is past the point where verifying it helps
                # anyone (its RPC caller has backed off) — it must not
                # consume flush capacity. Resolved below with an
                # EXPLICIT PlaneOverloaded verdict, never silently
                # dropped. Ages ride the LEDGER clock (virtual under
                # simnet), not perf_counter: a shed is a VERDICT, and
                # the soak harness asserts the verdict stream replays
                # byte-identically — a real-clock cutoff would make it
                # host-load-dependent. In production the ledger clock
                # IS the monotonic real clock, so behavior there is
                # unchanged. Cross-generation stamps (clock swapped
                # mid-queue) are treated as fresh.
                gen = tracing.clock_gen()
                now_ns = tracing.monotonic_ns()
                for lane in SHEDDABLE_LANES:
                    if not self.lane_deadline[lane]:
                        continue
                    q = self._pending[lane]
                    cutoff = now_ns - int(self.lane_deadline[lane] * 1e9)
                    while q and q[0].clock_gen == gen \
                            and q[0].t_submit_led < cutoff:
                        sub = q.popleft()
                        self._pending_rows[lane] -= len(sub.rows)
                        self._tenant_unpend(lane, sub)
                        shed.append(sub)
                # weighted drain: whole CONSENSUS submissions first up
                # to max_batch rows (a lone oversized submission still
                # dispatches alone), then GATEWAY and finally BULK fill
                # the remaining capacity — each with its guaranteed
                # anti-starvation quantum, so every lane makes progress
                # even under a sustained higher-priority storm.
                # CONSENSUS drains whole with NO tenant gate in the
                # loop — per-tenant unsheddability is structural here,
                # exactly like the lane wall: no quota, no rotation,
                # no code path that could skip one tenant's votes.
                rows = 0
                cq = self._pending[LANE_CONSENSUS]
                while cq:
                    nxt = len(cq[0].rows)
                    if batch and rows + nxt > self.max_batch:
                        break
                    sub = cq.popleft()
                    self._pending_rows[LANE_CONSENSUS] -= nxt
                    self._tenant_unpend(LANE_CONSENSUS, sub)
                    rows += nxt
                    batch.append(sub)
                quantum = max(1, self.max_batch // BULK_QUANTUM_DIV)
                for lane in SHEDDABLE_LANES:
                    q = self._pending[lane]
                    budget = max(self.max_batch - rows, quantum)
                    rows += self._drain_sheddable(lane, q, budget, batch)
                depth = self._depth_locked()
                if self.metrics is not None:
                    self.metrics.plane_queue_depth.set(depth)
                self._cv.notify_all()  # wake backpressured submitters
            if shed:
                for sub in shed:
                    self._shed_count(1, sub.lane)
                    self.tenants.note_shed(sub.tenant, sub.lane)
                    sub.future._fail(PlaneOverloaded(
                        f"verify plane shed {sub.lane} submission past "
                        f"its "
                        f"{round(self.lane_deadline[sub.lane] * 1000, 1)}"
                        f"ms deadline",
                        retry_after_ms=self._retry_hint_ms(sub.lane),
                    ))
                if not batch:
                    # a drain cycle can shed everything and cut no
                    # flush — the ledger must still say so, or
                    # /dump_flushes' shed column disagrees with the
                    # sheds counter exactly when an operator is
                    # debugging overload
                    t = tracing.monotonic_ns()
                    self.ledger.record([
                        next(self._flush_seq), round(t / 1e6, 3), 0, 0,
                        0.0, 0.0, 0.0, 0.0, 0.0, 0, PATH_SHED_ONLY,
                        STAMP_HOST,
                        self._breaker.state, 0, depth, 0, 0, 0,
                        len(shed), 0, 0, 0, 0, 0.0, 0.0, 0, 0.0, 0.0, (),
                        SPLIT_EXACT,
                    ])
            if not batch:
                # nothing to pack: land a flight (the first READY one,
                # else wait briefly for new work or readiness — landing
                # the oldest blind would block the dispatcher exactly
                # when a new flush could fly the free half)
                if deck:
                    self._land_or_wait(deck)
                continue
            # staging-rotation safety: the pool hands pack m the very
            # buffers pack m-(flights+1) filled, so a flight that old
            # must LAND (FIFO, blocking) before this pack may touch
            # its memory — out-of-order landing is free only within
            # the pool's rotation window, never across it
            while deck and deck[0].pack_idx <= self._packs - self.flights:
                self._finish_flight(deck.pop(0))
                self._deck_update(deck)
            flight = self._stage(batch, depth, shed_n=len(shed),
                                 deck=deck)
            # flights in the air at dispatch time (post any drain the
            # fan-out policy forced): the ledger's airborne column and
            # the overlap counter — a real overlap means this flush
            # packed on the host while >=1 flight flew on the device
            air = len(deck)
            flight.led[_L_AIR] = air
            if air:
                self.overlapped += 1
            if flight.airborne:
                deck.append(flight)
                self._deck_update(deck)
                while len(deck) > self.flights:
                    self._land_one(deck)
            else:
                # synchronous flush (host path / grouped device):
                # verdicts are already final — land the airborne deck
                # first (its flights dispatched earlier), then settle
                # NOW; deferring would add a whole flush of latency
                # for no overlap
                while deck:
                    self._land_one(deck)
                self._finish_flight(flight)
        while deck:
            self._land_one(deck)

    def _drain_sheddable(self, lane: str, q, budget: int,
                         batch: List[_Submission]) -> int:
        """_cv held: fill up to `budget` rows from one sheddable lane
        into `batch`; returns the rows taken. With ONE tenant queued
        this is the original FIFO loop (O(1) dict probe, no extra
        work on the single-chain plane). With several, the fair-share
        drain: submissions bucket per tenant (FIFO within each), the
        registry's rotation cursor picks the cycle's order, and each
        tenant gets an equal share of the budget before a second pass
        hands unused capacity back out in the same rotation order —
        so a flooding tenant can fill leftover capacity but can never
        crowd a quieter tenant out of its slice, and the head-of-line
        position rotates instead of favoring one chain forever."""
        if len(self._pending_tenant_rows[lane]) <= 1:
            lrows = 0
            while q:
                nxt = len(q[0].rows)
                if batch and lrows + nxt > budget:
                    break
                sub = q.popleft()
                self._pending_rows[lane] -= nxt
                self._tenant_unpend(lane, sub)
                lrows += nxt
                batch.append(sub)
            return lrows
        buckets: dict = {}
        for sub in q:
            buckets.setdefault(sub.tenant, []).append(sub)
        order = self.tenants.drain_order(buckets)
        share = max(1, budget // len(order))
        taken_ids = set()
        lrows = 0
        # pass 1: each tenant up to its equal share (oldest first)
        for name in order:
            b = buckets[name]
            trows = 0
            while b:
                nxt = len(b[0].rows)
                if batch and (trows + nxt > share
                              or lrows + nxt > budget):
                    break
                sub = b.pop(0)
                trows += nxt
                lrows += nxt
                taken_ids.add(id(sub))
                batch.append(sub)
        # pass 2: leftover capacity (tenants under their share left
        # some) goes back out greedily in the same rotation order
        for name in order:
            b = buckets[name]
            while b:
                nxt = len(b[0].rows)
                if batch and lrows + nxt > budget:
                    break
                sub = b.pop(0)
                lrows += nxt
                taken_ids.add(id(sub))
                batch.append(sub)
            if batch and b:
                break  # budget exhausted mid-bucket
        if taken_ids:
            remaining = [s for s in q if id(s) not in taken_ids]
            q.clear()
            q.extend(remaining)
            for sub in batch:
                if id(sub) in taken_ids:
                    self._pending_rows[lane] -= len(sub.rows)
                    self._tenant_unpend(lane, sub)
        return lrows

    def _land_one(self, deck: List[_Flight]) -> None:
        """Land one deck flight: the first READY one (out-of-order —
        flight k+1 landing first never blocks behind k), else the
        oldest (FIFO; its collect blocks until the device finishes)."""
        idx = _ready_index(deck)
        self._finish_flight(deck.pop(0 if idx is None else idx))
        self._deck_update(deck)

    def _land_or_wait(self, deck: List[_Flight]) -> None:
        """Idle-deck landing: settle a READY flight immediately; with
        none ready, poll in short slices for readiness or new work for
        up to one window (new work wins — it can fly a free half while
        the deck stays airborne), then land FIFO regardless: futures
        must resolve even when the runtime offers no readiness probe.
        Only ever called with device flights airborne, so the simnet
        host path (and its ledger determinism) never touches the
        real-clock polling here."""
        idx = _ready_index(deck)
        if idx is None:
            deadline = time.perf_counter() + max(self.window, 0.1)
            while True:
                with self._cv:
                    if self._running and not self._depth_locked():
                        self._cv.wait(timeout=0.005)
                    if self._depth_locked():
                        return  # pack the new flush first
                idx = _ready_index(deck)
                if idx is not None or not self._running \
                        or time.perf_counter() >= deadline:
                    break
            if idx is None:
                idx = 0  # probe can't tell: land FIFO, collect blocks
        self._finish_flight(deck.pop(idx))
        self._deck_update(deck)

    def _deck_update(self, deck: List[_Flight]) -> None:
        n = len(deck)
        self.deck_airborne = n
        if n > self.deck_peak:
            self.deck_peak = n
        if self.metrics is not None:
            self.metrics.plane_deck_airborne.set(float(n))

    def _pick_half(self, deck: List[_Flight]):
        """The sub-mesh half the next fused flush should prefer: a
        half with NO airborne flight (disjoint devices — both halves
        fly at once), else the OLDEST flight's half (it lands soonest;
        the new flush queues behind it on that half exactly like the
        classic single slot queued behind the one in-flight pass)."""
        halves = self._halves
        if not halves or self.flights < 2:
            return None
        busy = set()
        for f in deck:
            busy.update(f.devs or ())
        for h in halves:
            if busy.isdisjoint(int(d.id) for d in h.devices.flat):
                return h
        old = deck[0].devs or ()
        for h in halves:
            if old and old[0] in {int(d.id) for d in h.devices.flat}:
                return h
        return halves[0]

    def _finish_flight(self, flight: _Flight) -> None:
        # hook audit (r05 post-mortem suspect #1): every tracing span
        # here sits behind an `enabled()` check so the DISABLED path
        # constructs no span object and no kwargs dict — the only
        # per-flush bookkeeping is the ledger stamps (plain int clock
        # reads) and the ring tuple.
        batch, finish, airborne, fid, led = (
            flight.batch, flight.finish, flight.airborne, flight.fid,
            flight.led)
        traced = tracing.enabled()
        t_exec = tracing.monotonic_ns()
        # collect-time compiles (first grouped-path kernel build, a
        # faulted flight's host fallback re-trace) attribute to this
        # flush too — comp_ms must name every compile the flush paid
        attr = deviceledger.attr_begin("plane.collect", led[_L_SEQ])
        if airborne:
            if traced:
                with tracing.span("plane.collect", cat="verifyplane",
                                  flush=fid):
                    verdicts, fused_tallies = finish()
                tracing.flight_end("plane.flight", fid, cat="verifyplane")
            else:
                verdicts, fused_tallies = finish()
        else:
            # synchronous flush: the deferred host/grouped verification
            # happens here, attributed to its own stage
            if traced:
                with tracing.span("plane.verify", cat="verifyplane",
                                  flush=fid):
                    verdicts, fused_tallies = finish()
            else:
                verdicts, fused_tallies = finish()
        deviceledger.attr_end(attr)
        if attr.ms:
            led[_L_COMP] = round(led[_L_COMP] + attr.ms, 3)
        t_settle = tracing.monotonic_ns()
        if traced:
            with tracing.span("plane.settle", cat="verifyplane",
                              flush=fid):
                self._settle(batch, verdicts, fused_tallies=fused_tallies)
        else:
            self._settle(batch, verdicts, fused_tallies=fused_tallies)
        t_done = tracing.monotonic_ns()
        # flight_ms: time the pass was airborne before the dispatcher
        # came back for it (the overlap window the double buffer wins);
        # collect_ms: the blocking fetch (or the sync verify itself).
        # The scratch list mutates in place and becomes the ring slot.
        # Differencing needs every stamp from one clock domain: a
        # tracing enable/disable or simnet clock install/restore while
        # the flush was airborne (test/bench teardown) would difference
        # a virtual-epoch ns against a perf_counter ns — same hazard
        # queued_ms guards with clock_gen at pack time. The stage
        # timings are recorded as 0.0 then; the record itself stays.
        if tracing.clock_gen() == led[_L_GEN]:
            if airborne:
                led[_L_FLIGHT] = round((t_exec - led[_L_TPACKED]) / 1e6, 3)
                # on-device time estimate: dispatch -> the first TRUE
                # readiness probe when the deck observed one (the
                # kernel-flight figure), else dispatch -> fetch done
                # (an upper bound that includes the d2h copy)
                ready_ns = led[_L_READY]
                led[_L_DEV] = round(
                    ((ready_ns if ready_ns else t_settle)
                     - led[_L_TPACKED]) / 1e6, 3)
            led[_L_COLLECT] = round((t_settle - t_exec) / 1e6, 3)
            led[_L_SETTLE] = round((t_done - t_settle) / 1e6, 3)
        self._charge_flush(led)
        self.ledger.record(led)

    def _charge_flush(self, led) -> None:
        """The cost observatory's per-flush hook, run once with every
        column final (just before the record becomes a ring slot):
        charge the flush's device-time columns to its tenants under
        the recorded split rule, and feed the device ledger's cost
        surfaces one observation. Always on — the whole hook stays
        under the 10 us budget (bench.cost_hooks_bookkeeping_us,
        asserted in tier-1), so there is no enable knob to forget."""
        tens = led[_L_TEN]
        if tens:
            rule, shares = split_device_columns(
                tens, led[_L_ROWS], led[_L_COMP], led[_L_H2D],
                led[_L_DEV], led[_L_DBYTES])
            led[_L_SPLIT] = rule
            self.tenants.note_device_shares(shares)
        # kernel cost surfaces: the on-device estimate when this flush
        # flew, else the collect wall (the host/grouped verify runs
        # inside the collect span — still the marginal cost of rows)
        deviceledger.observe_flush(
            led[_L_PATH], led[_L_STAMP], led[_L_ROWS], led[_L_NDEV],
            led[_L_COMP], led[_L_H2D],
            led[_L_DEV] if led[_L_DEV] else led[_L_COLLECT])

    def _observe_pack(self, seconds: float, h2d_bytes: int = 0,
                      stamp: str = STAMP_HOST) -> None:
        self.pack_seconds += seconds
        self.h2d_bytes += h2d_bytes
        if self.metrics is not None:
            self.metrics.plane_pack_seconds.observe(seconds)
            if h2d_bytes:
                # split by staging path so a dashboard can watch the
                # device-stamp rollout shrink the bus bill directly
                self.metrics.plane_h2d_bytes.inc(h2d_bytes, path=stamp)

    def _stage(self, batch: List[_Submission], depth: int = 0,
               shed_n: int = 0, deck: List[_Flight] = ()):
        """Pack one flush and (when eligible) launch it on the device
        WITHOUT waiting for results. Returns a _Flight whose finish()
        blocks for the verdicts — the seam that lets the dispatcher
        pack the next flush while this one (and the rest of the deck)
        flies. `deck` is the airborne flights: the fan-out policy picks
        a disjoint half for this flush, and a flush the policy sends to
        the full mesh lands the deck before dispatching. The whole
        host-side staging is one "plane.pack" trace span keyed by
        flush id, so pack(k+1) visibly overlaps device-flight(k) in
        the exported timeline.

        Ledger accounting happens on BOTH paths: the disabled-tracing
        fast path still stamps the clock and fills the scratch list
        (ints and interned strings only — no dict/span construction,
        the r05 post-mortem's suspect #1)."""
        fid = next(_FLUSH_IDS)
        self._packs += 1
        t0 = tracing.monotonic_ns()
        gen = tracing.clock_gen()
        t_min = None
        rows = 0
        c_rows = 0
        g_rows = 0
        tens: dict = {}
        for s in batch:
            rows += len(s.rows)
            tens[s.tenant] = tens.get(s.tenant, 0) + len(s.rows)
            if s.lane == LANE_CONSENSUS:
                c_rows += len(s.rows)
            elif s.lane == LANE_GATEWAY:
                g_rows += len(s.rows)
            if s.clock_gen != gen:
                # stamped under a different clock domain (simnet clock
                # swapped between submit and flush): unusable for a wait
                continue
            ts = s.t_submit_led
            if t_min is None or ts < t_min:
                t_min = ts
        queued_ms = round((t0 - t_min) / 1e6, 3) if t_min is not None \
            else 0.0
        # FIELDS-ordered record + internal slots (t0, t_packed, clock
        # gen, first-ready stamp); this list IS the eventual ring slot
        led = [next(self._flush_seq), round(t0 / 1e6, 3), rows,
               len(batch), queued_ms, 0.0, 0.0, 0.0, 0.0, 0,
               PATH_HOST, STAMP_HOST, self._breaker.state, 0, depth,
               c_rows, g_rows, rows - c_rows - g_rows, shed_n, 1, 1,
               0, 0, 0.0, 0.0, 0, 0.0, 0.0, tuple(sorted(tens.items())),
               SPLIT_EXACT if len(tens) <= 1 else SPLIT_ROWS,
               t0, t0, gen, 0]
        for s in batch:
            # the join key consumers read AFTER the future resolves
            # (height ledger -> /dump_flushes attribution)
            s.future.flush_seq = led[_L_SEQ]
        if not tracing.enabled():
            # disabled fast path: no O(batch) span-arg computation on
            # the dispatcher hot path
            finish, airborne, devs, ready = self._stage_inner(
                batch, fid, led, deck)
        else:
            with tracing.span("plane.pack", cat="verifyplane", flush=fid,
                              rows=rows, subs=len(batch),
                              queued_ms=queued_ms):
                finish, airborne, devs, ready = self._stage_inner(
                    batch, fid, led, deck)
        t1 = tracing.monotonic_ns()
        led[_L_PACK] = round((t1 - t0) / 1e6, 3)
        led[_L_TPACKED] = t1
        if ready is not None:
            # wrap the readiness probe to stamp the FIRST true reading
            # (dispatcher thread only): dev_ms = dispatch -> kernel
            # done, the observatory's on-device time estimate
            def probe(inner=ready, led=led):
                ok = inner()
                if ok and not led[_L_READY] \
                        and tracing.clock_gen() == led[_L_GEN]:
                    led[_L_READY] = tracing.monotonic_ns()
                return ok

            ready = probe
        return _Flight(batch, finish, airborne, fid, led, devs, ready,
                       pack_idx=self._packs)

    def _flush_mesh(self, rows: int):
        """The mesh a fused flush of `rows` rows should shard over, or
        None for single-device dispatch. Resolution is lazy and cached
        (mesh identity feeds every downstream memo); flushes under
        mesh_min_rows stay on one chip — the psum isn't free and tiny
        flushes fit a single device's lanes anyway."""
        if self._mesh_devices is None or rows < self.mesh_min_rows:
            return None
        if not self._mesh_resolved:
            from cometbft_tpu.verifyplane import fused as fz

            try:
                self._mesh = fz.plane_mesh(self._mesh_devices)
            except Exception:  # noqa: BLE001 - no backend: stay single
                self._mesh = None
            self.mesh_ndev = (0 if self._mesh is None
                              else int(self._mesh.devices.size))
            if self.flights_max > 1 and self._mesh is not None:
                # the deck's disjoint halves ride the same memoized
                # sub-mesh seam effective_mesh clamps through; meshes
                # under 4 devices have none (single-flight dispatch).
                # Gated on the CEILING, not the live value: the
                # controller may grow flights after the mesh resolved
                self._halves = fz.half_meshes(self._mesh)
            # published LAST: the warmer's _mesh_targets reads
            # (_mesh_resolved, _mesh, _halves) from its own thread —
            # seeing resolved=True with the halves still unassigned
            # would warm the full mesh instead of the halves flushes
            # actually look tables up under
            self._mesh_resolved = True
            if self.metrics is not None:
                self.metrics.plane_shard_ndev.set(float(self.mesh_ndev))
        return self._mesh

    def _stage_inner(self, batch: List[_Submission], fid: int, led,
                     deck: List[_Flight] = ()):
        """The breaker's allow() — which consumes the single half-open
        probe slot when the breaker is open — is only asked once a
        fused plan exists, i.e. when a device attempt will actually
        happen; an ineligible flush must not burn the probe the
        generic path needs to recover."""
        rows = [r for sub in batch for r in sub.rows]
        t0 = time.perf_counter()
        miss0 = self._staging.misses
        try:
            fp.fail_point("verifyplane.dispatch")
        except Exception:  # noqa: BLE001 - dispatch fault, not verdicts
            _log.exception(
                "verify plane dispatch fault (%d rows); degrading this "
                "flush to the inline host path", len(rows),
            )
            # verdict work is deferred into finish() so the pack span
            # measures staging only (the finish runs immediately for
            # synchronous flushes — same thread, same ordering)
            led[_L_PATH] = PATH_FAILPOINT
            return (lambda: (_host_verdicts(rows), None)), False, \
                None, None
        plan = None
        if self._use_device:
            # lazy re-arm: start()'s attempt is refused when jax was
            # not yet imported (kernels-injected planes); by the first
            # device dispatch it must be — a plane-level flag keeps
            # the steady-state cost at one attribute check
            if not self._listener_armed:
                self._listener_armed = \
                    deviceledger.arm_compile_listener()
        if self._use_device and self._kernels is None:
            from cometbft_tpu.verifyplane import fused as fz

            try:
                mesh = self._flush_mesh(len(rows))
                half = self._pick_half(deck) if mesh is not None \
                    else None
                plan = fz.plan_fused(batch, pool=self._staging,
                                     mesh=mesh, half=half,
                                     half_max_rows=self.half_mesh_rows)
            except Exception:  # noqa: BLE001 - staging bug, not device
                _log.exception("fused flush staging failed; grouped path")
                plan = None
            if plan is not None and not self._breaker.allow():
                plan = None
        if plan is not None:
            if plan.drain_first and deck:
                # the policy sent this flush to the FULL mesh while
                # half-flights are airborne: land the deck before the
                # dispatch so the giant flush owns every chip at once
                # instead of queueing piecemeal behind the halves
                while deck:
                    self._land_one(deck)
            # device observatory attribution: every backend compile
            # landing during THIS dispatch (mesh step rebuild, cold
            # table build, new bucket shape) is charged to this flush
            # — comp_ms in the ledger, site/flush_seq in /dump_devices
            attr = deviceledger.attr_begin("plane.flush", led[_L_SEQ])
            try:
                # [tracing] profile_dir: bracket the device flight with
                # a jax.profiler capture so device traces line up with
                # the host spans (no-op unless configured)
                prof = tracing.profiler_stop if tracing.profiler_start() \
                    else None
                t_d0 = tracing.monotonic_ns()
                fz.dispatch_fused(plan)
                t_d1 = tracing.monotonic_ns()
                deviceledger.attr_end(attr)
                tracing.flight_begin("plane.flight", fid,
                                     cat="verifyplane", rows=len(rows))
                stamped = bool(getattr(plan, "stamped", False))
                led[_L_STAMP] = STAMP_DEVICE if stamped else STAMP_HOST
                led[_L_DBYTES] = getattr(plan, "delta_bytes", 0)
                self._observe_pack(
                    time.perf_counter() - t0, fz.plan_h2d_bytes(plan),
                    stamp=led[_L_STAMP])
                led[_L_COMP] = round(attr.ms, 3)
                led[_L_UTIL] = plan.util
                if tracing.clock_gen() == led[_L_GEN]:
                    # h2d estimate: the synchronous dispatch wall
                    # (device_put staging + kernel enqueue) net of the
                    # compile time attributed above
                    led[_L_H2D] = round(
                        max((t_d1 - t_d0) / 1e6 - attr.ms, 0.0), 3)
                if plan.mesh is not None:
                    led[_L_PATH] = PATH_FUSED_SHARDED
                    led[_L_NDEV] = plan.n_dev
                    led[_L_DEV0] = plan.devs[0]
                else:
                    led[_L_PATH] = PATH_FUSED
                # warm: did this flush find its valset table cached,
                # or pay the build inline (the post-rotation stall)?
                led[_L_WARM] = 1 if plan.warm else 0
                if not plan.warm and tracing.enabled():
                    tracing.instant("plane.cold_table",
                                    cat="verifyplane", flush=fid,
                                    rows=len(rows))
                led[_L_SMISS] = self._staging.misses - miss0

                def finish():
                    try:
                        out = fz.collect_fused(plan)
                    except Exception:  # noqa: BLE001 - device fault
                        self._breaker.record_failure()
                        _log.exception(
                            "fused verify-plane flush failed in flight; "
                            "host fallback for this flush"
                        )
                        led[_L_PATH] = PATH_FUSED_FALLBACK
                        # the host fallback re-verifies from raw rows:
                        # whatever the device stamped never became a
                        # verdict, so the stamp column degrades with
                        # the path column
                        led[_L_STAMP] = STAMP_HOST
                        # the verdicts below come from the HOST: a
                        # sharded flight that faulted must not keep
                        # claiming cross-chip fan-out (ledger n_dev
                        # and the shard counters/metrics would
                        # disagree with host_fallback — the PR-7 shed
                        # column lesson)
                        led[_L_NDEV] = 1
                        led[_L_DEV0] = 0
                        return _host_verdicts(rows), None
                    finally:
                        if prof is not None:
                            prof()
                    self._breaker.record_success()
                    # device observatory steady declaration: after two
                    # successful fused collects the flush shapes are
                    # compiled — any further backend compile is the
                    # round-5 regression class (compile_storm watches)
                    self._steady_flushes += 1
                    if self._steady_flushes == 2:
                        deviceledger.mark_steady()
                    if plan.mesh is not None:
                        # counted on COLLECT success: only completed
                        # cross-chip passes are attributed sharded
                        self.shard_flushes += 1
                        self.shard_rows += len(rows)
                        if self.metrics is not None:
                            self.metrics.plane_shard_flushes.inc()
                            self.metrics.plane_shard_rows.inc(len(rows))
                    return out

                # the module-attr lookup keeps the probe patchable
                # (the forced-4-device deck test gates it)
                return finish, True, plan.devs, \
                    (lambda: fz.plan_ready(plan))
            except Exception:  # noqa: BLE001 - device fault at dispatch
                deviceledger.attr_end(attr)
                # compiles a FAILED dispatch paid still belong to this
                # flush (the grouped/host fallback below records it)
                led[_L_COMP] = round(attr.ms, 3)
                if prof is not None:
                    prof()  # un-bracket a failed dispatch
                self._breaker.record_failure()
                _log.exception(
                    "fused verify-plane dispatch failed; falling back "
                    "to the grouped path"
                )
        self._observe_pack(time.perf_counter() - t0)
        led[_L_PATH] = PATH_GROUPED if self._use_device else PATH_HOST
        led[_L_SMISS] = self._staging.misses - miss0
        # deferred like the failpoint arm: pack_seconds (and the
        # plane.pack span) cover staging; the host/grouped verify runs
        # inside finish() under its own plane.verify span
        return (lambda: (self._verify_rows(rows), None)), False, \
            None, None

    def _verify_rows(self, rows) -> List[bool]:
        """One padded device pass under the circuit breaker, or the
        inline host path when no accelerator exists. verify_batch_direct
        itself degrades to the host path when the breaker is open or the
        device faults mid-flush."""
        if not self._use_device:
            return _host_verdicts(rows)
        from cometbft_tpu.crypto import batch as cbatch
        from cometbft_tpu.ops import ed25519_kernel as ek

        n = len(rows)
        try:
            waste = ek.bucket_size(n) - n
        except ValueError:
            waste = 0
        self.padding_waste += waste
        if self.metrics is not None:
            self.metrics.plane_padding_waste.inc(waste)
        pubs = [r[0] for r in rows]
        msgs = [r[1] for r in rows]
        sigs = [r[2] for r in rows]
        valid = cbatch.verify_batch_direct(
            pubs, msgs, sigs, kernels=self._kernels, breaker=self._breaker
        )
        return [bool(v) for v in np.asarray(valid)[:n]]

    def _settle(self, batch: List[_Submission], verdicts,
                fused_tallies=None) -> None:
        """Scatter verdicts to futures + fuse the per-group tallies —
        one pass over the flush, so a VoteSet's quorum event fires
        before any submitter even wakes. With fused_tallies (the device
        pass computed the per-group sums) the host adds those instead
        of re-reducing verdicts."""
        now = time.perf_counter()
        if fused_tallies is not None:
            for g, t in fused_tallies.items():
                if t:
                    g.add(t)
        off = 0
        tids = set()
        for sub in batch:
            sl = verdicts[off:off + len(sub.rows)]
            off += len(sub.rows)
            tids.add(sub.tid)
            if fused_tallies is None and sub.counted \
                    and sub.group is not None and all(sl):
                sub.group.add(sub.power)
            self.lane_rows[sub.lane] += len(sub.rows)
            wait_ms = (now - sub.t_submit) * 1000.0
            self.lane_waits[sub.lane].append(wait_ms)
            self.tenants.note_served(sub.tenant, sub.lane,
                                     len(sub.rows), wait_ms)
            if self.metrics is not None:
                self.metrics.plane_wait_seconds.observe(now - sub.t_submit)
                self.metrics.plane_lane_rows.inc(len(sub.rows),
                                                 lane=sub.lane)
            sub.future._resolve(sl)
        self.batches += 1
        self.rows_verified += off
        if self.metrics is not None:
            self.metrics.plane_batch_size.observe(off)
            # breaker_open is sampled at scrape time by
            # NodeMetrics.expose_text (it must stay fresh with the
            # plane idle too), so no push here
        self.dispatch_log.append({
            "rows": off,
            "submissions": len(batch),
            "tids": tids,
        })

    # -- controller actuators (libs/controller) ----------------------------
    # Clamped live setters over the knobs the dispatcher already
    # re-reads every drain cycle (lane_window / lane_deadline /
    # flights) — no dispatcher restart, no queue disturbance. The
    # CONSENSUS lane is structurally off-limits: its window and bounds
    # have no setter path, and the lane is rejected outright, so no
    # control loop can ever create a path that sheds CONSENSUS.

    def set_lane_window_ms(self, lane: str, ms: float) -> float:
        """Retune a SHEDDABLE lane's coalescing window. Returns the
        applied value (ms)."""
        if lane not in SHEDDABLE_LANES:
            raise ValueError(
                f"lane {lane!r} window is not controller-adjustable "
                f"(CONSENSUS bounds are structurally off-limits)")
        w = max(0.0, float(ms)) / 1000.0
        with self._cv:
            self.lane_window[lane] = w
            if lane == LANE_BULK:
                self.bulk_window = w
            else:
                self.gateway_window = w
            self._cv.notify_all()
        return w * 1000.0

    def set_lane_deadline_ms(self, lane: str, ms: float) -> float:
        """Retune a SHEDDABLE lane's shed deadline. A lane configured
        with deadline 0 (shedding disabled) stays disabled — enabling
        shedding is an operator decision, not a controller move."""
        if lane not in self.lane_deadline:
            raise ValueError(
                f"lane {lane!r} has no shed deadline (CONSENSUS is "
                f"never shed)")
        d = max(0.0, float(ms)) / 1000.0
        with self._cv:
            if not self.lane_deadline[lane]:
                return 0.0
            self.lane_deadline[lane] = d
            if lane == LANE_BULK:
                self.bulk_deadline = d
            else:
                self.gateway_deadline = d
        return d * 1000.0

    def set_flights(self, n: int) -> int:
        """Grow/shrink the flight deck within [1, flights_max]. The
        staging pool and mesh halves were sized for flights_max at
        construction, so a live grow never aliases staging buffers;
        a shrink drains excess airborne flights on the next cycle."""
        with self._cv:
            self.flights = min(self.flights_max, max(1, int(n)))
            self._cv.notify_all()
            return self.flights

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            depth = self._depth_locked()
            lane_depths = dict(self._pending_rows)
        return {
            "running": self._running,
            "queue_depth": depth,
            "lane_depths": lane_depths,
            "lane_rows": dict(self.lane_rows),
            "sheds": dict(self.sheds),
            "batches": self.batches,
            "rows_verified": self.rows_verified,
            "padding_waste": self.padding_waste,
            "breaker_state": self._breaker.state,
            "use_device": self._use_device,
            "pack_seconds": self.pack_seconds,
            "h2d_bytes": self.h2d_bytes,
            "overlapped": self.overlapped,
            "flushes_logged": len(self.ledger),
            "mesh_ndev": self.mesh_ndev,
            "shard_flushes": self.shard_flushes,
            "shard_rows": self.shard_rows,
            "flights": self.flights,
            "flights_max": self.flights_max,
            "halves": len(self._halves),
            "deck_airborne": self.deck_airborne,
            "deck_peak": self.deck_peak,
            "tenants": len(self.tenants.tenants()),
        }

    def tenant_depths(self) -> dict:
        """Per-(lane, tenant) pending rows (the quota gate's view)."""
        with self._cv:
            return {lane: dict(t)
                    for lane, t in self._pending_tenant_rows.items()}

    def lane_depths(self) -> dict:
        """Per-lane pending rows (scrape-time gauge source)."""
        with self._cv:
            return dict(self._pending_rows)

    def lane_wait_stats(self) -> dict:
        """Per-lane submit-to-result wall latency percentiles over the
        recent bounded window (real clock — powers the soak harness's
        p99-under-flood assertion and cfg9's report)."""
        from cometbft_tpu.libs.quantiles import wait_summary_ms

        return {lane: wait_summary_ms(waits)
                for lane, waits in self.lane_waits.items()}

    def dump_flushes(self) -> dict:
        """The always-on flush ledger: per-flush records + percentile
        summary (served by /dump_flushes; works after stop() too)."""
        return {
            "running": self._running,
            "summary": self.ledger.summary(),
            "flushes": self.ledger.records(),
        }


# --------------------------------------------------------------------------
# the process-global plane (node lifecycle owns it)
# --------------------------------------------------------------------------

_GLOBAL: Optional[VerifyPlane] = None
# the last plane that was ever global: /dump_flushes and simnet replay
# blobs read its ledger even after the node stopped the plane (the
# ledger is history, and post-mortems happen after shutdown)
_LAST: Optional[VerifyPlane] = None
_GLOBAL_LOCK = threading.Lock()


def set_global_plane(plane: Optional[VerifyPlane]) -> None:
    global _GLOBAL, _LAST
    with _GLOBAL_LOCK:
        _GLOBAL = plane
        if plane is not None:
            _LAST = plane
    # the tenancy registry mirrors the plane (one registry per plane):
    # /dump_tenants and the /metrics tenant families follow whichever
    # plane is mounted, with the same _LAST survival contract
    from cometbft_tpu.verifyplane import tenants as vtenants

    vtenants.set_global_registry(None if plane is None
                                 else plane.tenants)


def clear_global_plane(plane: VerifyPlane) -> None:
    """Unregister `plane` if (and only if) it is the current global —
    a stopping node must not tear down another node's plane."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is plane:
            _GLOBAL = None
    from cometbft_tpu.verifyplane import tenants as vtenants

    vtenants.clear_global_registry(plane.tenants)


def global_plane() -> Optional[VerifyPlane]:
    """The running global plane, or None. Returns None on the plane's
    own dispatcher thread (callers there must verify directly)."""
    p = _GLOBAL
    if p is None or not p.is_running() or p.in_dispatcher():
        return None
    return p


def dump_flushes() -> dict:
    """The flush ledger of the current global plane — or, after a
    stop, of the LAST plane that was global (the ledger survives
    stop(): a post-mortem reads history, not liveness)."""
    p = _GLOBAL or _LAST
    if p is None:
        return {"running": False, "summary": {"flushes": 0},
                "flushes": []}
    return p.dump_flushes()


def ledger_tail(n: int = 8) -> List[str]:
    """Compact tail of the most recent flushes (rides simnet replay
    blobs next to the trace tail)."""
    p = _GLOBAL or _LAST
    return [] if p is None else p.ledger.tail(n)


def flush_stats_for_seqs(seqs) -> dict:
    """Join a set of flush-ledger seqs against the ledger ring: the
    summed WORK milliseconds (pack+flight+collect+settle — queued_ms is
    coalescing wait, not verify-plane work), how many flushes matched,
    and how many of the matched fused flushes paid a COLD table build
    inline. The consensus height ledger calls this once per height to
    attribute verify-plane time; a seq already rotated out of the
    bounded ring simply doesn't contribute (honest undercount, never a
    guess)."""
    p = _GLOBAL or _LAST
    out = {"ms": 0.0, "flushes": 0, "cold": 0}
    if p is None or not seqs:
        return out
    for r in list(p.ledger._ring):
        if r[_L_SEQ] in seqs:
            out["ms"] += (r[_L_PACK] + r[_L_FLIGHT] + r[_L_COLLECT]
                          + r[_L_SETTLE])
            out["flushes"] += 1
            if r[_L_PATH] in (PATH_FUSED, PATH_FUSED_SHARDED) \
                    and not r[_L_WARM]:
                out["cold"] += 1
    out["ms"] = round(out["ms"], 3)
    return out


def ledger_mark() -> tuple:
    """Opaque position marker for :func:`ledger_advanced`: which plane
    the module-level ledger readers currently resolve to, and how far
    its ring has been written. ``_LAST`` is process-global and never
    cleared, so a consumer that only wants flushes from ITS OWN window
    of activity (the simnet replay blob) marks at start and attaches
    the tail only when the ledger moved past the mark."""
    p = _GLOBAL or _LAST
    if p is None:
        return (None, -1)
    ring = p.ledger._ring
    return (id(p), ring[-1][_L_SEQ] if ring else -1)


def ledger_advanced(mark: tuple) -> bool:
    """True when any flush was recorded after ``mark`` (a new plane
    became global, or the marked plane's ring grew)."""
    return ledger_mark() != mark


def plane_batch_fn(lane: str = LANE_CONSENSUS) -> Optional[Callable]:
    """A batch_fn(pubs, msgs, sigs) -> (n,) bool routed through the
    running global plane, or None when no plane is running — callers
    keep their existing direct path in that case. `lane` picks the QoS
    class the rows ride (light-client headers are CONSENSUS; bulk
    callers pass LANE_BULK)."""
    if global_plane() is None:
        return None

    def fn(pubs, msgs, sigs):
        p = global_plane()
        if p is not None:
            try:
                return p.submit_and_wait(pubs, msgs, sigs, lane=lane)
            except PlaneError:
                pass  # stopped/overflowed/shed mid-call: verify directly
        from cometbft_tpu.crypto import batch as cbatch

        return cbatch.verify_batch_direct(pubs, msgs, sigs)

    return fn
