"""Device-fused flush: cached valset table + in-pass quorum tally.

When a flush's submissions all come from quorum groups backed by one
shared validator set (the gossiped-vote burst shape: many validators'
precommits for the same height, grouped per candidate block), the plane
skips the generic grouped dispatch and reuses the cached-valset window
table (ops.ed25519_cached): each signature is scattered to device row
``stride*M + validator_index`` so the kernel's static BlockSpec table
fetch applies, and the per-group voting-power tally is computed by the
SAME device pass (ed25519_kernel.tally_core) that verifies the
signatures — the quorum bit a VoteSet waits on is a kernel output, not
a host reduction.

This is the plane's TPU specialization; it is bypassed on CPU backends
(the interpret-mode cached kernel costs minutes of compile) where the
generic host path in plane._verify_rows serves the same semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

MAX_FUSED_ROWS = 65536


class _Plan:
    """A fully host-side staged fused flush: everything up to (but not
    including) the device dispatch. Splitting plan from execution lets
    the plane consume a circuit-breaker probe slot only when a device
    attempt actually happens (an ineligible flush must not burn the
    breaker's half-open probe). dispatch_fused() then launches the
    kernel WITHOUT fetching (pending holds the in-flight device
    arrays), and collect_fused() blocks for the verdicts — the split
    that lets the plane pack flush k+1 while flush k flies."""

    __slots__ = ("rows", "pos", "batch", "groups", "sub_gid",
                 "counted_pos", "n_commits", "pubs_v", "powers_v",
                 "pending")


def _eligible(batch):
    """All submissions carry validator indices, ed25519 keys only, and
    share ONE valset-backed group family; returns (valset_pubs,
    valset_powers) or None."""
    pubs0 = powers0 = None
    for sub in batch:
        g = sub.group
        if g is None or sub.vidx is None or g.valset_pubs is None:
            return None
        if len(sub.vidx) != len(sub.rows):
            return None
        # the cached window table is ed25519-only; secp/sr valsets take
        # the generic grouped dispatch
        if any(r[0].key_type != "ed25519" or len(r[0].data) != 32
               for r in sub.rows):
            return None
        if pubs0 is None:
            pubs0, powers0 = g.valset_pubs, g.valset_powers
        elif g.valset_pubs is not pubs0 and g.valset_pubs != pubs0:
            return None
    if pubs0 is None:
        return None
    return pubs0, powers0


def plan_fused(batch, pool=None) -> Optional[_Plan]:
    """Host-side staging of the fused cached-table dispatch for a
    flush. Returns a _Plan, or None when the flush shape is ineligible
    — the caller then runs the generic grouped path. No device work
    happens here (dispatch_fused/collect_fused do that, under the
    breaker)."""
    import jax

    if jax.default_backend() == "cpu":
        return None
    valset = _eligible(batch)
    if valset is None:
        return None
    pubs_v, powers_v = valset
    nvals = len(pubs_v)

    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops.ed25519_pallas import _PB

    M = ec.table_pad(nvals)

    # slot assignment: row -> stride*M + vidx, first free stride wins
    # (a validator's vote and its extension land in different strides)
    pubs: List[bytes] = []
    msgs: List[bytes] = []
    sigs: List[bytes] = []
    row_pos: List[int] = []
    counted_pos: List[Optional[int]] = []  # per submission
    occupied: List[set] = []
    groups: List[object] = []
    gid_of: Dict[int, int] = {}
    sub_gid: List[int] = []
    for sub in batch:
        g = sub.group
        gid = gid_of.get(id(g))
        if gid is None:
            gid = gid_of[id(g)] = len(groups)
            groups.append(g)
        sub_gid.append(gid)
        cpos = None
        for k, ((pub, msg, sig), v) in enumerate(zip(sub.rows, sub.vidx)):
            if not (0 <= v < nvals) or pub.data != pubs_v[v] \
                    or len(sig) != 64:
                return None  # wrong key/slot claim: generic path decides
            s = 0
            while s < len(occupied) and v in occupied[s]:
                s += 1
            if s == len(occupied):
                occupied.append(set())
            occupied[s].add(v)
            pos = s * M + v
            pubs.append(pub.data)
            msgs.append(msg)
            sigs.append(sig)
            row_pos.append(pos)
            if k == 0 and sub.counted:
                if sub.power != powers_v[v]:
                    return None  # tally rides the table's power column
                cpos = pos
        counted_pos.append(cpos)
    n = len(pubs)
    B = len(occupied) * M
    if n == 0 or B > MAX_FUSED_ROWS:
        return None

    n_commits = len(groups)
    pbd = ek.pack_batch(pubs, msgs, sigs, pad_to=n)
    pos = np.asarray(row_pos, np.int64)
    # pinned double-buffered staging: the scatter targets and the final
    # packed rows rotate through persistent host buffers per shape (the
    # CALLER's pool — one writer per key; the plane passes its private
    # pool), so packing flush k+1 never touches the memory flush k is
    # still uploading from
    if pool is None:
        from cometbft_tpu.crypto.batch import staging_pool

        pool = staging_pool()
    ry = pool.get("fused.ry", (B, pbd.ry.shape[1]), pbd.ry.dtype)
    ry[pos] = pbd.ry[:n]
    rsign = pool.get("fused.rsign", (B,), np.int32)
    rsign[pos] = np.asarray(pbd.rsign[:n], np.int32)
    sdig = pool.get("fused.sdig", (B, pbd.sdig.shape[1]), pbd.sdig.dtype)
    sdig[pos] = pbd.sdig[:n]
    hdig = pool.get("fused.hdig", (B, pbd.hdig.shape[1]), pbd.hdig.dtype)
    hdig[pos] = pbd.hdig[:n]
    precheck = pool.get("fused.precheck", (B,), np.bool_)
    precheck[pos] = np.asarray(pbd.precheck[:n], np.bool_)
    counted = pool.get("fused.counted", (B,), np.bool_)
    commit_ids = pool.get("fused.cid", (B,), np.int32)
    cur = 0
    for sub, gid, cpos in zip(batch, sub_gid, counted_pos):
        for p in row_pos[cur:cur + len(sub.rows)]:
            commit_ids[p] = gid
        cur += len(sub.rows)
        if cpos is not None:
            counted[cpos] = True
    thresh = np.zeros((n_commits, ek.TALLY_LIMBS), np.int32)
    for gid, g in enumerate(groups):
        thresh[gid] = ek.threshold_limbs(max(g.threshold - 1, 0))[0]

    pb = _PB(None, None, ry, rsign, sdig, hdig, precheck)
    out = pool.get("fused.rows", ec.packed_rows_shape(B, n_commits),
                   np.int32)
    plan = _Plan()
    plan.rows = ec.pack_rows_cached(pb, counted, commit_ids, thresh,
                                    out=out)
    plan.pos = pos
    plan.batch = batch
    plan.groups = groups
    plan.sub_gid = sub_gid
    plan.counted_pos = counted_pos
    plan.n_commits = n_commits
    plan.pubs_v = pubs_v
    plan.powers_v = powers_v
    plan.pending = None
    return plan


def plan_h2d_bytes(plan: _Plan) -> int:
    """Bytes this flush stages to the device (the packed rows; the
    valset table is device-resident and uploads once per valset)."""
    return int(plan.rows.nbytes)


def dispatch_fused(plan: _Plan) -> None:
    """Launch a staged plan on the device WITHOUT fetching: fetch the
    (device-resident, valset-keyed) window table and enqueue the fused
    verify+tally kernel. Returns as soon as the dispatch is in flight
    (JAX async dispatch) so the caller can pack the next flush. Raises
    on dispatch-time device faults (the caller's breaker handles
    those). The rows buffer is dead once the kernel has read it, and
    the staging pool rotation guarantees the host copy isn't reused
    until this flight lands."""
    from cometbft_tpu.ops import ed25519_cached as ec

    # pubs_v/powers_v are the QuorumGroup's immutable tuples, so the
    # content-key digest is identity-memoized (no per-flush O(valset)
    # hashing) and a steady-state flush never re-uploads the valset
    table = ec.table_for_pubs(plan.pubs_v, plan.powers_v)
    plan.pending = ec.verify_tally_rows_cached(
        plan.rows, table, plan.n_commits
    )


def collect_fused(plan: _Plan) -> Tuple[List[bool], Dict[object, int]]:
    """Block for a dispatched plan's results and gate the tallies per
    submission. Raises on in-flight device faults.

    Returns (per-row verdicts in flush order, {group: verified power
    tallied by the device this flush})."""
    from cometbft_tpu.ops import ed25519_kernel as ek

    valid, tally, _quorum = plan.pending
    valid = np.asarray(valid)
    tallies_raw = ek.tally_to_int(np.asarray(tally))

    verdicts = [bool(v) for v in valid[plan.pos]]
    tallies: Dict[object, int] = {
        g: int(tallies_raw[gid]) for gid, g in enumerate(plan.groups)
    }
    # submission gating: power counts only when EVERY row of a counted
    # submission verified (a valid vote with a forged extension is
    # rejected by the caller, so its power must not stand in the tally)
    off = 0
    for sub, gid, cpos in zip(plan.batch, plan.sub_gid,
                              plan.counted_pos):
        sl = verdicts[off:off + len(sub.rows)]
        off += len(sub.rows)
        if cpos is not None and sl[0] and not all(sl):
            tallies[plan.groups[gid]] -= sub.power
    return verdicts, tallies
