"""Device-fused flush: cached valset table + in-pass quorum tally.

When a flush's submissions all come from quorum groups backed by one
shared validator set (the gossiped-vote burst shape: many validators'
precommits for the same height, grouped per candidate block), the plane
skips the generic grouped dispatch and reuses the cached-valset window
table (ops.ed25519_cached): each signature is scattered to device row
``stride*M + validator_index`` so the kernel's static BlockSpec table
fetch applies, and the per-group voting-power tally is computed by the
SAME device pass (ed25519_kernel.tally_core) that verifies the
signatures — the quorum bit a VoteSet waits on is a kernel output, not
a host reduction.

Multichip ([verify_plane] mesh knobs): when the plane is configured
with a >1-device mesh, plan_fused lays the scattered rows out in
per-device blocks (validator v of stride s lands at
``d*B_loc + s*M_s + (v mod M_s)`` with d = v // M_s — shard_positions
is the one home of that math), the valset window table is
device-resident PER SHARD (ed25519_cached.sharded_table_for_pubs), and
dispatch_fused launches parallel/mesh.sharded_fused_verify: each chip
verifies its validators' signatures against its local table shard and
the voting-power tally psum-reduces ON DEVICE, so the quorum bit is
still a kernel output — one cross-chip pass for a 100k-validator
commit (a single chip's table budget caps at 65536 validator slots).

Pipelined mesh halves ([verify_plane] pipeline_flights): the plane's
flight deck keeps up to K flushes airborne at once on DISJOINT
sub-meshes. half_meshes splits the flush mesh into two halves on the
same device-prefix seam effective_mesh clamps through, and plan_fused
carries the size-aware fan-out policy: a small flush rides the free
half (its psum reduces over that half alone — every one of its rows
and its whole table shard set live there, so the quorum bit is exact),
while a flush past the half's per-device budget (or over the
half_mesh_rows knob) takes the full mesh and sets ``drain_first`` so
the dispatcher lands the airborne deck before dispatching it.
plan_ready is the non-blocking landing probe that lets the deck settle
flights out of order.

This is the plane's TPU specialization; it is bypassed on CPU backends
(the interpret-mode cached kernel costs minutes of compile) where the
generic host path in plane._verify_rows serves the same semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

MAX_FUSED_ROWS = 65536  # per-device rows budget (B_loc when sharded)

# Test seam: tier-1 has no accelerator, so the sharded plumbing is
# proven on a forced multi-device CPU host with the expensive kernels
# stubbed (tests/test_zshardplane_smoke.py flips this in a subprocess).
# Production CPU backends stay on the host path — interpret-mode Pallas
# costs minutes per compile.
ALLOW_CPU_FUSED = False

# Device-side sign-bytes stamping (ISSUE 19): template-eligible flushes
# ship (device-resident template, per-row deltas) and the stamping
# prologue rebuilds the packed rows on device. Module-level toggle +
# setter (the validation._TEMPLATE_PACK pattern) so the config plumbs
# it and the differential tests force either path.
DEVICE_STAMP = True


def set_device_stamping(on: bool) -> None:
    global DEVICE_STAMP
    DEVICE_STAMP = bool(on)


# jax-free replicas of the packed-row layout constants, for the staging
# byte-budget arithmetic below (cfg19_smoke runs with no jax import;
# tests cross-check these against ed25519_cached.V_THRESH /
# ed25519_kernel.TALLY_LIMBS in a jax-enabled process)
_V_THRESH_REPLICA = 27
_TALLY_LIMBS_REPLICA = 6


def delta_slot_specs(B: int) -> dict:
    """name -> (shape, itemsize) of the staging slots a DEVICE-STAMPED
    flush of B rows occupies: raw signatures, the (secs_lo, secs_hi,
    nanos) timestamp words, and the packed live/counted/template/commit
    flags. Pure arithmetic — the cfg19_smoke byte budget."""
    return {"fused.dsig": ((B, 64), 1),
            "fused.dts": ((B, 3), 4),
            "fused.dflags": ((B,), 4)}


def legacy_slot_specs(B: int, n_commits: int = 1) -> dict:
    """name -> (shape, itemsize) of the staging slots a HOST-PACKED
    flush of B rows occupies (the scatter buffers plus the packed rows
    the device actually reads)."""
    t_rows = max(1, -(-(n_commits * _TALLY_LIMBS_REPLICA) // B))
    return {"fused.ry": ((B, 20), 4),
            "fused.rsign": ((B,), 4),
            "fused.sdig": ((B, 64), 4),
            "fused.hdig": ((B, 64), 4),
            "fused.precheck": ((B,), 1),
            "fused.counted": ((B,), 1),
            "fused.cid": ((B,), 4),
            "fused.rows": ((_V_THRESH_REPLICA + t_rows, B), 4)}


def specs_bytes(specs: dict) -> int:
    total = 0
    for shape, itemsize in specs.values():
        n = itemsize
        for d in shape:
            n *= d
        total += n
    return total


class _Plan:
    """A fully host-side staged fused flush: everything up to (but not
    including) the device dispatch. Splitting plan from execution lets
    the plane consume a circuit-breaker probe slot only when a device
    attempt actually happens (an ineligible flush must not burn the
    breaker's half-open probe). dispatch_fused() then launches the
    kernel WITHOUT fetching (pending holds the in-flight device
    arrays), and collect_fused() blocks for the verdicts — the split
    that lets the plane pack flush k+1 while flush k flies."""

    __slots__ = ("rows", "pos", "batch", "groups", "sub_gid",
                 "counted_pos", "n_commits", "pubs_v", "powers_v",
                 "pending", "mesh", "n_dev", "thresh", "devs",
                 "drain_first", "warm", "util",
                 # device-stamped delta staging: `stamped` selects the
                 # path, `delta` holds the (sig, ts, flags) staging
                 # buffers, `sites` the StampSites in template-id
                 # order, `delta_bytes` the staged delta footprint
                 # (rows is None on this path)
                 "stamped", "delta", "sites", "delta_bytes")


def _eligible(batch):
    """All submissions carry validator indices, ed25519 keys only, and
    share ONE valset-backed group family; returns (valset_pubs,
    valset_powers) or None."""
    pubs0 = powers0 = None
    for sub in batch:
        g = sub.group
        if g is None or sub.vidx is None or g.valset_pubs is None:
            return None
        if len(sub.vidx) != len(sub.rows):
            return None
        # the cached window table is ed25519-only; secp/sr valsets take
        # the generic grouped dispatch
        if any(r[0].key_type != "ed25519" or len(r[0].data) != 32
               for r in sub.rows):
            return None
        if pubs0 is None:
            pubs0, powers0 = g.valset_pubs, g.valset_powers
        elif g.valset_pubs is not pubs0 and g.valset_pubs != pubs0:
            return None
    if pubs0 is None:
        return None
    return pubs0, powers0


def _stamp_sites(stamp_meta, row_gid, max_sites: int):
    """Template-id assignment + device-stamp eligibility for a flush.

    Returns (StampSites in template-id order, per-row template ids) or
    None when the flush must fall back to host packing: a row without
    stamp metadata (non-vote rows — e.g. extension rows), timestamp
    words outside the staged int32 layout, more than the
    for-block/for-nil template pair among one commit's rows, or more
    template families than the staged flags' 8-bit id field."""
    ids: List[int] = []
    sites: List[object] = []
    idx_of: Dict[object, int] = {}
    per_gid: Dict[int, set] = {}
    for st, gid in zip(stamp_meta, row_gid):
        if st is None:
            return None
        tpl, secs, nanos = st
        if not (-2**31 <= nanos < 2**31 and -2**63 <= secs < 2**63):
            return None
        site = tpl.stamp_site()
        key = site.key
        tid = idx_of.get(key)
        if tid is None:
            if len(sites) >= max_sites:
                return None
            tid = idx_of[key] = len(sites)
            sites.append(site)
        gset = per_gid.setdefault(gid, set())
        gset.add(key)
        if len(gset) > 2:
            return None  # mixed block_ids past the for-block/nil pair
        ids.append(tid)
    return tuple(sites), ids


def shard_positions(vidx, strides, m_shard: int,
                    n_strides: int) -> np.ndarray:
    """Row positions for the fused flush layout, single- or multi-chip.

    Validator v of stride s lands at ``d*B_loc + s*m_shard +
    (v mod m_shard)`` where d = v // m_shard owns the validator's table
    shard and B_loc = n_strides*m_shard is one device's slice width.
    With one device m_shard is the whole padded valset and this
    degenerates to the classic ``s*M + v``. Pure numpy — cfg11's smoke
    exercises it with no jax in the process."""
    v = np.asarray(vidx, np.int64)
    s = np.asarray(strides, np.int64)
    b_loc = n_strides * m_shard
    return (v // m_shard) * b_loc + s * m_shard + (v % m_shard)


# the plane's flush mesh, memoized per requested device count (mesh
# identity feeds the step/table memos downstream — a fresh Mesh per
# flush would defeat them)
_MESH_MEMO: dict = {}


def plane_mesh(devices: int):
    """Resolve the verify plane's flush mesh: 0 = all local devices,
    N caps at the first N. Returns None when fewer than 2 devices are
    usable — single-device dispatch is strictly better then."""
    import jax

    from cometbft_tpu.parallel import mesh as pm

    devs = jax.devices()
    n = len(devs) if not devices else min(int(devices), len(devs))
    if n < 2:
        return None
    m = _MESH_MEMO.get(n)
    if m is None:
        m = _MESH_MEMO[n] = pm.make_mesh(devs[:n])
    return m


# sub-meshes over a mesh's devices, memoized by the exact device tuple
# (effective_mesh clamps through prefixes; half_meshes slices the same
# memo into the deck's disjoint halves)
_SUBMESH_MEMO: dict = {}


def _sub_mesh_devs(devs: tuple):
    from cometbft_tpu.parallel import mesh as pm

    m = _SUBMESH_MEMO.get(devs)
    if m is None:
        m = _SUBMESH_MEMO[devs] = pm.make_mesh(list(devs))
    return m


def _sub_mesh(mesh, n_eff: int):
    return _sub_mesh_devs(tuple(mesh.devices.flat)[:n_eff])


def half_meshes(mesh) -> list:
    """The flush mesh split into two DISJOINT halves for the pipelined
    flight deck: lower half = device prefix, upper half = the rest.
    Each half needs >= 2 devices to run the sharded fused program
    pinned to its own chips, so meshes under 4 devices return [] and
    the deck degrades to classic single-flight dispatch."""
    if mesh is None or mesh.devices.size < 4:
        return []
    devs = tuple(mesh.devices.flat)
    mid = len(devs) // 2
    return [_sub_mesh_devs(devs[:mid]), _sub_mesh_devs(devs[mid:])]


def effective_mesh(mesh, nvals: int):
    """Clamp a flush mesh to the devices this valset actually fills.

    shard_stride rounds the per-shard slice up to a table_pad bucket,
    and the coarse buckets can leave trailing shards EMPTY — e.g. 10k
    validators over 8 devices takes a 4096-slot stride, so devices 3-7
    would stage, transfer, and verify pure padding on every flush with
    no correctness benefit. Shrinks the fan-out until every shard
    holds validators (fixpoint of n_eff = ceil(nvals / m_s)).

    Returns (mesh-or-None, n_dev, m_shard); None means single-device
    dispatch is strictly better (the whole valset fits one stride).
    Raises ValueError when the valset exceeds even the full mesh's
    table budget."""
    from cometbft_tpu.ops import ed25519_cached as ec

    if mesh is None:
        return None, 1, ec.shard_stride(nvals, 1)
    n_eff = int(mesh.devices.size)
    while True:
        m_s = ec.shard_stride(nvals, n_eff)
        need = -(-max(nvals, 1) // m_s)
        if need >= n_eff:
            break
        n_eff = need
    if n_eff < 2:
        return None, 1, ec.shard_stride(nvals, 1)
    if n_eff < mesh.devices.size:
        mesh = _sub_mesh(mesh, n_eff)
    return mesh, n_eff, m_s


def plan_fused(batch, pool=None, mesh=None, half=None,
               half_max_rows: int = 0) -> Optional[_Plan]:
    """Host-side staging of the fused cached-table dispatch for a
    flush. Returns a _Plan, or None when the flush shape is ineligible
    — the caller then runs the generic grouped path. No device work
    happens here (dispatch_fused/collect_fused do that, under the
    breaker). `mesh` (a >1-device parallel.mesh Mesh) selects the
    sharded cross-chip layout; None is the single-device path.

    `half` is the flight deck's fan-out offer: a free sub-mesh half
    the flush should prefer so it can fly while the other half carries
    an airborne flight. The size-aware policy lives here because only
    the plan knows the flush's true shape: the half is taken when the
    valset and stride count fit its per-device budget AND the flush is
    under `half_max_rows` (0 = budget-only); otherwise the flush takes
    the full `mesh` and the plan's ``drain_first`` flag tells the
    dispatcher to land the airborne deck before dispatching it."""
    import jax

    if jax.default_backend() == "cpu" and not ALLOW_CPU_FUSED:
        return None
    valset = _eligible(batch)
    if valset is None:
        return None
    pubs_v, powers_v = valset
    nvals = len(pubs_v)

    from cometbft_tpu.ops import ed25519_cached as ec
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops.ed25519_pallas import _PB
    from cometbft_tpu.types import canonical

    # slot assignment: first free stride wins (a validator's vote and
    # its extension land in different strides); positions are computed
    # AFTER the walk — the per-device slice width depends on the final
    # stride count when the valset is sharded
    pubs: List[bytes] = []
    msgs: List[bytes] = []
    sigs: List[bytes] = []
    row_v: List[int] = []
    row_s: List[int] = []
    row_gid: List[int] = []
    stamp_meta: List[Optional[tuple]] = []  # (template, secs, nanos)
    counted_ridx: List[Optional[int]] = []  # per submission: row index
    occupied: List[set] = []
    groups: List[object] = []
    gid_of: Dict[int, int] = {}
    sub_gid: List[int] = []
    for sub in batch:
        g = sub.group
        gid = gid_of.get(id(g))
        if gid is None:
            gid = gid_of[id(g)] = len(groups)
            groups.append(g)
        sub_gid.append(gid)
        cidx = None
        stamps = getattr(sub, "stamp", None)
        for k, ((pub, msg, sig), v) in enumerate(zip(sub.rows, sub.vidx)):
            if not (0 <= v < nvals) or pub.data != pubs_v[v] \
                    or len(sig) != 64:
                return None  # wrong key/slot claim: generic path decides
            s = 0
            while s < len(occupied) and v in occupied[s]:
                s += 1
            if s == len(occupied):
                occupied.append(set())
            occupied[s].add(v)
            pubs.append(pub.data)
            msgs.append(msg)
            sigs.append(sig)
            row_v.append(v)
            row_s.append(s)
            row_gid.append(gid)
            stamp_meta.append(stamps[k] if stamps is not None
                              and k < len(stamps) else None)
            if k == 0 and sub.counted:
                if sub.power != powers_v[v]:
                    return None  # tally rides the table's power column
                cidx = len(row_v) - 1
        counted_ridx.append(cidx)
    n = len(pubs)
    n_strides = len(occupied)
    if n == 0:
        return None

    # fan-out policy. The rows budget is PER DEVICE: each chip runs
    # the kernel on its B/n_dev slice, so a sharded flush scales the
    # cap with the mesh — a half offers half the budget at half the
    # dispatch footprint. effective_mesh clamps either choice to the
    # devices the valset actually fills.
    def _fit(m):
        m2, nd, ms = effective_mesh(m, nvals)
        if n_strides * ms > MAX_FUSED_ROWS:
            raise ValueError("flush over the per-device rows budget")
        return m2, nd, ms

    chosen = None
    took_full = False
    if half is not None and (not half_max_rows or n <= half_max_rows):
        try:
            chosen = _fit(half)
        except ValueError:
            chosen = None  # giant flush: the full mesh decides below
    if chosen is None:
        took_full = half is not None
        try:
            chosen = _fit(mesh)
        except ValueError:
            return None  # over even the full mesh's table budget
    mesh, n_dev, M = chosen
    B = n_dev * n_strides * M

    n_commits = len(groups)
    pos = shard_positions(row_v, row_s, M, n_strides)
    counted_pos = [None if ci is None else int(pos[ci])
                   for ci in counted_ridx]
    # pinned double-buffered staging: the scatter targets and the final
    # packed rows rotate through persistent host buffers per shape (the
    # CALLER's pool — one writer per key; the plane passes its private
    # pool), so packing flush k+1 never touches the memory flush k is
    # still uploading from
    if pool is None:
        from cometbft_tpu.crypto.batch import staging_pool

        pool = staging_pool()
    thresh = np.zeros((n_commits, ek.TALLY_LIMBS), np.int32)
    for gid, g in enumerate(groups):
        thresh[gid] = ek.threshold_limbs(max(g.threshold - 1, 0))[0]

    plan = _Plan()
    stamp = (_stamp_sites(stamp_meta, row_gid, ec.MAX_TEMPLATE_SITES)
             if DEVICE_STAMP else None)
    if stamp is not None:
        # device-stamped delta staging: ship 80 B/row — raw signature,
        # (secs_lo, secs_hi, nanos) words, packed flags — and let the
        # device prologue rebuild the packed rows next to the resident
        # template. Slot layout mirrors delta_slot_specs; the pool's
        # zero fill makes unoccupied lanes live=0, which the prologue
        # expands to the same all-zero columns host packing pads with.
        sites, site_ids = stamp
        sec_a = np.fromiter((st[1] for st in stamp_meta), np.int64,
                            count=n)
        nan_a = np.fromiter((st[2] for st in stamp_meta), np.int64,
                            count=n)
        ts_rows = canonical.split_ts_words(sec_a, nan_a)
        fl_rows = np.ones((n,), np.int32)
        fl_rows |= np.asarray(site_ids, np.int32) << 2
        fl_rows |= np.asarray(row_gid, np.int32) << 10
        for ci in counted_ridx:
            if ci is not None:
                fl_rows[ci] |= 2
        dsig = pool.get("fused.dsig", (B, 64), np.uint8)
        dsig[pos] = np.frombuffer(b"".join(sigs), np.uint8) \
            .reshape(n, 64)
        dts = pool.get("fused.dts", (B, 3), np.int32)
        dts[pos] = ts_rows
        dfl = pool.get("fused.dflags", (B,), np.int32)
        dfl[pos] = fl_rows
        plan.rows = None
        plan.stamped = True
        plan.delta = (dsig, dts, dfl)
        plan.sites = sites
        plan.delta_bytes = int(dsig.nbytes + dts.nbytes + dfl.nbytes)
    else:
        # legacy full-row host pack — bit-live as the differential
        # oracle and the fallback for non-template-eligible flushes
        pbd = ek.pack_batch(pubs, msgs, sigs, pad_to=n)
        ry = pool.get("fused.ry", (B, pbd.ry.shape[1]), pbd.ry.dtype)
        ry[pos] = pbd.ry[:n]
        rsign = pool.get("fused.rsign", (B,), np.int32)
        rsign[pos] = np.asarray(pbd.rsign[:n], np.int32)
        sdig = pool.get("fused.sdig", (B, pbd.sdig.shape[1]),
                        pbd.sdig.dtype)
        sdig[pos] = pbd.sdig[:n]
        hdig = pool.get("fused.hdig", (B, pbd.hdig.shape[1]),
                        pbd.hdig.dtype)
        hdig[pos] = pbd.hdig[:n]
        precheck = pool.get("fused.precheck", (B,), np.bool_)
        precheck[pos] = np.asarray(pbd.precheck[:n], np.bool_)
        counted = pool.get("fused.counted", (B,), np.bool_)
        commit_ids = pool.get("fused.cid", (B,), np.int32)
        cur = 0
        for sub, gid, cpos in zip(batch, sub_gid, counted_pos):
            for p in pos[cur:cur + len(sub.rows)]:
                commit_ids[p] = gid
            cur += len(sub.rows)
            if cpos is not None:
                counted[cpos] = True

        pb = _PB(None, None, ry, rsign, sdig, hdig, precheck)
        # sharded: thresholds ride as a separate REPLICATED kernel
        # argument (the in-rows threshold rows would shard into
        # per-device fragments) so the packed rows carry a zero
        # threshold row; single-device keeps packing them into the
        # rows as before
        pack_thresh = None if mesh is not None else thresh
        out = pool.get(
            "fused.rows",
            ec.packed_rows_shape(B, 1 if mesh is not None else n_commits),
            np.int32)
        plan.rows = ec.pack_rows_cached(pb, counted, commit_ids,
                                        pack_thresh, out=out)
        plan.stamped = False
        plan.delta = None
        plan.sites = None
        plan.delta_bytes = 0
    plan.pos = pos
    plan.batch = batch
    plan.groups = groups
    plan.sub_gid = sub_gid
    plan.counted_pos = counted_pos
    plan.n_commits = n_commits
    plan.pubs_v = pubs_v
    plan.powers_v = powers_v
    plan.pending = None
    plan.mesh = mesh
    plan.n_dev = n_dev
    plan.thresh = thresh
    # device ids this flush will occupy (None = single-device): the
    # deck's disjointness bookkeeping and the ledger's dev0 column
    plan.devs = (None if mesh is None
                 else tuple(int(d.id) for d in mesh.devices.flat))
    plan.drain_first = took_full
    # did the dispatch find its valset table cached? (set by
    # dispatch_fused; the plane stamps it into the ledger's warm
    # column so post-rotation cold builds are attributable)
    plan.warm = False
    # rows-x-cost utilization: the fraction of the staged device pass
    # doing real work (n live rows over the B padded slots the kernel
    # sweeps across the whole fan-out) — the ledger's util column, so
    # cfg11/cfg12 report how much of the mesh a flush actually used
    plan.util = round(n / B, 4) if B else 0.0
    return plan


def plan_ready(plan: _Plan) -> bool:
    """Non-blocking landing probe for a dispatched plan: True when
    every in-flight output array is ready to fetch. The deck lands
    ready flights out of order (no head-of-line blocking when flight
    k+1 finishes before flight k); False — including when the runtime
    offers no probe — means the caller falls back to FIFO landing."""
    p = plan.pending
    if p is None:
        return True
    try:
        return all(bool(a.is_ready()) for a in p)
    except Exception:  # noqa: BLE001 - no readiness probe: FIFO lands
        return False


def plan_h2d_bytes(plan: _Plan) -> int:
    """Bytes this flush stages to the device (the packed rows, or the
    per-row delta buffers when device-stamped; the valset table and
    template are device-resident and upload once per valset/family)."""
    if plan.stamped:
        return int(plan.delta_bytes)
    return int(plan.rows.nbytes)


def dispatch_fused(plan: _Plan) -> None:
    """Launch a staged plan on the device WITHOUT fetching: fetch the
    (device-resident, valset-keyed) window table and enqueue the fused
    verify+tally kernel. Returns as soon as the dispatch is in flight
    (JAX async dispatch) so the caller can pack the next flush. Raises
    on dispatch-time device faults (the caller's breaker handles
    those). The rows buffer is dead once the kernel has read it, and
    the staging pool rotation guarantees the host copy isn't reused
    until this flight lands.

    With a mesh plan, the rows stage straight to the batch
    NamedSharding (one device_put, no host resharding inside the
    jitted step), the table comes from the per-shard device-resident
    cache, and the tally psums across the mesh — the quorum bit is
    still a kernel output."""
    from cometbft_tpu.ops import ed25519_cached as ec

    if plan.mesh is None:
        # pubs_v/powers_v are the QuorumGroup's immutable tuples, so the
        # content-key digest is identity-memoized (no per-flush O(valset)
        # hashing) and a steady-state flush never re-uploads the valset
        table, plan.warm = ec.table_for_pubs_info(plan.pubs_v,
                                                  plan.powers_v)
        if plan.stamped:
            ent = ec.template_entry(plan.sites)
            dsig, dts, dfl = plan.delta
            plan.pending = ec.verify_tally_delta_cached(
                dsig, dts, dfl, ent, table, plan.n_commits, plan.thresh
            )
        else:
            plan.pending = ec.verify_tally_rows_cached(
                plan.rows, table, plan.n_commits
            )
        return
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cometbft_tpu.parallel import mesh as pm

    table, plan.warm = ec.sharded_table_for_pubs_info(
        plan.pubs_v, plan.powers_v, plan.mesh)
    axis = plan.mesh.axis_names[0]
    thresh_d = jax.device_put(
        plan.thresh, NamedSharding(plan.mesh, P(None, None)))
    if plan.stamped:
        # per-shard stamping: each device expands ITS rows slice from
        # its delta slice + the replicated template + its own pub_raw
        # shard — shard_positions already placed every row on the
        # device owning its validator, so the stamped slices bit-match
        # the single-device oracle's slices
        ent = ec.template_entry(plan.sites)
        step = pm.sharded_stamped_verify(plan.mesh, plan.n_commits,
                                         ent.msg_max)
        dsig, dts, dfl = plan.delta
        row_sh = NamedSharding(plan.mesh, P(axis, None))
        lane_sh = NamedSharding(plan.mesh, P(axis))
        repl = NamedSharding(plan.mesh, P())
        plan.pending = step(
            jax.device_put(dsig, row_sh), jax.device_put(dts, row_sh),
            jax.device_put(dfl, lane_sh),
            jax.device_put(ent.pre_mat, repl),
            jax.device_put(ent.pre_len, repl),
            jax.device_put(ent.suf_mat, repl),
            jax.device_put(ent.suf_len, repl),
            jax.device_put(ent.ts_tag, repl),
            table.pub_raw, table.tab, table.ok, table.power5,
            ec.base60_repl(plan.mesh), thresh_d)
        return
    step = pm.sharded_fused_verify(plan.mesh, plan.n_commits)
    rows_d = jax.device_put(
        plan.rows, NamedSharding(plan.mesh, P(None, axis)))
    plan.pending = step(rows_d, table.tab, table.ok, table.power5,
                        ec.base60_repl(plan.mesh), thresh_d)


def collect_fused(plan: _Plan) -> Tuple[List[bool], Dict[object, int]]:
    """Block for a dispatched plan's results and gate the tallies per
    submission. Raises on in-flight device faults.

    Returns (per-row verdicts in flush order, {group: verified power
    tallied by the device this flush})."""
    from cometbft_tpu.ops import ed25519_kernel as ek

    valid, tally, _quorum = plan.pending
    valid = np.asarray(valid)
    tallies_raw = ek.tally_to_int(np.asarray(tally))

    verdicts = [bool(v) for v in valid[plan.pos]]
    tallies: Dict[object, int] = {
        g: int(tallies_raw[gid]) for gid, g in enumerate(plan.groups)
    }
    # submission gating: power counts only when EVERY row of a counted
    # submission verified (a valid vote with a forged extension is
    # rejected by the caller, so its power must not stand in the tally)
    off = 0
    for sub, gid, cpos in zip(plan.batch, plan.sub_gid,
                              plan.counted_pos):
        sl = verdicts[off:off + len(sub.rows)]
        off += len(sub.rows)
        if cpos is not None and sl[0] and not all(sl):
            tallies[plan.groups[gid]] -= sub.power
    return verdicts, tallies
