"""Verify plane: cross-caller continuous batching for signature verify.

The device is a shared service: every verification consumer (gossiped
votes, vote extensions, light-client commits, crypto.batch callers)
submits items to one always-on scheduler that coalesces them into padded
bucket batches, flushes on a micro-batch deadline or a full bucket, and
fuses per-group voting-power tallies into the same pass.
"""
from cometbft_tpu.verifyplane.plane import (
    LANE_BULK,
    LANE_CONSENSUS,
    LANE_GATEWAY,
    LANES,
    SHEDDABLE_LANES,
    FlushLedger,
    PlaneError,
    PlaneOverloaded,
    PlaneQueueFull,
    PlaneStopped,
    QuorumGroup,
    VerifyFuture,
    VerifyPlane,
    clear_global_plane,
    dump_flushes,
    global_plane,
    ledger_advanced,
    ledger_mark,
    ledger_tail,
    plane_batch_fn,
    set_global_plane,
)

__all__ = [
    "LANE_BULK",
    "LANE_CONSENSUS",
    "LANE_GATEWAY",
    "LANES",
    "SHEDDABLE_LANES",
    "FlushLedger",
    "PlaneError",
    "PlaneOverloaded",
    "PlaneQueueFull",
    "PlaneStopped",
    "QuorumGroup",
    "VerifyFuture",
    "VerifyPlane",
    "clear_global_plane",
    "dump_flushes",
    "global_plane",
    "ledger_advanced",
    "ledger_mark",
    "ledger_tail",
    "plane_batch_fn",
    "set_global_plane",
]
