"""Verify plane: cross-caller continuous batching for signature verify.

The device is a shared service: every verification consumer (gossiped
votes, vote extensions, light-client commits, crypto.batch callers)
submits items to one always-on scheduler that coalesces them into padded
bucket batches, flushes on a micro-batch deadline or a full bucket, and
fuses per-group voting-power tallies into the same pass.
"""
from cometbft_tpu.verifyplane.plane import (
    DEFAULT_TENANT,
    LANE_BULK,
    LANE_CONSENSUS,
    LANE_GATEWAY,
    LANES,
    SHEDDABLE_LANES,
    FlushLedger,
    PlaneError,
    PlaneOverloaded,
    PlaneQueueFull,
    PlaneStopped,
    QuorumGroup,
    VerifyFuture,
    VerifyPlane,
    clear_global_plane,
    dump_flushes,
    flush_stats_for_seqs,
    global_plane,
    ledger_advanced,
    ledger_mark,
    ledger_tail,
    plane_batch_fn,
    set_global_plane,
)
from cometbft_tpu.verifyplane.tenants import (
    TenantOverloaded,
    TenantRegistry,
    dump_tenants,
    global_registry,
    last_registry,
)
from cometbft_tpu.verifyplane.warmer import (
    TableWarmer,
    clear_global_warmer,
    global_warmer,
    notify_next_valset,
    set_global_warmer,
)

__all__ = [
    "DEFAULT_TENANT",
    "LANE_BULK",
    "LANE_CONSENSUS",
    "LANE_GATEWAY",
    "LANES",
    "SHEDDABLE_LANES",
    "FlushLedger",
    "PlaneError",
    "PlaneOverloaded",
    "PlaneQueueFull",
    "PlaneStopped",
    "QuorumGroup",
    "TableWarmer",
    "TenantOverloaded",
    "TenantRegistry",
    "VerifyFuture",
    "VerifyPlane",
    "clear_global_plane",
    "clear_global_warmer",
    "global_warmer",
    "notify_next_valset",
    "set_global_warmer",
    "dump_flushes",
    "dump_tenants",
    "flush_stats_for_seqs",
    "global_plane",
    "global_registry",
    "last_registry",
    "ledger_advanced",
    "ledger_mark",
    "ledger_tail",
    "plane_batch_fn",
    "set_global_plane",
]
