"""Async next-epoch valset table warmer.

The cached-table verify path amortizes the expensive A-side curve work
over a long-lived validator set (ops/ed25519_cached) — which means the
FIRST commit after an epoch rotation pays the whole table build
(~seconds at 10k validators) inline on the verify path: a visible
post-rotation stall on a chain that re-elects every few hours
(PAPERS.md arXiv 2004.12990; arXiv 2302.00418's per-epoch signer set
is exactly what the batch verifier amortizes over).

The warmer closes that gap: when state/execution.py applies validator
updates and computes the epoch e+1 set (`_update_state` ->
:func:`notify_next_valset`), a background thread builds e+1's window
table — and, when the verify plane runs a multichip mesh, its sharded
per-device tables too — while epoch e is still live. The build lands
in the same bounded caches every verifier reads (ops/table_cache), so
the first post-rotation flush is a straight LRU hit; table_cache marks
the key and the hit is attributed honestly (``warmed_hits``).

Failure containment (the warmer is an OPTIMIZATION and must never be
load-bearing):

  * the ``warmer.build`` failpoint (and any build exception) degrades
    to the cold path — the failure is counted, nothing is inserted,
    live-epoch verdicts are untouched;
  * a device breaker already OPEN skips the build (a faulting device
    must not be hammered with a multi-second table program while the
    host fallback carries consensus);
  * ``stop()`` mid-warm abandons cleanly — the dispatcher never waits
    on the warmer, so a wedged build can at worst waste its own
    thread;
  * the build path uses build_table/device_put only — it NEVER touches
    the verify plane's private staging pool (one-writer-per-key
    rotation contract), so a warm can't race the dispatcher's buffers;
  * requests are a latest-wins slot of depth 1: back-to-back rotations
    supersede an unstarted older request instead of queueing stale
    epochs.

No jax import at module level: the warmer object (and everything
cfg13_smoke / the tier-1 tests drive) is host-only until a real build
runs.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing

_log = logging.getLogger(__name__)

fp.register("warmer.build",
            "top of a next-epoch table-warmer build (raise = build "
            "fault; the warm is abandoned and the first post-rotation "
            "flush takes the cold path — live verdicts unaffected)")


class TableWarmer:
    """Background builder of next-epoch valset tables.

    `build_fn(pubs, powers)` overrides the real device build (tests,
    cfg13_smoke); the default builds through ed25519_cached into the
    shared bounded caches. `mesh_fn()` resolves the verify plane's
    flush mesh (default: the global plane's already-resolved mesh) so
    a multichip node warms its sharded tables too. `breaker` defaults
    to the process device breaker; `use_device=None` auto-detects an
    accelerator like the verify plane does (no accelerator and no
    injected build_fn = every request skips: a CPU interpret build
    costs minutes and warms nothing worth having)."""

    def __init__(self, build_fn: Optional[Callable] = None,
                 mesh_fn: Optional[Callable] = None,
                 breaker=None, use_device: Optional[bool] = None):
        self._build_fn = build_fn
        self._mesh_fn = mesh_fn
        self._breaker = breaker
        self._use_device = use_device
        self._cv = threading.Condition()
        self._req: Optional[tuple] = None   # latest-wins (pubs, powers)
        self._building = False
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # accounting (sampled into /metrics at scrape time).
        # builds_incremental counts the ok-builds the cache satisfied
        # by patching a near-miss table's delta rows (ed25519_cached
        # update_table) instead of the full next-epoch build — the
        # epoch-churn fast path; always <= builds_ok.
        self.builds_ok = 0
        self.builds_failed = 0
        self.builds_skipped = 0
        # the subset of skips refused by a tenant's HBM residency
        # budget (verifyplane/tenants.py warm gate); always <= skipped
        self.builds_skipped_quota = 0
        self.builds_incremental = 0
        self.superseded = 0
        self.last_build_ms = 0.0
        # device stamping templates actually BUILT here (ISSUE 19):
        # warm_template is a no-op on a cached entry, so this counts
        # real prefetches only — same honesty rule as table marks
        self.tmpl_warms = 0
        self._tmpl_req: Optional[tuple] = None  # latest-wins sites

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="valset-warmer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop accepting requests and join. A build in flight is
        abandoned to its own (daemon) thread rather than waited out —
        node shutdown must never block on a device table program."""
        with self._cv:
            if not self._running:
                return
            self._running = False
            self._req = None
            self._tmpl_req = None
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def is_running(self) -> bool:
        return self._running

    # -- requests ----------------------------------------------------------

    def request(self, pubs, powers,
                chain_id: Optional[str] = None) -> None:
        """Warm the table for (pubs, powers). Latest-wins: an unstarted
        older request is superseded (epoch e+2 announced before e+1's
        build began means e+1's table would be dead on arrival).
        `chain_id` attributes the warm to the owning tenant
        (verifyplane/tenants.py): the build is gated on the tenant's
        HBM residency budget and the built table's owner is recorded
        for per-tenant residency accounting."""
        pubs = tuple(pubs)
        powers = None if powers is None else tuple(powers)
        with self._cv:
            if not self._running:
                return
            if self._req is not None:
                self.superseded += 1
            self._req = (pubs, powers, chain_id)
            self._cv.notify_all()

    def request_template(self, sites) -> None:
        """Warm the device stamping template for `sites` (a tuple of
        canonical.StampSite — ISSUE 19). Latest-wins like table
        requests, built on the warmer thread through
        ed25519_cached.warm_template, which inserts into the bounded
        template cache and warm-marks ONLY when the entry was absent
        (the PR 11 honest-mark rule: a flush that already paid the
        build inline must not credit the warmer). Best-effort by
        design — a flush racing the same cold entry just builds it
        itself."""
        sites = tuple(sites)
        with self._cv:
            if not self._running or not sites:
                return
            self._tmpl_req = sites
            self._cv.notify_all()

    def request_valset(self, vals,
                       chain_id: Optional[str] = None) -> None:
        """Warm for a types.validator.ValidatorSet. Column extraction
        happens HERE on the caller's thread (O(n), ~ms at 10k): the set
        keeps mutating (proposer-priority rotation) after apply_block
        returns, but keys and powers — all the table depends on — do
        not."""
        self.request(tuple(v.pub_key.data for v in vals.validators),
                     tuple(v.voting_power for v in vals.validators),
                     chain_id=chain_id)

    # -- the build loop ----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._running and self._req is None \
                        and self._tmpl_req is None:
                    self._cv.wait(timeout=0.25)
                if not self._running:
                    return
                # tables first: a template entry is a few KB of encode
                # work, the table is the multi-second program the
                # rotation stall is made of
                req, self._req = self._req, None
                tmpl_req, self._tmpl_req = self._tmpl_req, None
                self._building = True
            try:
                if req is not None:
                    self._build(*req)
                if tmpl_req is not None:
                    self._warm_template(tmpl_req)
            finally:
                with self._cv:
                    self._building = False
                    self._cv.notify_all()

    def _warm_template(self, sites: tuple) -> None:
        """Template prefetch (never load-bearing: any failure is a
        cold-path degrade, and a breaker-open device is left alone
        exactly like table builds)."""
        if self._breaker_open() or not self._device_ok():
            self.builds_skipped += 1
            return
        try:
            from cometbft_tpu.ops import ed25519_cached as ec

            if ec.warm_template(sites):
                self.tmpl_warms += 1
        except Exception:  # noqa: BLE001 - prefetch fault: cold path
            self.builds_failed += 1
            _log.exception(
                "stamping-template warm failed (%d sites); the next "
                "delta flush builds it inline", len(sites))

    def _breaker_open(self) -> bool:
        brk = self._breaker
        if brk is None:
            try:
                from cometbft_tpu.crypto import batch as cbatch

                brk = cbatch.device_breaker()
            except Exception:  # noqa: BLE001 - no crypto stack: skip
                return False
        return brk.state == "open"

    def _device_ok(self) -> bool:
        if self._use_device is not None:
            return self._use_device
        from cometbft_tpu.crypto import batch as cbatch

        return bool(cbatch._accel_backend())

    def _build(self, pubs: tuple, powers: Optional[tuple],
               chain_id: Optional[str] = None) -> None:
        try:
            fp.fail_point("warmer.build")
        except Exception:  # noqa: BLE001 - injected fault: cold path
            self.builds_failed += 1
            _log.exception(
                "valset warmer build fault (%d validators); next "
                "rotation takes the cold path", len(pubs))
            return
        if self._breaker_open():
            # the device is already degraded: the host fallback is
            # carrying consensus and a table build would hammer the
            # very device the breaker is resting
            self.builds_skipped += 1
            return
        if not self._tenant_allows(chain_id, len(pubs)):
            # residency-budget refusal: the tenant's cold tables were
            # already evicted (its own retired epochs go first) and the
            # warm STILL would not fit — skip, count, cold path. The
            # live epoch keeps verifying; only the prefetch is denied.
            self.builds_skipped += 1
            self.builds_skipped_quota += 1
            return
        t0 = time.perf_counter()
        try:
            if self._build_fn is not None:
                self._build_fn(pubs, powers)
            elif self._device_ok():
                self._build_default(pubs, powers, chain_id)
            else:
                self.builds_skipped += 1
                return
        except Exception:  # noqa: BLE001 - build fault: cold path
            self.builds_failed += 1
            _log.exception(
                "valset warmer build failed (%d validators); next "
                "rotation takes the cold path", len(pubs))
            return
        self.last_build_ms = round((time.perf_counter() - t0) * 1000, 3)
        self.builds_ok += 1
        tracing.instant("warmer.built", cat="verifyplane",
                        vals=len(pubs), ms=self.last_build_ms)

    def _tenant_allows(self, chain_id: Optional[str],
                       nvals: int) -> bool:
        """The tenant residency gate: a warm for a budgeted tenant that
        would breach its HBM residency budget is refused — AFTER one
        attempt to make room by evicting the tenant's own cold tables
        (the noisy-neighbor contract: a tenant over budget loses its
        retired epochs first, never another tenant's tables). No
        registry / no chain_id / unbudgeted tenant = always allowed."""
        if chain_id is None:
            return True
        from cometbft_tpu.verifyplane import tenants as vtenants

        reg = vtenants.global_registry()
        if reg is None:
            return True
        est = vtenants.estimate_table_bytes(nvals)
        if reg.warm_allowed(chain_id, est):
            return True
        reg.evict_cold_tables(chain_id)
        if reg.warm_allowed(chain_id, est):
            return True
        reg.note_warm_skip(chain_id)
        return False

    def _build_default(self, pubs: tuple, powers: Optional[tuple],
                       chain_id: Optional[str] = None) -> None:
        """The real device build: the plain table, plus the sharded
        per-device tables when the plane runs a mesh. Inserts ride the
        shared bounded caches (LRU: the LIVE epoch's table is the most
        recently used, so this insert can only evict retired epochs).

        Warm marks are only set for tables this warmer actually BUILT:
        if consensus already paid the cold build inline (the rotation
        landed before the warm ran), the lookup here is a hit and
        marking it would falsely credit the warmer for a stall that
        happened (warmed_hits is the honest-signal counter cfg13 and
        /metrics attribution rely on). Best-effort: when a dispatcher
        flush and this warm race the SAME cold build concurrently
        (both miss, both build), the warmer's miss still marks — a
        single-flight build lock isn't worth buying for a stats
        counter's once-per-rotation race window."""
        from cometbft_tpu.ops import ed25519_cached as ec
        from cometbft_tpu.ops import table_cache as tcache

        key = ec._cache_key(pubs, powers)
        if chain_id is not None:
            # residency attribution: the registry's read-time walk of
            # the live caches resolves this content key to its tenant
            from cometbft_tpu.verifyplane import tenants as vtenants

            reg = vtenants.global_registry()
            if reg is not None:
                reg.note_table_owner(key, chain_id)
        # PEEK before looking up: the consuming hit path would pop a
        # still-pending warm mark (a repeat notify for an identical
        # valset — e.g. a power re-set to its current value — must not
        # let the warmer consume its own mark and count a warmed_hit
        # no verifier ever saw)
        with tcache.LOCK:
            present = key in tcache.TABLES
        if not present:
            # the lookup itself prefers the incremental path: a small
            # change set patches a cached near-miss table's delta rows
            # (update_table) instead of the full build. The stat delta
            # attributes it — this warm was an epoch-churn patch, not
            # a from-scratch table program.
            with tcache.LOCK:
                inc0 = tcache.STATS["incremental_patches"]
            _, hit = ec.table_for_pubs_info(pubs, powers)
            if not hit:
                ec.note_warmed(key)
                with tcache.LOCK:
                    if tcache.STATS["incremental_patches"] > inc0:
                        self.builds_incremental += 1
        meshes = self._mesh_targets(len(pubs))
        if meshes:
            from cometbft_tpu.parallel import mesh as pm

            for mesh in meshes:
                mkey = pm._mesh_key(mesh)
                with tcache.LOCK:
                    present = (key, mkey) in tcache.SHARDS
                if present:
                    continue
                _, hit = ec.sharded_table_for_pubs_info(pubs, powers,
                                                        mesh)
                if not hit:
                    # distinct mark per (family, mesh): the plain and
                    # per-half sharded lookups each attribute their
                    # own first post-rotation hit
                    ec.note_warmed((key, "shard", mkey))

    def _mesh_targets(self, nvals: int) -> list:
        """The meshes post-rotation sharded flushes will ACTUALLY look
        tables up under. The dispatcher clamps every fused flush
        through fused.effective_mesh, and with the flight deck's
        halves configured, steady flushes ride a HALF mesh — so the
        warm must target the clamped halves (both), not the full
        resolved mesh, or its key never matches a flush's lookup and
        the cold build is paid anyway. Without halves it's the
        effective full mesh. (A drain-first giant flush over the
        half budget still takes the full mesh and may build cold —
        visible in the ledger's warm column.)"""
        meshes = []
        if self._mesh_fn is not None:
            m = self._mesh_fn()
            if m is not None:
                meshes = [m]
        else:
            from cometbft_tpu.verifyplane import plane as vp

            p = vp._GLOBAL
            if p is not None and p._mesh_resolved \
                    and p._mesh is not None:
                meshes = list(p._halves) or [p._mesh]
        if not meshes:
            return []
        from cometbft_tpu.verifyplane import fused as fz

        out = []
        for m in meshes:
            try:
                eff, _, _ = fz.effective_mesh(m, nvals)
            except ValueError:
                continue  # valset over this mesh's table budget
            if eff is not None and all(eff is not o for o in out):
                out.append(eff)
        return out

    # -- observability -----------------------------------------------------

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no request is pending or building (tests and the
        cfg13 bench use this to measure the warmed path honestly)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._req is not None or self._tmpl_req is not None \
                    or self._building:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=left)
        return True

    def stats(self) -> dict:
        with self._cv:
            pending = self._req is not None \
                or self._tmpl_req is not None or self._building
        return {
            "running": self._running,
            "pending": pending,
            "builds_ok": self.builds_ok,
            "builds_failed": self.builds_failed,
            "builds_skipped": self.builds_skipped,
            "builds_skipped_quota": self.builds_skipped_quota,
            "builds_incremental": self.builds_incremental,
            "superseded": self.superseded,
            "last_build_ms": self.last_build_ms,
            "tmpl_warms": self.tmpl_warms,
        }


# --------------------------------------------------------------------------
# the process-global warmer (node lifecycle owns it)
# --------------------------------------------------------------------------

_GLOBAL: Optional[TableWarmer] = None
# the last warmer ever global: /metrics samples its counters after the
# node stopped it (post-mortems read history) — the _LAST-plane pattern
_LAST: Optional[TableWarmer] = None
_LOCK = threading.Lock()


def set_global_warmer(w: Optional[TableWarmer]) -> None:
    global _GLOBAL, _LAST
    with _LOCK:
        _GLOBAL = w
        if w is not None:
            _LAST = w


def clear_global_warmer(w: TableWarmer) -> None:
    """Unregister `w` iff it is the current global — a stopping node
    must not tear down another node's warmer."""
    global _GLOBAL
    with _LOCK:
        if _GLOBAL is w:
            _GLOBAL = None


def global_warmer() -> Optional[TableWarmer]:
    w = _GLOBAL
    if w is None or not w.is_running():
        return None
    return w


def last_warmer() -> Optional[TableWarmer]:
    return _GLOBAL or _LAST


def notify_next_valset(vals, chain_id: Optional[str] = None) -> None:
    """state/execution.py's seam: called with the epoch e+1 validator
    set whenever a block's validator updates produced one. A cheap
    no-op when no warmer is registered (simnet determinism: no warmer
    runs there unless a test mounts one). `chain_id` attributes the
    warm to the owning tenant on a shared multi-chain plane."""
    w = global_warmer()
    if w is not None:
        w.request_valset(vals, chain_id=chain_id)
