"""Multi-tenant verify plane: the tenancy registry.

ROADMAP item 7's appchain-hosting story: ONE device plane serving the
signature work of MANY small chains at the cost of one. Committee
verification dominates small-committee chains (PAPERS.md arXiv
2302.00418) — exactly the workload that wastes a dedicated accelerator
per chain — and the FPGA verification engines for permissioned chains
(arXiv 2112.02229) already multiplex one shared hardware verifier
across clients. The plane's flush path needs almost nothing to join
them: commit ids are flush-local and the tally psum never cared which
chain a QuorumGroup came from, so a fused flush can carry rows from K
chains as long as something OWNS the fairness and capacity questions.
That something is this module:

  * every submission is keyed by ``(chain_id, lane)`` — the plane's
    submit paths thread ``chain_id`` through and tag the submission
    with its tenant;
  * a :class:`TenantRegistry` holds per-tenant quotas (pending-row
    quota over the sheddable lanes, HBM residency budget over the
    valset tables the tenant's chains pin) and the per-tenant
    accounting surfaces (/dump_tenants, /metrics top-K);
  * the dispatcher's sheddable drain consults :meth:`drain_order` for
    a deterministic fair-share rotation: when several tenants queue in
    one lane, each gets an equal slice of the flush budget and the
    rotation cursor advances every drain cycle, so no tenant parks at
    the head of the FIFO forever;
  * noisy-neighbor overflow follows the existing overload contract —
    a tenant past its row quota sheds its GATEWAY/BULK work with an
    explicit retry-hinted :class:`TenantOverloaded` verdict (a
    subclass of PlaneOverloaded, so every existing isinstance arm —
    the mempool's explicit-verdict dispatch, lightgate's overload
    reply — keeps working unchanged) and gets its COLD tables evicted
    first; CONSENSUS is structurally out of reach of every tenant
    gate, exactly like the lane wall.

Residency attribution: the bounded table caches (ops/table_cache) key
tables by valset content digest, which says nothing about chains — so
the registry keeps a bounded ``owner`` map (content key -> chain_id)
written by whoever builds or warms a table for a known tenant, and
:func:`residency_by_tenant` walks the live cache under the cache's own
LOCK attributing each resident table's bytes to its owner (unowned
tables fall to the ``default`` tenant). Attribution is computed at
READ time from the cache's truth, never double-entry bookkeeping — an
LRU eviction can't leak a stale per-tenant charge.

No jax import anywhere: the registry, the quota gates, and the cold
eviction all run on the tier-1 host (test_ztenant_smoke asserts it).
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from cometbft_tpu.verifyplane.plane import (
    DEFAULT_TENANT, LANES, PlaneOverloaded, ms_to_us)

# per-tenant submit-to-result samples kept for the wait percentiles
TENANT_WAIT_WINDOW = 1024
# bounded (content key -> chain_id) owner map: table_cache caps TABLES
# at a handful of entries, so 64 owners comfortably covers every live
# key plus churn headroom without growing with chain count
OWNER_MAP_MAX = 64
# top-K tenants sampled into /metrics by activity (the ping_rtt_ms
# cardinality discipline: hundreds of chains must not mint hundreds of
# label sets per scrape)
METRICS_TOP_K = 8
# window-table residency estimate for the warm budget gate: the
# device-side per-validator cost of one cached window table (tab rows
# + ok/power columns), rounded up — the gate only needs the right
# order of magnitude to refuse a warm that would blow the budget
EST_TABLE_BYTES_PER_VAL = 2048


class TenantOverloaded(PlaneOverloaded):
    """Explicit per-tenant quota shed verdict: the tenant is past its
    pending-row quota on a sheddable lane. Subclasses PlaneOverloaded
    so the existing overload arms (mempool's OVERLOADED CheckTx code,
    lightgate's 503) handle it unchanged; carries the tenant so shed
    storms attribute to the neighbor that caused them."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0,
                 tenant: str = ""):
        super().__init__(msg, retry_after_ms=retry_after_ms)
        self.tenant = tenant


class _Tenant:
    """One registered chain: quotas + the per-tenant accounting the
    dump and /metrics read. Mutated under the registry lock only."""

    __slots__ = ("chain_id", "row_quota", "residency_budget",
                 "lane_rows", "lane_sheds", "warm_skips",
                 "cold_evictions", "waits", "registered_ms",
                 "device_us", "comp_us", "h2d_us", "delta_bytes")

    def __init__(self, chain_id: str, row_quota: int = 0,
                 residency_budget: int = 0, registered_ms: float = 0.0):
        self.chain_id = chain_id
        # 0 = unlimited (the single-tenant plane behaves exactly as
        # before this subsystem existed)
        self.row_quota = max(0, int(row_quota))
        self.residency_budget = max(0, int(residency_budget))
        self.lane_rows = {lane: 0 for lane in LANES}
        self.lane_sheds = {lane: 0 for lane in LANES}
        self.warm_skips = 0
        self.cold_evictions = 0
        self.waits: deque = deque(maxlen=TENANT_WAIT_WINDOW)
        self.registered_ms = registered_ms
        # device-time chargeback (ISSUE 20): integer MICROseconds so
        # the conservation cross-check (reconcile_device) is exact
        # integer equality against the flush ledger — the ledger's ms
        # columns are rounded to 3 decimals, so ms_to_us is lossless
        self.device_us = 0
        self.comp_us = 0
        self.h2d_us = 0
        self.delta_bytes = 0

    @property
    def rows_total(self) -> int:
        return sum(self.lane_rows.values())

    @property
    def sheds_total(self) -> int:
        return sum(self.lane_sheds.values())


class TenantRegistry:
    """The tenancy control surface one plane owns: registration (auto
    on first submission, explicit for quota-carrying tenants), the
    fair-share rotation cursor, per-tenant accounting, the bounded
    table-owner map, and eviction with a retired-totals accumulator so
    the /metrics counters stay monotone after a tenant leaves (the
    PR-14 drop-ring lesson, applied before it bites)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: "OrderedDict[str, _Tenant]" = OrderedDict()
        self._owners: "OrderedDict" = OrderedDict()  # key -> chain_id
        self._cursor = 0
        self.evicted = 0
        # totals folded in when a tenant is evicted from the registry:
        # the scrape's tenant="_retired" series accumulates these, so
        # sum(tenant_rows_total) never regresses across an eviction
        self.retired = {"rows": 0, "sheds": 0, "warm_skips": 0,
                        "cold_evictions": 0, "device_us": 0,
                        "comp_us": 0, "h2d_us": 0, "delta_bytes": 0}

    # -- registration ------------------------------------------------------

    def register(self, chain_id: str, row_quota: Optional[int] = None,
                 residency_budget: Optional[int] = None) -> None:
        """Register (or retune) a tenant. Quotas left None keep their
        current value; a never-seen tenant starts unlimited (0)."""
        from cometbft_tpu.libs import tracing

        chain_id = str(chain_id)
        with self._lock:
            t = self._tenants.get(chain_id)
            if t is None:
                t = self._tenants[chain_id] = _Tenant(
                    chain_id,
                    registered_ms=round(tracing.monotonic_ns() / 1e6, 3))
            if row_quota is not None:
                t.row_quota = max(0, int(row_quota))
            if residency_budget is not None:
                t.residency_budget = max(0, int(residency_budget))

    def _touch(self, chain_id: str) -> _Tenant:
        """Lock held: the auto-registration seam every accounting path
        rides — the first submission from a chain creates its tenant."""
        t = self._tenants.get(chain_id)
        if t is None:
            from cometbft_tpu.libs import tracing

            t = self._tenants[chain_id] = _Tenant(
                chain_id,
                registered_ms=round(tracing.monotonic_ns() / 1e6, 3))
        return t

    def evict(self, chain_id: str) -> bool:
        """Drop a tenant from the registry, folding its counted totals
        into the retired accumulator (monotone /metrics across the
        eviction) and releasing its owner-map entries."""
        with self._lock:
            t = self._tenants.pop(chain_id, None)
            if t is None:
                return False
            self.evicted += 1
            self.retired["rows"] += t.rows_total
            self.retired["sheds"] += t.sheds_total
            self.retired["warm_skips"] += t.warm_skips
            self.retired["cold_evictions"] += t.cold_evictions
            self.retired["device_us"] += t.device_us
            self.retired["comp_us"] += t.comp_us
            self.retired["h2d_us"] += t.h2d_us
            self.retired["delta_bytes"] += t.delta_bytes
            for key in [k for k, c in self._owners.items()
                        if c == chain_id]:
                del self._owners[key]
        return True

    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._tenants)

    def row_quota(self, chain_id: str) -> int:
        """The tenant's pending-row quota (0 = unlimited). Read-only:
        an UNKNOWN chain is unlimited and is NOT auto-registered here
        — the hot submit path must not take a registration write for
        every probe."""
        with self._lock:
            t = self._tenants.get(chain_id)
            return t.row_quota if t is not None else 0

    # -- fair-share rotation ----------------------------------------------

    def drain_order(self, names) -> List[str]:
        """Deterministic fair-share order for one drain cycle: the
        (sorted) tenant names rotated by a cursor that advances every
        call — with K tenants queued, each spends 1/K of the cycles at
        the head, so the tenant drained first (and the one whose tail
        rows wait for the next flush) rotates instead of being
        whichever chain_id sorts lowest forever."""
        names = sorted(names)
        if not names:
            return names
        with self._lock:
            off = self._cursor % len(names)
            self._cursor += 1
        return names[off:] + names[:off]

    # -- accounting (the plane's settle/shed paths) ------------------------

    def note_served(self, chain_id: str, lane: str, rows: int,
                    wait_ms: float) -> None:
        with self._lock:
            t = self._touch(chain_id)
            t.lane_rows[lane] = t.lane_rows.get(lane, 0) + int(rows)
            t.waits.append(float(wait_ms))

    def note_shed(self, chain_id: str, lane: str, n: int = 1) -> None:
        with self._lock:
            t = self._touch(chain_id)
            t.lane_sheds[lane] = t.lane_sheds.get(lane, 0) + int(n)

    def note_warm_skip(self, chain_id: str) -> None:
        with self._lock:
            self._touch(chain_id).warm_skips += 1

    def note_device(self, chain_id: str, comp_us: int, h2d_us: int,
                    dev_us: int, delta_bytes: int) -> None:
        """Charge one flush's (split) device-time share to a tenant,
        with integer microseconds from split_device_columns, so the sum
        over tenants equals the ledger record exactly (no float fold)."""
        self.note_device_shares(
            ((chain_id, comp_us, h2d_us, dev_us, delta_bytes),))

    def note_device_shares(self, shares) -> None:
        """Batched note_device over one flush's split shares — ONE lock
        acquisition for the whole fused batch. This is the plane's
        _charge_flush path, bound by the per-flush hook budget
        (bench.cost_hooks_bookkeeping_us, tier-1-asserted < 10 us)."""
        with self._lock:
            for chain_id, comp_us, h2d_us, dev_us, delta_bytes in shares:
                t = self._touch(chain_id)
                t.comp_us += int(comp_us)
                t.h2d_us += int(h2d_us)
                t.device_us += int(dev_us)
                t.delta_bytes += int(delta_bytes)

    def device_totals(self) -> dict:
        """Registry-wide device-time totals, live + retired, in the
        accumulators' native integer microseconds. The conservation
        invariant: these equal the flush ledger's column sums over the
        same window (reconcile_device asserts it, cfg20 embeds it)."""
        with self._lock:
            tot = {"comp_us": self.retired["comp_us"],
                   "h2d_us": self.retired["h2d_us"],
                   "device_us": self.retired["device_us"],
                   "delta_bytes": self.retired["delta_bytes"]}
            for t in self._tenants.values():
                tot["comp_us"] += t.comp_us
                tot["h2d_us"] += t.h2d_us
                tot["device_us"] += t.device_us
                tot["delta_bytes"] += t.delta_bytes
            return tot

    # -- residency ---------------------------------------------------------

    def note_table_owner(self, key, chain_id: str) -> None:
        """Record that the cached table under `key` belongs to
        `chain_id` (the warmer and any tenant-aware builder call this
        when they build for a known chain). Bounded latest-wins."""
        with self._lock:
            self._owners[key] = str(chain_id)
            self._owners.move_to_end(key)
            while len(self._owners) > OWNER_MAP_MAX:
                self._owners.popitem(last=False)

    def table_owner(self, key) -> str:
        with self._lock:
            return self._owners.get(key, DEFAULT_TENANT)

    def residency_by_tenant(self) -> Dict[str, dict]:
        """{tenant: {bytes, tables}} over the LIVE table caches,
        attributed through the owner map at read time (never
        double-entry: the cache's own contents are the truth, so an
        LRU eviction can't strand a stale charge). The device ledger's
        family x device accounting was pre-plumbed for exactly this
        walk — /dump_devices grows the same block."""
        from cometbft_tpu.ops import table_cache as tc

        with self._lock:
            owners = dict(self._owners)
        out: Dict[str, dict] = {}
        with tc.LOCK:
            items = (list(tc.TABLES._od.items())
                     + [(k[0], v) for k, v in tc.SHARDS._od.items()])
            sizes = [(k, tc.default_size(v)) for k, v in items]
        for key, nb in sizes:
            chain = owners.get(key, DEFAULT_TENANT)
            slot = out.setdefault(chain, {"bytes": 0, "tables": 0})
            slot["bytes"] += nb
            slot["tables"] += 1
        return out

    def warm_allowed(self, chain_id: str, est_bytes: int) -> bool:
        """The warmer's budget gate: would a build of `est_bytes` push
        this tenant past its residency budget? Unbudgeted (0) tenants
        always pass. A refused warm is counted (note_warm_skip is the
        caller's job — the gate itself is a pure read) and the
        tenant's cold tables are evicted first so the NEXT warm can
        fit."""
        with self._lock:
            t = self._tenants.get(chain_id)
            budget = t.residency_budget if t is not None else 0
        if not budget:
            return True
        used = self.residency_by_tenant().get(
            chain_id, {"bytes": 0})["bytes"]
        return used + max(0, int(est_bytes)) <= budget

    def evict_cold_tables(self, chain_id: str) -> int:
        """Evict this tenant's COLD cached tables — every owned entry
        except the most-recently-used one (the live epoch a flush may
        be using right now; the LRU order is the coldness order). The
        noisy-neighbor contract's 'cold tables evicted first': an
        over-budget tenant loses its own retired epochs before any
        other tenant loses anything."""
        from cometbft_tpu.ops import table_cache as tc

        with self._lock:
            owned = {k for k, c in self._owners.items()
                     if c == chain_id}
        if not owned:
            return 0
        evicted = 0
        with tc.LOCK:
            # oldest-first walk; keep the newest owned plain table
            mine = [k for k in tc.TABLES._od if k in owned]
            for key in mine[:-1]:
                tc.TABLES.pop(key)
                evicted += 1
            keep = set(mine[-1:])
            for skey in [k for k in tc.SHARDS._od
                         if k[0] in owned and k[0] not in keep]:
                tc.SHARDS.pop(skey)
                evicted += 1
        if evicted:
            with self._lock:
                self._touch(chain_id).cold_evictions += evicted
        return evicted

    # -- surfaces ----------------------------------------------------------

    def dump(self) -> dict:
        """The /dump_tenants document: registry + quotas + per-tenant
        rows/sheds/residency/wait percentiles + the retired totals."""
        from cometbft_tpu.libs.quantiles import wait_summary_ms

        res = self.residency_by_tenant()
        with self._lock:
            rows = {}
            for name, t in self._tenants.items():
                rows[name] = {
                    "row_quota": t.row_quota,
                    "residency_budget": t.residency_budget,
                    "lane_rows": dict(t.lane_rows),
                    "rows": t.rows_total,
                    "lane_sheds": dict(t.lane_sheds),
                    "sheds": t.sheds_total,
                    "warm_skips": t.warm_skips,
                    "cold_evictions": t.cold_evictions,
                    "wait_ms": wait_summary_ms(t.waits),
                    "registered_ms": t.registered_ms,
                    # device-time chargeback columns (ms rendered from
                    # the exact integer-us accumulators)
                    "device_ms": round(t.device_us / 1000.0, 3),
                    "comp_ms": round(t.comp_us / 1000.0, 3),
                    "h2d_ms": round(t.h2d_us / 1000.0, 3),
                    "delta_bytes": t.delta_bytes,
                }
            doc = {
                "tenants": rows,
                "registry_size": len(self._tenants),
                "evicted": self.evicted,
                "retired": dict(self.retired),
                "owner_keys": len(self._owners),
            }
        for name, slot in res.items():
            doc["tenants"].setdefault(name, {})["residency"] = slot
        return doc

    def metrics_rows(self, k: int = METRICS_TOP_K) -> dict:
        """The scrape-time sample: top-K tenants by CUMULATIVE rows
        (cumulative ranking keeps counter series stable — a tenant's
        series appears when it earns top-K and starts at its true
        running total, which is monotone) plus the retired totals the
        ``_retired`` series accumulates."""
        with self._lock:
            ranked = sorted(self._tenants.values(),
                            key=lambda t: (-t.rows_total, t.chain_id))
            top = {t.chain_id: {"rows": t.rows_total,
                                "sheds": t.sheds_total,
                                "device_ms": round(t.device_us / 1000.0,
                                                   3)}
                   for t in ranked[:max(1, int(k))]}
            return {"top": top, "retired": dict(self.retired),
                    "registry_size": len(self._tenants)}


# --------------------------------------------------------------------------
# the process-global registry: mirrors the global plane (plane.py's
# set_global_plane installs the mounted plane's registry here), with
# the same _LAST survival contract every other dump surface honors —
# /dump_tenants serves history after the node stopped.
# --------------------------------------------------------------------------

_GLOBAL: Optional[TenantRegistry] = None
_LAST: Optional[TenantRegistry] = None
_LOCK = threading.Lock()


def set_global_registry(reg: Optional[TenantRegistry]) -> None:
    global _GLOBAL, _LAST
    with _LOCK:
        _GLOBAL = reg
        if reg is not None:
            _LAST = reg


def clear_global_registry(reg: TenantRegistry) -> None:
    """Unregister `reg` iff it is the current global — a stopping node
    must not tear down another node's tenancy registry."""
    global _GLOBAL
    with _LOCK:
        if _GLOBAL is reg:
            _GLOBAL = None


def global_registry() -> Optional[TenantRegistry]:
    return _GLOBAL


def last_registry() -> Optional[TenantRegistry]:
    return _GLOBAL or _LAST


def dump_tenants() -> dict:
    """The registry of the current global plane — or, after a stop,
    of the LAST one (the registry is history, like the flush ledger)."""
    reg = _GLOBAL or _LAST
    if reg is None:
        return {"tenants": {}, "registry_size": 0, "evicted": 0,
                "retired": {"rows": 0, "sheds": 0, "warm_skips": 0,
                            "cold_evictions": 0, "device_us": 0,
                            "comp_us": 0, "h2d_us": 0,
                            "delta_bytes": 0},
                "owner_keys": 0}
    return reg.dump()


def reconcile_device(records, registry: TenantRegistry) -> dict:
    """Exact-accounting cross-check (the HBM reconcile() discipline,
    applied to time): sum the flush ledger's device columns over
    `records` (dicts from FlushLedger.records()) and compare against
    the registry's live+retired per-tenant accumulators. While the
    ledger ring still holds every charged flush (and no other plane
    fed the registry), every drift is EXACTLY zero — integer us, no
    tolerance band. cfg20 embeds this; a unit test drives it across
    evict()/retirement."""
    led = {"comp_us": 0, "h2d_us": 0, "device_us": 0, "delta_bytes": 0}
    for r in records:
        if not r.get("tenants"):
            continue  # tenantless record: nothing was charged
        led["comp_us"] += ms_to_us(r["comp_ms"])
        led["h2d_us"] += ms_to_us(r["h2d_ms"])
        led["device_us"] += ms_to_us(r["dev_ms"])
        led["delta_bytes"] += int(r["delta_bytes"])
    reg = registry.device_totals()
    return {
        "ledger": led,
        "registry": reg,
        "drift": {k: reg[k] - led[k] for k in led},
    }


def estimate_table_bytes(n_vals: int) -> int:
    """The warm gate's size estimate for an n-validator window table."""
    return max(0, int(n_vals)) * EST_TABLE_BYTES_PER_VAL
