"""Remote signer: keep validator keys in a separate process (HSM shape).

Reference: privval/signer_listener_endpoint.go:223 (the NODE listens and
the signer dials in — the usual HSM deployment), signer_client.go (the
PrivValidator proxy the consensus engine holds), signer_server.go +
signer_dialer_endpoint.go (the key-holding side).

Protocol: the JSON length-prefixed framing shared with the ABCI socket
layer; requests pub_key / sign_vote / sign_proposal, the signer answers
with the signature or a remote error (double-sign protection runs ON THE
SIGNER, where the key and last-sign state live).
"""
from __future__ import annotations

import socket
import threading
from typing import Optional

from cometbft_tpu.abci.server import _recv_msg, _send_msg
from cometbft_tpu.crypto.keys import PubKey
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.types import serde
from cometbft_tpu.types.block_id import BlockID
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import Vote


class RemoteSignerError(Exception):
    pass


class SignerListenerEndpoint:
    """Node-side PrivValidator proxy (signer_listener_endpoint.go:223 +
    signer_client.go): listens, accepts the signer's dial-in, then
    forwards signing requests over the connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 10.0):
        self._listener = socket.create_server((host, port))
        self.addr = self._listener.getsockname()
        self.timeout = timeout
        self._conn: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True, name="privval-accept"
        )
        self._connected = threading.Event()
        self._accept_thread.start()
        self._cached_pub: Optional[PubKey] = None

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                conn.settimeout(self.timeout)
                self._conn = conn
            self._connected.set()

    def wait_for_signer(self, timeout: float = 10.0) -> bool:
        return self._connected.wait(timeout)

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            if self._conn is not None:
                self._conn.close()

    def _call(self, doc: dict) -> dict:
        with self._lock:
            if self._conn is None:
                raise RemoteSignerError("no signer connected")
            try:
                _send_msg(self._conn, doc)
                resp = _recv_msg(self._conn)
            except OSError as e:
                raise RemoteSignerError(f"signer io error: {e}") from e
        if resp is None:
            raise RemoteSignerError("signer disconnected")
        if "err" in resp:
            raise RemoteSignerError(resp["err"])
        return resp

    # -- PrivValidator surface --------------------------------------------

    def pub_key(self) -> PubKey:
        if self._cached_pub is None:
            r = self._call({"m": "pub_key"})
            self._cached_pub = PubKey(bytes.fromhex(r["pub"]), r["type"])
        return self._cached_pub

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool = False) -> bytes:
        r = self._call({
            "m": "sign_vote", "chain_id": chain_id,
            "vote": serde.vote_to_j(vote),
            "sign_extension": sign_extension,
        })
        # the extension signature is produced signer-side and travels
        # back alongside the vote signature
        vote.extension_signature = bytes.fromhex(r.get("ext_sig", ""))
        return bytes.fromhex(r["sig"])

    def sign_proposal(self, chain_id: str, height: int, round_: int,
                      pol_round: int, block_id: BlockID,
                      ts: Timestamp) -> bytes:
        r = self._call({
            "m": "sign_proposal", "chain_id": chain_id, "height": height,
            "round": round_, "pol_round": pol_round,
            "block_id": serde.bid_to_j(block_id),
            "ts": serde.ts_to_j(ts),
        })
        return bytes.fromhex(r["sig"])


class SignerServer(BaseService):
    """Key-holding side (signer_server.go): dials the node and serves
    signing requests from a local FilePV (which enforces the double-sign
    protection next to the key)."""

    def __init__(self, privval, host: str, port: int,
                 retry_interval: float = 0.5):
        super().__init__("SignerServer")
        self.privval = privval
        self.host, self.port = host, port
        self.retry_interval = retry_interval
        self._thread: Optional[threading.Thread] = None

    def on_start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="signer-server"
        )
        self._thread.start()

    def on_stop(self) -> None:
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        import time

        while self.is_running():
            try:
                conn = socket.create_connection(
                    (self.host, self.port), timeout=5.0
                )
            except OSError:
                time.sleep(self.retry_interval)
                continue
            try:
                self._serve(conn)
            except OSError:
                pass
            finally:
                conn.close()

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        while self.is_running():
            try:
                req = _recv_msg(conn)
            except socket.timeout:
                continue
            if req is None:
                return
            try:
                resp = self._handle(req)
            except Exception as e:  # noqa: BLE001 - incl. DoubleSignError
                resp = {"err": repr(e)}
            _send_msg(conn, resp)

    def _handle(self, req: dict) -> dict:
        m = req.get("m")
        if m == "pub_key":
            pub = self.privval.pub_key()
            return {"pub": pub.data.hex(), "type": pub.key_type}
        if m == "sign_vote":
            vote = serde.vote_from_j(req["vote"])
            sig = self.privval.sign_vote(
                req["chain_id"], vote,
                sign_extension=bool(req.get("sign_extension")),
            )
            return {"sig": sig.hex(),
                    "ext_sig": vote.extension_signature.hex()}
        if m == "sign_proposal":
            sig = self.privval.sign_proposal(
                req["chain_id"], req["height"], req["round"],
                req["pol_round"], serde.bid_from_j(req["block_id"]),
                serde.ts_from_j(req["ts"]),
            )
            return {"sig": sig.hex()}
        raise RemoteSignerError(f"unknown request {m!r}")
