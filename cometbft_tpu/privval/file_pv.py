"""FilePV: file-backed validator signer with double-sign protection.

Reference: privval/file.go:157 (FilePV = key file + state file),
:75-100 (FilePVLastSignState: height/round/step + signbytes/signature
memo), :308-370 (signVote/signProposal: refuse to regress HRS; re-serve
the exact previous signature when only the timestamp differs).
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional, Tuple

from cometbft_tpu.crypto.keys import PrivKey, PubKey
from cometbft_tpu.types import canonical
from cometbft_tpu.types.vote import Vote

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {
    canonical.PREVOTE_TYPE: STEP_PREVOTE,
    canonical.PRECOMMIT_TYPE: STEP_PRECOMMIT,
}


class DoubleSignError(Exception):
    pass


class FilePV:
    """PrivValidator (types/priv_validator.go) backed by key+state files."""

    def __init__(self, priv_key: PrivKey, key_path: Optional[str] = None,
                 state_path: Optional[str] = None):
        self.priv_key = priv_key
        self.key_path = key_path
        self.state_path = state_path
        self.height = 0
        self.round = 0
        self.step = 0
        self.sign_bytes: Optional[bytes] = None
        self.signature: Optional[bytes] = None
        self._ext_signature: Optional[bytes] = None
        if state_path and os.path.exists(state_path):
            self._load_state()

    # -- persistence ---------------------------------------------------------

    @staticmethod
    def generate(dirpath: str, seed: Optional[bytes] = None) -> "FilePV":
        os.makedirs(dirpath, exist_ok=True)
        pv = FilePV(
            PrivKey.generate(seed),
            os.path.join(dirpath, "priv_validator_key.json"),
            os.path.join(dirpath, "priv_validator_state.json"),
        )
        pv.save_key()
        pv._save_state()
        return pv

    @staticmethod
    def load(dirpath: str) -> "FilePV":
        key_path = os.path.join(dirpath, "priv_validator_key.json")
        with open(key_path) as f:
            j = json.load(f)
        return FilePV(
            PrivKey(bytes.fromhex(j["priv_key"])),
            key_path,
            os.path.join(dirpath, "priv_validator_state.json"),
        )

    def save_key(self) -> None:
        if not self.key_path:
            return
        with open(self.key_path, "w") as f:
            json.dump({
                "address": self.pub_key().address().hex(),
                "pub_key": self.pub_key().data.hex(),
                "priv_key": self.priv_key.data.hex(),
            }, f)

    def _save_state(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "height": self.height,
                "round": self.round,
                "step": self.step,
                "sign_bytes": (self.sign_bytes or b"").hex(),
                "signature": (self.signature or b"").hex(),
                "ext_signature": (self._ext_signature or b"").hex(),
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    def _load_state(self) -> None:
        with open(self.state_path) as f:
            j = json.load(f)
        self.height = j["height"]
        self.round = j["round"]
        self.step = j["step"]
        self.sign_bytes = bytes.fromhex(j["sign_bytes"]) or None
        self.signature = bytes.fromhex(j["signature"]) or None
        self._ext_signature = bytes.fromhex(
            j.get("ext_signature", "")
        ) or None

    # -- PrivValidator interface ----------------------------------------------

    def pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool = False) -> bytes:
        """Sign a vote with HRS regression protection (file.go:308).

        With `sign_extension` (precommits once extensions are enabled)
        the extension signature is produced too — even for an EMPTY
        extension, since the vote-extension discipline requires a
        signature on every non-nil precommit — and set on the vote in
        place (privval SignVote's signExtension arm)."""
        step = _VOTE_STEP[vote.vote_type]
        self._check_hrs(vote.height, vote.round, step)
        sb = vote.sign_bytes(chain_id)
        # same HRS: only OK if sign bytes identical or only timestamp
        # differs (file.go:330-346) — we require identical here; the
        # consensus engine never re-signs with a new timestamp
        if (self.height, self.round, self.step) == (
            vote.height, vote.round, step
        ):
            if sb == self.sign_bytes:
                # extensions are NOT covered by sb and may differ between
                # retries (the app regenerates them) — re-sign the
                # extension unconditionally; only the vote signature is
                # double-sign-protected (file.go re-signs it too)
                if sign_extension and vote.vote_type == 2:
                    vote.extension_signature = self.priv_key.sign(
                        vote.extension_sign_bytes(chain_id)
                    )
                else:
                    vote.extension_signature = self._ext_signature or b""
                return self.signature
            raise DoubleSignError(
                f"conflicting vote data at {vote.height}/{vote.round}/"
                f"{step}"
            )
        sig = self.priv_key.sign(sb)
        ext_sig = None
        if sign_extension and vote.vote_type == 2:  # PRECOMMIT
            ext_sig = self.priv_key.sign(vote.extension_sign_bytes(chain_id))
            vote.extension_signature = ext_sig
        self.height, self.round, self.step = vote.height, vote.round, step
        self.sign_bytes, self.signature = sb, sig
        self._ext_signature = ext_sig
        self._save_state()
        return sig

    def sign_proposal(self, chain_id: str, height: int, round_: int,
                      pol_round: int, block_id, ts) -> bytes:
        self._check_hrs(height, round_, STEP_PROPOSE)
        sb = canonical.canonical_proposal_bytes(
            chain_id, height, round_, pol_round, block_id, ts
        )
        if (self.height, self.round, self.step) == (
            height, round_, STEP_PROPOSE
        ):
            if sb == self.sign_bytes:
                return self.signature
            raise DoubleSignError(
                f"conflicting proposal data at {height}/{round_}"
            )
        sig = self.priv_key.sign(sb)
        self.height, self.round, self.step = height, round_, STEP_PROPOSE
        self.sign_bytes, self.signature = sb, sig
        self._save_state()
        return sig

    def _check_hrs(self, h: int, r: int, s: int) -> None:
        if (h, r, s) < (self.height, self.round, self.step):
            raise DoubleSignError(
                f"height regression: last signed "
                f"{self.height}/{self.round}/{self.step}, asked {h}/{r}/{s}"
            )
