// hostaccel: native host-side batch helpers for the TPU verify path.
//
// The reference gets its host-side speed from Go + assembly inside
// curve25519-voi; here the host hot loop is staging work for the device
// (SURVEY.md §7 step 2: host bridge). This module removes the
// per-signature Python call overhead from batch digesting:
// one call hashes every (R || A || M) row of a commit.
//
// Self-contained FIPS 180-4 SHA-512 (no OpenSSL linkage — the image's
// toolchain is plain g++); differentially tested against hashlib in
// tests/test_native.py.
//
// Build: g++ -O3 -shared -fPIC -o _hostaccel.so hostaccel.cpp
// (done on demand by cometbft_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

namespace {

typedef uint64_t u64;
typedef uint8_t u8;

const u64 K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline u64 rotr(u64 x, int n) { return (x >> n) | (x << (64 - n)); }
inline u64 load_be(const u8* p) {
  u64 v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}
inline void store_be(u8* p, u64 v) {
  for (int i = 7; i >= 0; i--) { p[i] = (u8)v; v >>= 8; }
}

struct Sha512 {
  u64 h[8];
  u8 buf[128];
  u64 total;
  size_t fill;

  void init() {
    static const u64 iv[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
        0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
        0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    memcpy(h, iv, sizeof(iv));
    total = 0;
    fill = 0;
  }

  void block(const u8* p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) w[i] = load_be(p + 8 * i);
    for (int i = 16; i < 80; i++) {
      u64 s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
      u64 s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = h[0], b = h[1], c = h[2], d = h[3];
    u64 e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      u64 S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
      u64 ch = (e & f) ^ (~e & g);
      u64 t1 = hh + S1 + ch + K[i] + w[i];
      u64 S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
      u64 maj = (a & b) ^ (a & c) ^ (b & c);
      u64 t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const u8* p, size_t n) {
    total += n;
    if (fill) {
      size_t take = 128 - fill;
      if (take > n) take = n;
      memcpy(buf + fill, p, take);
      fill += take;
      p += take;
      n -= take;
      if (fill == 128) { block(buf); fill = 0; }
    }
    while (n >= 128) { block(p); p += 128; n -= 128; }
    if (n) { memcpy(buf, p, n); fill = n; }
  }

  void final(u8* out) {
    u64 bits = total * 8;
    u8 pad = 0x80;
    update(&pad, 1);
    u8 zero = 0;
    while (fill != 112) update(&zero, 1);
    u8 len[16] = {0};
    store_be(len + 8, bits);  // messages < 2^64 bits: high word zero
    update(len, 16);
    for (int i = 0; i < 8; i++) store_be(out + 8 * i, h[i]);
  }
};

}  // namespace

extern "C" {

// Hash n variable-length rows of one contiguous buffer.
// data: concatenated rows; offs[i]/lens[i]: row i; out: n x 64 bytes.
void batch_sha512(const u8* data, const u64* offs, const u64* lens,
                  u64 n, u8* out) {
  Sha512 s;
  for (u64 i = 0; i < n; i++) {
    s.init();
    s.update(data + offs[i], lens[i]);
    s.final(out + 64 * i);
  }
}

// The ed25519 batch-digest shape: rows are (R[32] || A[32] || M_i),
// where R/A come from fixed-stride arrays and M rows vary. Avoids
// materializing the concatenated buffer in Python.
void ed25519_batch_digest(const u8* r32, const u8* a32, const u8* msgs,
                          const u64* moffs, const u64* mlens, u64 n,
                          u8* out) {
  Sha512 s;
  for (u64 i = 0; i < n; i++) {
    s.init();
    s.update(r32 + 32 * i, 32);
    s.update(a32 + 32 * i, 32);
    s.update(msgs + moffs[i], mlens[i]);
    s.final(out + 64 * i);
  }
}

}  // extern "C"

// ---- scalar reduction mod L = 2^252 + c ------------------------------
// c = 27742317777372353535851937790883648493 (ed25519 group order tail).
// Used to fold the 64-byte challenge digest into h mod L without a
// Python bigint round trip per signature.

namespace {

// little-endian 4x64 add/sub helpers over 256-bit values
struct U256 {
  u64 w[4];
};

const U256 L_CONST = {{0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                       0x0000000000000000ULL, 0x1000000000000000ULL}};
const U256 C_CONST = {{0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0, 0}};

inline void add256(U256& a, const U256& b) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 t = (unsigned __int128)a.w[i] + b.w[i] + carry;
    a.w[i] = (u64)t;
    carry = t >> 64;
  }
}

inline bool sub256(U256& a, const U256& b) {  // a -= b; returns borrow
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 t =
        (unsigned __int128)a.w[i] - b.w[i] - borrow;
    a.w[i] = (u64)t;
    borrow = (t >> 64) ? 1 : 0;
  }
  return borrow != 0;
}

inline bool geq256(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; i--) {
    if (a.w[i] != b.w[i]) return a.w[i] > b.w[i];
  }
  return true;
}

// r = (r * 2^32 + word) mod L, with r < L on entry and exit.
// Split shifted = hi * 2^252 + lo; shifted mod L = lo - hi*c (+L).
inline void muladd_mod_l(U256& r, u64 word32) {
  // shifted = r << 32 | word32 as a 288-bit value in 5 words
  u64 s[5];
  s[0] = (r.w[0] << 32) | word32;
  s[1] = (r.w[1] << 32) | (r.w[0] >> 32);
  s[2] = (r.w[2] << 32) | (r.w[1] >> 32);
  s[3] = (r.w[3] << 32) | (r.w[2] >> 32);
  s[4] = r.w[3] >> 32;
  // hi = shifted >> 252 (shifted < 2^285 so hi < 2^33); lo = low 252
  // bits — bit 252 lives at position 60 of word 3 (252 - 3*64)
  u64 hi = (s[4] << 4) | (s[3] >> 60);
  U256 lo = {{s[0], s[1], s[2], s[3] & 0x0FFFFFFFFFFFFFFFULL}};
  // hi * c: c < 2^126 (2 words), hi < 2^33 -> product < 2^159 (3 words)
  U256 hc = {{0, 0, 0, 0}};
  unsigned __int128 p0 = (unsigned __int128)hi * C_CONST.w[0];
  unsigned __int128 p1 = (unsigned __int128)hi * C_CONST.w[1];
  hc.w[0] = (u64)p0;
  unsigned __int128 mid = (p0 >> 64) + (u64)p1;
  hc.w[1] = (u64)mid;
  hc.w[2] = (u64)((mid >> 64) + (p1 >> 64));
  if (sub256(lo, hc)) add256(lo, L_CONST);  // went negative: one L fixes
  if (geq256(lo, L_CONST)) sub256(lo, L_CONST);
  r = lo;
}

inline void reduce512_mod_l(const u8* digest64, u8* out32) {
  // digest is little-endian (RFC 8032); feed words from the top
  U256 r = {{0, 0, 0, 0}};
  for (int i = 15; i >= 0; i--) {
    u64 w = (u64)digest64[4 * i] | ((u64)digest64[4 * i + 1] << 8) |
            ((u64)digest64[4 * i + 2] << 16) |
            ((u64)digest64[4 * i + 3] << 24);
    muladd_mod_l(r, w);
  }
  for (int i = 0; i < 4; i++) {
    u64 v = r.w[i];
    for (int j = 0; j < 8; j++) {
      out32[8 * i + j] = (u8)v;
      v >>= 8;
    }
  }
}

}  // namespace

extern "C" {

// h_i = SHA512(R_i || A_i || M_i) mod L, 32 bytes little-endian each —
// the full challenge-scalar staging for the ed25519 device batch.
void ed25519_batch_challenge(const u8* r32, const u8* a32,
                             const u8* msgs, const u64* moffs,
                             const u64* mlens, u64 n, u8* out32) {
  Sha512 s;
  u8 digest[64];
  for (u64 i = 0; i < n; i++) {
    s.init();
    s.update(r32 + 32 * i, 32);
    s.update(a32 + 32 * i, 32);
    s.update(msgs + moffs[i], mlens[i]);
    s.final(digest);
    reduce512_mod_l(digest, out32 + 32 * i);
  }
}

// standalone reduction (differential-test surface)
void batch_reduce_mod_l(const u8* digests64, u64 n, u8* out32) {
  for (u64 i = 0; i < n; i++) {
    reduce512_mod_l(digests64 + 64 * i, out32 + 32 * i);
  }
}

}  // extern "C"

namespace {

// 32 LE bytes (top bit already masked) -> 20 x 13-bit int32 limbs
// (ops/field.py LIMB_BITS=13 NLIMBS=20 layout)
inline void limbs13(const u8* b, int32_t* out) {
  for (int i = 0; i < 20; i++) {
    int bit = 13 * i;
    int byte = bit >> 3, sh = bit & 7;
    u64 w = 0;
    for (int k = 0; k < 4 && byte + k < 32; k++) {
      w |= (u64)b[byte + k] << (8 * k);
    }
    out[i] = (int32_t)((w >> sh) & 0x1FFF);
  }
}

// 32 bytes -> 64 base-16 digits little-endian (scalar_digits)
inline void nibbles64(const u8* b, int32_t* out) {
  for (int i = 0; i < 32; i++) {
    out[2 * i] = b[i] & 0xF;
    out[2 * i + 1] = b[i] >> 4;
  }
}

inline bool below_l(const u8* s32) {
  // lexicographic compare on the LE bytes of L, from the top
  static const u8 LBYTES[32] = {
      0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
      0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};
  for (int i = 31; i >= 0; i--) {
    if (s32[i] != LBYTES[i]) return s32[i] < LBYTES[i];
  }
  return false;  // equal -> not below
}

}  // namespace

extern "C" {

// -- canonical sign-bytes templating (types/canonical.py
// CanonicalVoteEncoder): within a commit only the timestamp varies, so
// each row's message is
//   uvarint(len(body)) || pre || 0x2a || uvarint(len(ts)) || ts || suf
// with ts = f_varint(1, seconds) + f_varint(2, nanos) (zero fields
// omitted; negatives as 64-bit two's-complement 10-byte varints —
// libs/protoenc.py rules, byte-identical by differential test).

namespace {

inline int put_uvarint(u8* p, u64 v) {
  int i = 0;
  while (v >= 0x80) {
    p[i++] = (u8)(v | 0x80);
    v >>= 7;
  }
  p[i++] = (u8)v;
  return i;
}

// f_varint(field, v) for int64 values (two's complement when negative)
inline int put_field_varint(u8* p, int field, long long v) {
  if (v == 0) return 0;
  int i = put_uvarint(p, (u64)(field << 3));  // wire type 0
  i += put_uvarint(p + i, (u64)v);
  return i;
}

inline int put_ts_body(u8* p, long long secs, long long nanos) {
  int i = put_field_varint(p, 1, secs);
  i += put_field_varint(p + i, 2, nanos);
  return i;
}

}  // namespace

// Fused commit pack: per-row canonical sign-bytes from (template,
// timestamp) + SHA-512 + mod-L + limb/nibble decomposition + S<L, one
// call per streamed chunk (blocksync/pipeline.py). tmpl holds each
// commit's pre/suf slices.
void ed25519_pack_commits(
    const u8* pubs /* n x 32 */, const u8* sigs /* n x 64 */,
    const u8* tmpl, const u64* pre_off, const u64* pre_len,
    const u64* suf_off, const u64* suf_len,
    const int32_t* row_tmpl, const long long* row_secs,
    const long long* row_nanos, u64 n,
    int32_t* ay, int32_t* asign, int32_t* ry, int32_t* rsign,
    int32_t* sdig, int32_t* hdig, u8* precheck) {
  Sha512 sh;
  u8 digest[64], hred[32], masked[32];
  u8 tsbuf[24], head[16], lenbuf[10];
  for (u64 i = 0; i < n; i++) {
    const u8* pk = pubs + 32 * i;
    const u8* r = sigs + 64 * i;
    const u8* s = sigs + 64 * i + 32;
    int t = row_tmpl[i];
    const u8* pre = tmpl + pre_off[t];
    const u8* suf = tmpl + suf_off[t];
    u64 plen = pre_len[t], slen = suf_len[t];

    int tslen = put_ts_body(tsbuf, row_secs[i], row_nanos[i]);
    int hlen = 0;
    head[hlen++] = 0x2a;  // tag(5, BYTES)
    hlen += put_uvarint(head + hlen, (u64)tslen);
    u64 body_len = plen + (u64)hlen + (u64)tslen + slen;
    int dlen = put_uvarint(lenbuf, body_len);

    sh.init();
    sh.update(r, 32);
    sh.update(pk, 32);
    sh.update(lenbuf, dlen);
    sh.update(pre, plen);
    sh.update(head, hlen);
    sh.update(tsbuf, tslen);
    sh.update(suf, slen);
    sh.final(digest);
    reduce512_mod_l(digest, hred);

    memcpy(masked, pk, 32);
    masked[31] &= 0x7F;
    limbs13(masked, ay + 20 * i);
    asign[i] = pk[31] >> 7;
    memcpy(masked, r, 32);
    masked[31] &= 0x7F;
    limbs13(masked, ry + 20 * i);
    rsign[i] = r[31] >> 7;
    nibbles64(s, sdig + 64 * i);
    nibbles64(hred, hdig + 64 * i);
    precheck[i] = below_l(s) ? 1 : 0;
  }
}

// Full host pack for one ed25519 batch (ops/ed25519_kernel.pack_batch
// fast path): digests + mod-L + limb/nibble decomposition + S<L
// precheck, one call for the whole commit.
void ed25519_pack(const u8* pubs /* n x 32 */, const u8* sigs /* n x 64 */,
                  const u8* msgs, const u64* moffs, const u64* mlens,
                  u64 n, int32_t* ay /* n x 20 */, int32_t* asign,
                  int32_t* ry, int32_t* rsign, int32_t* sdig /* n x 64 */,
                  int32_t* hdig /* n x 64 */, u8* precheck) {
  Sha512 sh;
  u8 digest[64], hred[32], masked[32];
  for (u64 i = 0; i < n; i++) {
    const u8* pk = pubs + 32 * i;
    const u8* r = sigs + 64 * i;
    const u8* s = sigs + 64 * i + 32;
    sh.init();
    sh.update(r, 32);
    sh.update(pk, 32);
    sh.update(msgs + moffs[i], mlens[i]);
    sh.final(digest);
    reduce512_mod_l(digest, hred);

    memcpy(masked, pk, 32);
    masked[31] &= 0x7F;
    limbs13(masked, ay + 20 * i);
    asign[i] = pk[31] >> 7;
    memcpy(masked, r, 32);
    masked[31] &= 0x7F;
    limbs13(masked, ry + 20 * i);
    rsign[i] = r[31] >> 7;
    nibbles64(s, sdig + 64 * i);
    nibbles64(hred, hdig + 64 * i);
    precheck[i] = below_l(s) ? 1 : 0;
  }
}

}  // extern "C"

// ---- keccak-f[1600] ---------------------------------------------------
// Batched permutation for the merlin/STROBE transcript host path
// (crypto/keccak.py keccak_f1600_np) — sr25519 challenge generation runs
// thousands of lanes of STROBE, and the numpy route spends ~200 ms per
// 5k-row batch where C needs ~5 ms.

namespace {

const u64 KRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

inline u64 rotl64(u64 x, int n) {
  return n ? (x << n) | (x >> (64 - n)) : x;
}

// rotation offsets indexed [x][y] (keccak.py _ROT layout)
const int KROT[5][5] = {{0, 36, 3, 41, 18},
                        {1, 44, 10, 45, 2},
                        {62, 6, 43, 15, 61},
                        {28, 55, 25, 21, 56},
                        {27, 20, 39, 8, 14}};

inline void f1600_one(u64* s /* 25 lanes, order x + 5y */) {
  u64 a[5][5], b[5][5], c[5], d[5];
  for (int y = 0; y < 5; y++)
    for (int x = 0; x < 5; x++) a[x][y] = s[x + 5 * y];
  for (int r = 0; r < 24; r++) {
    for (int x = 0; x < 5; x++)
      c[x] = a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x][y] ^= d[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y][(2 * x + 3 * y) % 5] = rotl64(a[x][y], KROT[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x][y] = b[x][y] ^ (~b[(x + 1) % 5][y] & b[(x + 2) % 5][y]);
    a[0][0] ^= KRC[r];
  }
  for (int y = 0; y < 5; y++)
    for (int x = 0; x < 5; x++) s[x + 5 * y] = a[x][y];
}

}  // namespace

// ---- STROBE-128 / merlin transcripts ----------------------------------
// Full sr25519 challenge transcripts in native code
// (crypto/merlin.py Strobe128 semantics, differential-tested in
// tests/test_native.py). The numpy BatchStrobe route pays python+numpy
// dispatch for every transcript op (~70 ms host_pack for a 5k-row
// mixed commit, round-4 verdict cfg3 weakness); one C call walks each
// lane's whole transcript.

namespace {

constexpr int SR = 166;  // STROBE-128 rate: 200 - 2*16 - 2
constexpr u8 SFLAG_I = 1, SFLAG_A = 2, SFLAG_C = 4, SFLAG_M = 16,
             SFLAG_K = 32;

struct Strobe {
  u8 st[200];
  int pos, pos_begin;
  u8 cur_flags;

  void run_f() {
    st[pos] ^= (u8)pos_begin;
    st[pos + 1] ^= 0x04;
    st[SR + 1] ^= 0x80;
    u64 lanes[25];
    memcpy(lanes, st, 200);
    f1600_one(lanes);
    memcpy(st, lanes, 200);
    pos = 0;
    pos_begin = 0;
  }

  void absorb(const u8* data, u64 len) {
    for (u64 i = 0; i < len; i++) {
      st[pos] ^= data[i];
      if (++pos == SR) run_f();
    }
  }

  void squeeze(u8* out, u64 len) {
    for (u64 i = 0; i < len; i++) {
      out[i] = st[pos];
      st[pos] = 0;
      if (++pos == SR) run_f();
    }
  }

  void begin_op(u8 flags, bool more) {
    if (more) return;
    u8 hdr[2] = {(u8)pos_begin, flags};
    pos_begin = pos + 1;
    cur_flags = flags;
    absorb(hdr, 2);
    if ((flags & (SFLAG_C | SFLAG_K)) && pos != 0) run_f();
  }

  void meta_ad(const u8* d, u64 n, bool more) {
    begin_op(SFLAG_M | SFLAG_A, more);
    absorb(d, n);
  }
  void ad(const u8* d, u64 n, bool more) {
    begin_op(SFLAG_A, more);
    absorb(d, n);
  }
  void prf(u8* out, u64 n) {
    begin_op(SFLAG_I | SFLAG_A | SFLAG_C, false);
    squeeze(out, n);
  }

  void append_message(const u8* label, u64 ll, const u8* msg, u64 ml) {
    u8 len4[4] = {(u8)ml, (u8)(ml >> 8), (u8)(ml >> 16), (u8)(ml >> 24)};
    meta_ad(label, ll, false);
    meta_ad(len4, 4, true);
    ad(msg, ml, false);
  }

  void challenge(const u8* label, u64 ll, u8* out, u64 n) {
    u8 len4[4] = {(u8)n, (u8)(n >> 8), (u8)(n >> 16), (u8)(n >> 24)};
    meta_ad(label, ll, false);
    meta_ad(len4, 4, true);
    prf(out, n);
  }
};

}  // namespace

extern "C" {

// In-place batched keccak-f[1600]: states is n x 25 little-endian u64
// lanes (x + 5y order, matching keccak.py).
void batch_keccak_f1600(u64* states, u64 n) {
  for (u64 i = 0; i < n; i++) f1600_one(states + 25 * i);
}

// sr25519 (schnorrkel) batch challenge derivation: each lane clones the
// signing-context prefix transcript and runs
//   append_message("sign-bytes", msg)
//   append_message("proto-name", "Schnorr-sig")
//   append_message("sign:pk", pk)   append_message("sign:R", R)
//   challenge_bytes("sign:c", 64)
// (crypto/sr25519/batch.go:44-77 / sr25519_ref.challenge_scalar).
// prefix: 200-byte STROBE state + pos/pos_begin/cur_flags of the shared
// signing context; msgs is n x msg_len (caller groups rows by length).
void sr25519_batch_challenges(const u8* prefix, int pos, int pos_begin,
                              int cur_flags, const u8* msgs, u64 msg_len,
                              const u8* pks /* n x 32 */,
                              const u8* rs /* n x 32 */, u64 n,
                              u8* out /* n x 64 */) {
  for (u64 i = 0; i < n; i++) {
    Strobe s;
    memcpy(s.st, prefix, 200);
    s.pos = pos;
    s.pos_begin = pos_begin;
    s.cur_flags = (u8)cur_flags;
    s.append_message((const u8*)"sign-bytes", 10, msgs + i * msg_len,
                     msg_len);
    s.append_message((const u8*)"proto-name", 10,
                     (const u8*)"Schnorr-sig", 11);
    s.append_message((const u8*)"sign:pk", 7, pks + i * 32, 32);
    s.append_message((const u8*)"sign:R", 6, rs + i * 32, 32);
    s.challenge((const u8*)"sign:c", 6, out + i * 64, 64);
  }
}

int hostaccel_abi_version() { return 1; }

}  // extern "C"
