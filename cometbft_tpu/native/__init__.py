"""Native host-acceleration loader.

Compiles hostaccel.cpp to a shared object on first use (g++ is part of
the image toolchain; no pybind11 — plain `ctypes` over an extern "C"
ABI) and exposes numpy-friendly wrappers. Every entry point has a
pure-Python fallback, so the package works identically when no
compiler is present — `available()` says which path is live.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "hostaccel.cpp")
_SO = os.path.join(_DIR, "_hostaccel.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _compile() -> bool:
    # Compile to a per-pid temp path and os.replace() into place:
    # concurrent processes (e.g. the multi-process e2e testnet) would
    # otherwise interleave writes into the shared .so and a reader could
    # dlopen a permanently corrupt file.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.info("hostaccel compile unavailable: %s", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    if r.returncode != 0:
        _log.warning("hostaccel compile failed:\n%s", r.stderr[-2000:])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    try:
        os.replace(tmp, _SO)
    except OSError as e:
        _log.warning("hostaccel install failed: %s", e)
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_SO)
            assert lib.hostaccel_abi_version() == 1
        except (OSError, AttributeError, AssertionError) as e:
            _log.warning("hostaccel load failed: %s", e)
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        lib.batch_sha512.argtypes = [u8p, u64p, u64p, ctypes.c_uint64,
                                     u8p]
        lib.batch_sha512.restype = None
        lib.ed25519_batch_digest.argtypes = [u8p, u8p, u8p, u64p, u64p,
                                             ctypes.c_uint64, u8p]
        lib.ed25519_batch_digest.restype = None
        lib.ed25519_batch_challenge.argtypes = [u8p, u8p, u8p, u64p,
                                                u64p, ctypes.c_uint64,
                                                u8p]
        lib.ed25519_batch_challenge.restype = None
        lib.batch_reduce_mod_l.argtypes = [u8p, ctypes.c_uint64, u8p]
        lib.batch_reduce_mod_l.restype = None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.ed25519_pack.argtypes = [u8p, u8p, u8p, u64p, u64p,
                                     ctypes.c_uint64, i32p, i32p, i32p,
                                     i32p, i32p, i32p, u8p]
        lib.ed25519_pack.restype = None
        lib.ed25519_pack_commits.argtypes = [
            u8p, u8p, u8p, u64p, u64p, u64p, u64p,
            i32p, i64p, i64p, ctypes.c_uint64,
            i32p, i32p, i32p, i32p, i32p, i32p, u8p,
        ]
        lib.ed25519_pack_commits.restype = None
        u64arr = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        lib.batch_keccak_f1600.argtypes = [u64arr, ctypes.c_uint64]
        lib.batch_keccak_f1600.restype = None
        lib.sr25519_batch_challenges.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            u8p, ctypes.c_uint64, u8p, u8p, ctypes.c_uint64, u8p,
        ]
        lib.sr25519_batch_challenges.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def batch_sha512(rows: Sequence[bytes]) -> np.ndarray:
    """SHA-512 of each row; returns (n, 64) uint8. One native call for
    the whole batch (vs n hashlib calls)."""
    n = len(rows)
    out = np.empty((n, 64), np.uint8)
    lib = _load()
    if lib is None:
        for i, r in enumerate(rows):
            out[i] = np.frombuffer(hashlib.sha512(r).digest(), np.uint8)
        return out
    data = np.frombuffer(b"".join(rows), np.uint8)
    if data.size == 0:
        data = np.zeros(1, np.uint8)  # valid pointer for all-empty rows
    lens = np.asarray([len(r) for r in rows], np.uint64)
    offs = np.zeros(n, np.uint64)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    lib.batch_sha512(np.ascontiguousarray(data), offs, lens, n, out)
    return out


def _msg_arrays(msgs: Sequence[bytes]):
    n = len(msgs)
    mdata = np.frombuffer(b"".join(msgs), np.uint8)
    if mdata.size == 0:
        mdata = np.zeros(1, np.uint8)  # valid pointer for empty msgs
    mlens = np.asarray([len(m) for m in msgs], np.uint64)
    moffs = np.zeros(n, np.uint64)
    if n > 1:
        np.cumsum(mlens[:-1], out=moffs[1:])
    return np.ascontiguousarray(mdata), moffs, mlens


def ed25519_batch_digest(r_raw: np.ndarray, a_raw: np.ndarray,
                         msgs: Sequence[bytes]) -> np.ndarray:
    """Digests SHA512(R_i || A_i || M_i) for the ed25519 verify batch
    without materializing the concatenation in Python."""
    n = len(msgs)
    out = np.empty((n, 64), np.uint8)
    lib = _load()
    if lib is None:
        sha512 = hashlib.sha512
        rb, ab = r_raw.tobytes(), a_raw.tobytes()
        for i, m in enumerate(msgs):
            d = sha512(rb[32 * i:32 * i + 32]
                       + ab[32 * i:32 * i + 32] + m).digest()
            out[i] = np.frombuffer(d, np.uint8)
        return out
    mdata, moffs, mlens = _msg_arrays(msgs)
    lib.ed25519_batch_digest(
        np.ascontiguousarray(r_raw[:n].reshape(n, 32)),
        np.ascontiguousarray(a_raw[:n].reshape(n, 32)),
        mdata, moffs, mlens, n, out,
    )
    return out


_L = 2**252 + 27742317777372353535851937790883648493


def ed25519_batch_challenge(r_raw: np.ndarray, a_raw: np.ndarray,
                            msgs: Sequence[bytes]) -> Optional[np.ndarray]:
    """h_i = SHA512(R_i || A_i || M_i) mod L as (n, 32) LE bytes — the
    fused digest+reduce staging pass. None when no native library (the
    caller keeps its hashlib+bigint fallback)."""
    lib = _load()
    if lib is None:
        return None
    n = len(msgs)
    out = np.empty((n, 32), np.uint8)
    mdata, moffs, mlens = _msg_arrays(msgs)
    lib.ed25519_batch_challenge(
        np.ascontiguousarray(r_raw[:n].reshape(n, 32)),
        np.ascontiguousarray(a_raw[:n].reshape(n, 32)),
        mdata, moffs, mlens, n, out,
    )
    return out


def ed25519_pack(pub_cat: bytes, sig_cat: bytes,
                 msgs: Sequence[bytes], padded: int):
    """Full host pack: (n-concatenated pubkeys, sigs, msgs) -> device
    arrays padded to `padded` rows. None without the native library.

    Returns (ay, asign, ry, rsign, sdig, hdig, precheck) matching
    ops/ed25519_kernel.pack_batch's fast path exactly (differential
    test: tests/test_native.py pack parity)."""
    lib = _load()
    if lib is None:
        return None
    n = len(msgs)
    ay = np.zeros((padded, 20), np.int32)
    ry = np.zeros((padded, 20), np.int32)
    asign = np.zeros(padded, np.int32)
    rsign = np.zeros(padded, np.int32)
    sdig = np.zeros((padded, 64), np.int32)
    hdig = np.zeros((padded, 64), np.int32)
    precheck = np.zeros(padded, np.uint8)
    if n:
        mdata, moffs, mlens = _msg_arrays(msgs)
        pubs = np.frombuffer(pub_cat, np.uint8)
        sigs = np.frombuffer(sig_cat, np.uint8)
        lib.ed25519_pack(
            np.ascontiguousarray(pubs), np.ascontiguousarray(sigs),
            mdata, moffs, mlens, n,
            ay, asign, ry, rsign, sdig, hdig, precheck,
        )
    return ay, asign, ry, rsign, sdig, hdig, precheck.astype(np.bool_)


def ed25519_pack_commits(pub_cat: bytes, sig_cat: bytes,
                         templates, row_tmpl: np.ndarray,
                         row_secs: np.ndarray, row_nanos: np.ndarray,
                         padded: int):
    """Fused streamed-chunk pack: canonical sign-bytes are built
    in-native from (per-commit template, per-row timestamp) — no Python
    message list at all. `templates` is [(pre_bytes, suf_bytes)];
    row_tmpl[i] indexes it. Returns the same tuple as ed25519_pack, or
    None without the native library."""
    lib = _load()
    if lib is None:
        return None
    n = len(row_tmpl)
    ay = np.zeros((padded, 20), np.int32)
    ry = np.zeros((padded, 20), np.int32)
    asign = np.zeros(padded, np.int32)
    rsign = np.zeros(padded, np.int32)
    sdig = np.zeros((padded, 64), np.int32)
    hdig = np.zeros((padded, 64), np.int32)
    precheck = np.zeros(padded, np.uint8)
    if n:
        chunks, pre_off, pre_len, suf_off, suf_len = [], [], [], [], []
        pos = 0
        for pre, suf in templates:
            pre_off.append(pos)
            pre_len.append(len(pre))
            pos += len(pre)
            suf_off.append(pos)
            suf_len.append(len(suf))
            pos += len(suf)
            chunks.append(pre)
            chunks.append(suf)
        tmpl = np.frombuffer(b"".join(chunks), np.uint8)
        if tmpl.size == 0:
            tmpl = np.zeros(1, np.uint8)
        lib.ed25519_pack_commits(
            np.ascontiguousarray(np.frombuffer(pub_cat, np.uint8)),
            np.ascontiguousarray(np.frombuffer(sig_cat, np.uint8)),
            np.ascontiguousarray(tmpl),
            np.asarray(pre_off, np.uint64), np.asarray(pre_len, np.uint64),
            np.asarray(suf_off, np.uint64), np.asarray(suf_len, np.uint64),
            np.ascontiguousarray(row_tmpl, dtype=np.int32),
            np.ascontiguousarray(row_secs, dtype=np.int64),
            np.ascontiguousarray(row_nanos, dtype=np.int64),
            n, ay, asign, ry, rsign, sdig, hdig, precheck,
        )
    return ay, asign, ry, rsign, sdig, hdig, precheck.astype(np.bool_)


def batch_keccak_f1600(states: np.ndarray) -> Optional[np.ndarray]:
    """Batched keccak permutation: (n, 25) uint64 lanes -> permuted
    copy; None without the native library (callers keep the numpy
    route)."""
    lib = _load()
    if lib is None:
        return None
    out = np.ascontiguousarray(states, dtype=np.uint64).copy()
    lib.batch_keccak_f1600(out, out.shape[0])
    return out


def batch_reduce_mod_l(digests: np.ndarray) -> Optional[np.ndarray]:
    """(n, 64) LE digests -> (n, 32) LE scalars mod L; None without the
    native library."""
    lib = _load()
    if lib is None:
        return None
    n = digests.shape[0]
    out = np.empty((n, 32), np.uint8)
    lib.batch_reduce_mod_l(
        np.ascontiguousarray(digests.reshape(n, 64)), n, out
    )
    return out


def sr25519_batch_challenges(prefix_state: bytes, pos: int,
                             pos_begin: int, cur_flags: int,
                             msgs: np.ndarray, pks: np.ndarray,
                             rs: np.ndarray) -> Optional[np.ndarray]:
    """Whole sr25519 merlin challenge transcripts in one native call:
    (n, L) msgs + (n, 32) pks + (n, 32) R encodings -> (n, 64) raw
    challenge bytes. None without the native library (callers keep the
    numpy BatchStrobe route — the differential reference,
    tests/test_native.py)."""
    lib = _load()
    if lib is None:
        return None
    n = msgs.shape[0]
    out = np.empty((n, 64), np.uint8)
    lib.sr25519_batch_challenges(
        np.frombuffer(prefix_state, np.uint8), pos, pos_begin,
        cur_flags, np.ascontiguousarray(msgs, np.uint8),
        msgs.shape[1], np.ascontiguousarray(pks, np.uint8),
        np.ascontiguousarray(rs, np.uint8), n, out,
    )
    return out
