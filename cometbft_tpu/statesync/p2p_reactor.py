"""Statesync over p2p: snapshot discovery + chunk transfer.

Reference: statesync/reactor.go — SnapshotChannel 0x60 / ChunkChannel
0x61, SnapshotsRequest/SnapshotsResponse, ChunkRequest/ChunkResponse.
Serving side answers from the local app; syncing side feeds the Syncer.
"""
from __future__ import annotations

import base64
import json
import threading
from typing import List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.switch import Peer, Reactor

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61


class StatesyncP2PReactor(Reactor):
    def __init__(self, app: abci.Application, syncer=None):
        super().__init__("STATESYNC")
        self.app = app
        self.syncer = syncer  # None on serve-only nodes
        self._pending = {}    # (height, fmt, idx) -> [Event, data]
        self._lock = threading.Lock()

    def channel_descriptors(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=100,
                              recv_message_capacity=32 * 1024 * 1024),
        ]

    def add_peer(self, peer: Peer) -> None:
        if self.syncer is not None:
            peer.send(SNAPSHOT_CHANNEL,
                      json.dumps({"t": "snapshots_req"}).encode())

    # -- chunk fetch for the Syncer ---------------------------------------

    def _fetch_chunk(self, peer: Peer, snapshot: abci.Snapshot,
                     idx: int, timeout: float = 10.0) -> Optional[bytes]:
        key = (snapshot.height, snapshot.format, idx)
        ev = threading.Event()
        with self._lock:
            self._pending[key] = [ev, None]
        peer.send(CHUNK_CHANNEL, json.dumps({
            "t": "chunk_req", "h": snapshot.height,
            "f": snapshot.format, "i": idx,
        }).encode())
        ok = ev.wait(timeout)
        with self._lock:
            _, data = self._pending.pop(key, (None, None))
        return data if ok else None

    # -- inbound -----------------------------------------------------------

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        try:
            j = json.loads(msg.decode())
            t = j.get("t")
            if t == "snapshots_req":
                for s in self.app.list_snapshots():
                    peer.send(SNAPSHOT_CHANNEL, json.dumps({
                        "t": "snapshot", "h": s.height, "f": s.format,
                        "c": s.chunks, "hash": s.hash.hex(),
                        "m": s.metadata.hex(),
                    }).encode())
            elif t == "snapshot":
                if self.syncer is not None:
                    snap = abci.Snapshot(
                        height=int(j["h"]), format=int(j["f"]),
                        chunks=int(j["c"]), hash=bytes.fromhex(j["hash"]),
                        metadata=bytes.fromhex(j.get("m", "")),
                    )
                    self.syncer.add_snapshot(
                        snap,
                        lambda i, p=peer, s=snap: self._fetch_chunk(
                            p, s, i, timeout=self.syncer.chunk_timeout),
                        provider_id=str(getattr(peer, "node_id", peer)),
                    )
            elif t == "chunk_req":
                data = self.app.load_snapshot_chunk(
                    int(j["h"]), int(j["f"]), int(j["i"])
                )
                peer.send(CHUNK_CHANNEL, json.dumps({
                    "t": "chunk", "h": j["h"], "f": j["f"], "i": j["i"],
                    "data": base64.b64encode(data).decode(),
                }).encode())
            elif t == "chunk":
                key = (int(j["h"]), int(j["f"]), int(j["i"]))
                with self._lock:
                    entry = self._pending.get(key)
                    if entry is not None:
                        entry[1] = base64.b64decode(j["data"])
                        entry[0].set()
            else:
                raise ValueError(f"unknown statesync message {t!r}")
        except Exception as e:  # noqa: BLE001 - malformed peer message
            self.switch.stop_peer_for_error(peer, f"bad statesync msg: {e}")
