"""Statesync over p2p: snapshot discovery + chunk transfer.

Reference: statesync/reactor.go — SnapshotChannel 0x60 / ChunkChannel
0x61, SnapshotsRequest/SnapshotsResponse, ChunkRequest/ChunkResponse.
Serving side answers from the local app; syncing side feeds the Syncer.

PR 18 puts the serving side on the overload contract: every inbound
``snapshots_req``/``chunk_req`` passes the :class:`ServeGate` (a
per-peer token bucket on the ledger clock) and over-budget requests
are answered with EXPLICIT retry-hinted sheds (``chunk_shed`` /
``snapshots_shed`` messages carrying ``retry_after_ms``) instead of
silence — a donor under bootstrap storm degrades honestly, and its
CONSENSUS lane is structurally untouchable because serving work never
enters the verify plane's consensus lane at all. Served chunks carry
merkle inclusion proofs (statesync/snapshots.py) so the restoring peer
verifies each chunk on arrival and punishes only the sender of a bad
one.
"""
from __future__ import annotations

import base64
import json
import threading
import time
from typing import List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.switch import Peer, Reactor
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.statesync.snapshots import (
    ServeGate,
    SnapshotArchive,
    SnapshotCatalog,
    SnapshotServeOverloaded,
    proof_doc,
    verify_chunk,
)

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

# bounded client-side honoring of a donor's retry hint: one chunk
# request may be re-issued this many times after explicit sheds before
# the fetch gives up (the fetcher then tries another provider)
MAX_SHED_RETRIES = 2
MAX_RETRY_WAIT_S = 2.0


def _peer_id(peer: Peer) -> str:
    return str(getattr(peer, "node_id", peer))


class StatesyncP2PReactor(Reactor):
    def __init__(self, app: abci.Application, syncer=None,
                 gate: Optional[ServeGate] = None,
                 archive: Optional[SnapshotArchive] = None):
        super().__init__("STATESYNC")
        self.app = app
        self.syncer = syncer  # None on serve-only nodes
        self.gate = gate or ServeGate()
        self.archive = archive  # format-2 merkle snapshots (optional)
        self.catalog = SnapshotCatalog(app)
        # (height, fmt, idx) -> {"ev", "data", "proof", "retry_ms"}
        self._pending = {}
        self._lock = threading.Lock()

    def channel_descriptors(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=100,
                              recv_message_capacity=32 * 1024 * 1024),
        ]

    def add_peer(self, peer: Peer) -> None:
        if self.syncer is not None:
            peer.send(SNAPSHOT_CHANNEL,
                      json.dumps({"t": "snapshots_req"}).encode())

    # -- chunk fetch for the Syncer ---------------------------------------

    def _fetch_chunk(self, peer: Peer, snapshot: abci.Snapshot,
                     idx: int, timeout: float = 10.0,
                     root: Optional[bytes] = None) -> Optional[bytes]:
        key = (snapshot.height, snapshot.format, idx)
        for _ in range(1 + MAX_SHED_RETRIES):
            ev = threading.Event()
            with self._lock:
                self._pending[key] = {"ev": ev, "data": None,
                                      "proof": None, "retry_ms": None}
            peer.send(CHUNK_CHANNEL, json.dumps({
                "t": "chunk_req", "h": snapshot.height,
                "f": snapshot.format, "i": idx,
            }).encode())
            ok = ev.wait(timeout)
            with self._lock:
                entry = self._pending.pop(key, None) or {}
            if not ok:
                return None
            retry_ms = entry.get("retry_ms")
            if retry_ms is not None:
                # an explicit shed is a retry hint, not a failure:
                # honor it (bounded) instead of punishing the donor
                time.sleep(min(retry_ms / 1000.0, MAX_RETRY_WAIT_S))
                continue
            data = entry.get("data")
            if data is None:
                return None
            proof = entry.get("proof")
            if root is not None and proof is not None \
                    and not verify_chunk(root, data, proof):
                return None  # bad chunk: the fetcher punishes THIS peer
            return data
        return None

    # -- serving ------------------------------------------------------------

    def _serve_snapshots(self, peer: Peer) -> None:
        snaps = [(s, None) for s in self.app.list_snapshots()]
        if self.archive is not None:
            snaps += [(s, s.hash) for s in self.archive.list_snapshots()]
        for s, root in snaps:
            if root is None:
                ent = self.catalog.root_and_proofs(s.height, s.format,
                                                   s.chunks)
                root = ent[0] if ent else None
            msg = {"t": "snapshot", "h": s.height, "f": s.format,
                   "c": s.chunks, "hash": s.hash.hex(),
                   "m": s.metadata.hex()}
            if root is not None:
                msg["root"] = root.hex()
            peer.send(SNAPSHOT_CHANNEL, json.dumps(msg).encode())
        ss_stats.bump("snapshots_served")

    def _serve_chunk(self, peer: Peer, h: int, f: int, i: int) -> None:
        proof = None
        if self.archive is not None:
            data = self.archive.load_chunk(h, f, i)
            if data:
                proof = self.archive.proof_for(h, f, i)
        else:
            data = b""
        if not data:
            data = self.app.load_snapshot_chunk(h, f, i)
            if data:
                for s in self.app.list_snapshots():
                    if s.height == h and s.format == f:
                        ent = self.catalog.root_and_proofs(h, f, s.chunks)
                        if ent is not None:
                            proof = ent[1][i]
                        break
        msg = {"t": "chunk", "h": h, "f": f, "i": i,
               "data": base64.b64encode(data).decode()}
        if proof is not None:
            msg["proof"] = proof_doc(proof)
        peer.send(CHUNK_CHANNEL, json.dumps(msg).encode())
        ss_stats.bump("chunks_served")

    # -- inbound -----------------------------------------------------------

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        try:
            j = json.loads(msg.decode())
            t = j.get("t")
            if t == "snapshots_req":
                try:
                    self.gate.admit(_peer_id(peer), kind="snapshot")
                except SnapshotServeOverloaded as e:
                    peer.send(SNAPSHOT_CHANNEL, json.dumps({
                        "t": "snapshots_shed",
                        "retry_after_ms": round(e.retry_after_ms, 3),
                    }).encode())
                    return
                fp.fail_point("snapshot.serve")
                self._serve_snapshots(peer)
            elif t == "snapshot":
                if self.syncer is not None:
                    snap = abci.Snapshot(
                        height=int(j["h"]), format=int(j["f"]),
                        chunks=int(j["c"]), hash=bytes.fromhex(j["hash"]),
                        metadata=bytes.fromhex(j.get("m", "")),
                    )
                    root = (bytes.fromhex(j["root"])
                            if j.get("root") else None)
                    self.syncer.add_snapshot(
                        snap,
                        lambda i, p=peer, s=snap, r=root:
                            self._fetch_chunk(
                                p, s, i,
                                timeout=self.syncer.chunk_timeout,
                                root=r),
                        provider_id=_peer_id(peer),
                    )
            elif t == "snapshots_shed":
                pass  # discovery retries ride sync_any's own loop
            elif t == "chunk_req":
                h, f, i = int(j["h"]), int(j["f"]), int(j["i"])
                try:
                    self.gate.admit(_peer_id(peer), kind="chunk")
                except SnapshotServeOverloaded as e:
                    peer.send(CHUNK_CHANNEL, json.dumps({
                        "t": "chunk_shed", "h": h, "f": f, "i": i,
                        "retry_after_ms": round(e.retry_after_ms, 3),
                    }).encode())
                    return
                fp.fail_point("snapshot.serve")
                self._serve_chunk(peer, h, f, i)
            elif t == "chunk":
                key = (int(j["h"]), int(j["f"]), int(j["i"]))
                with self._lock:
                    entry = self._pending.get(key)
                    if entry is not None:
                        entry["data"] = base64.b64decode(j["data"])
                        entry["proof"] = j.get("proof")
                        entry["ev"].set()
            elif t == "chunk_shed":
                key = (int(j["h"]), int(j["f"]), int(j["i"]))
                with self._lock:
                    entry = self._pending.get(key)
                    if entry is not None:
                        entry["retry_ms"] = float(
                            j.get("retry_after_ms", 100.0))
                        entry["ev"].set()
            else:
                raise ValueError(f"unknown statesync message {t!r}")
        except Exception as e:  # noqa: BLE001 - malformed peer message
            self.switch.stop_peer_for_error(peer, f"bad statesync msg: {e}")
