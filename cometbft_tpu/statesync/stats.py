"""Always-on statesync accounting: chunk fetch/apply outcomes,
provider lifecycle, and snapshot-serving verdicts.

Statesync was invisible before this module: the engine punished and
dropped providers, timed out fetches, and restarted whole snapshot
rounds with no counter anywhere an operator could scrape. These are
plain process-global integers (no metrics handle in scope down in the
chunk engine), SAMPLED by ``NodeMetrics._sample`` at scrape time into
the ``cometbft_statesync_*`` families — the same pull model the WAL
fsync and failpoint counters use.

The counters are cumulative for the process. Tests that assert exact
accounting call :func:`reset` (or diff against a :func:`stats`
snapshot) around the section they measure.
"""
from __future__ import annotations

import threading
from typing import Dict

FIELDS = (
    # fetch side (chunks.py / syncer.py)
    "chunks_fetched",         # chunk payloads accepted into the queue
    "chunks_applied",         # chunks the app ACCEPTed during restore
    "fetch_timeouts",         # applier waits that expired with no chunk
    "providers_punished",     # failure strikes counted against providers
    "providers_dropped",      # providers dropped at MAX_PROVIDER_FAILURES
    "retry_snapshot_rounds",  # whole-snapshot RETRY_SNAPSHOT restarts
    "snapshots_offered",      # offers the local app accepted for restore
    "snapshots_restored",     # restores verified against the light client
    # serving side (p2p_reactor.py / snapshots.py serve gate)
    "snapshots_served",       # snapshot listings answered to peers
    "snapshots_shed",         # snapshot listings shed by the serve gate
    "chunks_served",          # chunk requests answered to peers
    "chunks_shed",            # chunk requests shed with a retry hint
)

_LOCK = threading.Lock()
_COUNTS: Dict[str, int] = {f: 0 for f in FIELDS}


def bump(field: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTS[field] = _COUNTS.get(field, 0) + n


def stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_COUNTS)


def reset() -> None:
    with _LOCK:
        for f in list(_COUNTS):
            _COUNTS[f] = 0
