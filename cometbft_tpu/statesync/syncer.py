"""Statesync: bootstrap a fresh node from an application snapshot.

Reference: statesync/syncer.go — SyncAny (:145) discovers snapshots,
offers them to the app (:322 OfferSnapshot), downloads + applies chunks
(:358,:415), verifies the restored app hash against a light block, and
builds the post-restore State; stateprovider.go:40-76 embeds a light
client to fetch trusted headers/validator sets.

The snapshot/chunk transport is pluggable: the p2p reactor
(statesync/p2p_reactor.py) or any provider callable (tests).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import replace
from typing import Callable, Dict, List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.state.state import State
from cometbft_tpu.types.params import ConsensusParams

_log = logging.getLogger(__name__)


class StateSyncError(Exception):
    pass


class LightStateProvider:
    """stateprovider.go: trusted State + Commit via a light client.

    The light client verifies every header it hands out (bisection from
    a trusted root), so statesync inherits light-client security."""

    def __init__(self, light_client, now=None, params=None):
        self.lc = light_client
        self.now = now
        # ConsensusParams are consensus-critical (vote-extension
        # discipline) but not reconstructible from verified headers
        # (consensus_hash covers only block params) — the operator
        # supplies them from the genesis doc every node holds
        self.params = params or ConsensusParams()

    def state_at(self, height: int) -> State:
        """State after `height` is applied (stateprovider.go State):
        needs light blocks h, h+1, h+2 for last/current/next valsets."""
        lb_last = self.lc.verify_light_block_at_height(height, now=self.now)
        lb_cur = self.lc.verify_light_block_at_height(
            height + 1, now=self.now
        )
        lb_next = self.lc.verify_light_block_at_height(
            height + 2, now=self.now
        )
        hdr = lb_last.signed_header.header
        # the commit's BlockID carries the REAL PartSetHeader the network
        # committed under — a synthetic psh here would fail validate_block's
        # full-BlockID equality against every subsequent block's
        # header.last_block_id (execution.py:139)
        bid = lb_last.signed_header.commit.block_id
        if bid.hash != hdr.hash():
            raise StateSyncError("light block commit/header hash mismatch")
        return State(
            chain_id=hdr.chain_id,
            initial_height=1,
            last_block_height=height,
            last_block_id=bid,
            last_block_time=hdr.time,
            validators=lb_cur.validator_set.copy(),
            next_validators=lb_next.validator_set.copy(),
            last_validators=lb_last.validator_set.copy(),
            last_height_validators_changed=height + 1,
            consensus_params=self.params,
            app_hash=lb_cur.signed_header.header.app_hash,
            last_results_hash=lb_cur.signed_header.header.last_results_hash,
        )

    def commit_at(self, height: int):
        lb = self.lc.verify_light_block_at_height(height, now=self.now)
        return lb.signed_header.commit


class Syncer:
    """SyncAny (syncer.go:145) over pluggable snapshot sources."""

    def __init__(self, app: abci.Application, state_provider,
                 chunk_timeout: float = 10.0):
        self.app = app
        self.state_provider = state_provider
        self.chunk_timeout = chunk_timeout
        # snapshot discovery: {(height, format): (snapshot, fetch_chunk)}
        self._snapshots: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._have = threading.Event()

    def add_snapshot(self, snapshot: abci.Snapshot,
                     fetch_chunk: Callable[[int], Optional[bytes]]) -> None:
        with self._lock:
            self._snapshots[(snapshot.height, snapshot.format)] = (
                snapshot, fetch_chunk
            )
        self._have.set()

    def sync_any(self, discovery_time: float = 5.0) -> State:
        """Try the best discovered snapshot; on failure fall through to
        the next (syncer.go SyncAny retry loop)."""
        deadline = time.time() + discovery_time
        attempts: Dict[tuple, int] = {}
        while True:
            with self._lock:
                candidates = sorted(
                    self._snapshots.values(),
                    key=lambda t: -t[0].height,
                )
            for snapshot, fetch in candidates:
                key = (snapshot.height, snapshot.format)
                try:
                    return self._sync_one(snapshot, fetch)
                except Exception as e:  # noqa: BLE001 - ANY failure falls
                    # through to the next candidate: provider errors are
                    # often transient (e.g. the chain hasn't produced
                    # height+2 yet, which state_at needs), so each
                    # snapshot gets a few tries before being dropped
                    attempts[key] = attempts.get(key, 0) + 1
                    _log.warning("snapshot h=%d failed (try %d): %s",
                                 snapshot.height, attempts[key], e)
                    if attempts[key] >= 3:
                        with self._lock:
                            self._snapshots.pop(key, None)
            if time.time() > deadline:
                raise StateSyncError(
                    "no usable snapshot discovered in time"
                )
            self._have.wait(timeout=0.5)
            self._have.clear()

    def _sync_one(self, snapshot: abci.Snapshot, fetch_chunk) -> State:
        # trusted target state FIRST: the app hash to verify against
        # comes from the light client, never from the snapshot sender
        state = self.state_provider.state_at(snapshot.height)
        if not self.app.offer_snapshot(snapshot):
            raise StateSyncError("app rejected snapshot offer")
        for i in range(snapshot.chunks):
            chunk = fetch_chunk(i)
            if chunk is None:
                raise StateSyncError(f"chunk {i} unavailable")
            if not self.app.apply_snapshot_chunk(i, chunk, ""):
                raise StateSyncError(f"app rejected chunk {i}")
        # verify the restored app (syncer.go verifyApp): height + hash
        # must match the light-client-trusted header
        info = self.app.info(abci.RequestInfo())
        if info.last_block_height != snapshot.height:
            raise StateSyncError(
                f"app restored height {info.last_block_height}, "
                f"want {snapshot.height}"
            )
        if info.last_block_app_hash != state.app_hash:
            raise StateSyncError(
                "restored app hash does not match trusted header"
            )
        return state
