"""Statesync: bootstrap a fresh node from an application snapshot.

Reference: statesync/syncer.go — SyncAny (:145) discovers snapshots,
offers them to the app (:322 OfferSnapshot), downloads + applies chunks
(:358,:415), verifies the restored app hash against a light block, and
builds the post-restore State; stateprovider.go:40-76 embeds a light
client to fetch trusted headers/validator sets.

The snapshot/chunk transport is pluggable: the p2p reactor
(statesync/p2p_reactor.py) or any provider callable (tests).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import tracing
from cometbft_tpu.state.state import State
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.types.params import ConsensusParams

_log = logging.getLogger(__name__)


class StateSyncError(Exception):
    pass


class LightStateProvider:
    """stateprovider.go: trusted State + Commit via a light client.

    The light client verifies every header it hands out (bisection from
    a trusted root), so statesync inherits light-client security."""

    def __init__(self, light_client, now=None, params=None):
        self.lc = light_client
        self.now = now
        # ConsensusParams are consensus-critical (vote-extension
        # discipline) but not reconstructible from verified headers
        # (consensus_hash covers only block params) — the operator
        # supplies them from the genesis doc every node holds
        self.params = params or ConsensusParams()

    def state_at(self, height: int) -> State:
        """State after `height` is applied (stateprovider.go State):
        needs light blocks h, h+1, h+2 for last/current/next valsets."""
        lb_last = self.lc.verify_light_block_at_height(height, now=self.now)
        lb_cur = self.lc.verify_light_block_at_height(
            height + 1, now=self.now
        )
        lb_next = self.lc.verify_light_block_at_height(
            height + 2, now=self.now
        )
        hdr = lb_last.signed_header.header
        # the commit's BlockID carries the REAL PartSetHeader the network
        # committed under — a synthetic psh here would fail validate_block's
        # full-BlockID equality against every subsequent block's
        # header.last_block_id (execution.py:139)
        bid = lb_last.signed_header.commit.block_id
        if bid.hash != hdr.hash():
            raise StateSyncError("light block commit/header hash mismatch")
        return State(
            chain_id=hdr.chain_id,
            initial_height=1,
            last_block_height=height,
            last_block_id=bid,
            last_block_time=hdr.time,
            validators=lb_cur.validator_set.copy(),
            next_validators=lb_next.validator_set.copy(),
            last_validators=lb_last.validator_set.copy(),
            last_height_validators_changed=height + 1,
            consensus_params=self.params,
            app_hash=lb_cur.signed_header.header.app_hash,
            last_results_hash=lb_cur.signed_header.header.last_results_hash,
        )

    def commit_at(self, height: int):
        lb = self.lc.verify_light_block_at_height(height, now=self.now)
        return lb.signed_header.commit


class Syncer:
    """SyncAny (syncer.go:145) over pluggable snapshot sources.

    Chunks are fetched in parallel from EVERY peer offering the chosen
    snapshot (statesync/chunks.go engine): a slow or lying provider is
    timed out / punished and its slots re-requested from the others,
    and fetched chunks persist in cache_dir so a restart resumes
    instead of refetching."""

    def __init__(self, app: abci.Application, state_provider,
                 chunk_timeout: float = 10.0,
                 cache_dir: Optional[str] = None):
        self.app = app
        self.state_provider = state_provider
        self.chunk_timeout = chunk_timeout
        self.cache_dir = cache_dir
        # discovery: {(height, format): (snapshot, {provider_id: fetch})}
        self._snapshots: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._have = threading.Event()

    def add_snapshot(self, snapshot: abci.Snapshot,
                     fetch_chunk: Callable[[int], Optional[bytes]],
                     provider_id: Optional[str] = None) -> None:
        """Register a snapshot offer; multiple peers offering the same
        (height, format) become parallel chunk providers."""
        key = (snapshot.height, snapshot.format)
        with self._lock:
            snap, providers = self._snapshots.get(key, (snapshot, {}))
            providers = dict(providers)
            providers[provider_id or f"p{len(providers)}"] = fetch_chunk
            self._snapshots[key] = (snap, providers)
        self._have.set()

    def sync_any(self, discovery_time: float = 5.0) -> State:
        """Try the best discovered snapshot; on failure fall through to
        the next (syncer.go SyncAny retry loop)."""
        # the discovery deadline ages on the LEDGER clock (virtual
        # under simnet), not wall time — a wall-clock deadline here was
        # the PR 18 satellite bug that made bootstrap replays diverge
        deadline = tracing.monotonic_ns() + discovery_time * 1e9
        attempts: Dict[tuple, int] = {}
        while True:
            with self._lock:
                candidates = sorted(
                    self._snapshots.values(),
                    key=lambda t: -t[0].height,
                )
            for snapshot, providers in candidates:
                key = (snapshot.height, snapshot.format)
                try:
                    return self._sync_one(snapshot, providers)
                except Exception as e:  # noqa: BLE001 - ANY failure falls
                    # through to the next candidate: provider errors are
                    # often transient (e.g. the chain hasn't produced
                    # height+2 yet, which state_at needs), so each
                    # snapshot gets a few tries before being dropped.
                    # The chunk cache is NOT wiped here: _apply_chunks
                    # wipes it itself on content-rejection failures; a
                    # transient pre-fetch error must not throw away
                    # chunks a restarted node already holds.
                    attempts[key] = attempts.get(key, 0) + 1
                    _log.warning("snapshot h=%d failed (try %d): %s",
                                 snapshot.height, attempts[key], e)
                    if attempts[key] >= 3:
                        with self._lock:
                            self._snapshots.pop(key, None)
            if tracing.monotonic_ns() > deadline:
                raise StateSyncError(
                    "no usable snapshot discovered in time"
                )
            self._have.wait(timeout=0.5)
            self._have.clear()

    def _clear_cache(self, snapshot: abci.Snapshot) -> None:
        if not self.cache_dir:
            return
        import shutil

        shutil.rmtree(
            os.path.join(self.cache_dir,
                         f"{snapshot.height}-{snapshot.format}"),
            ignore_errors=True,
        )

    def _apply_chunks(self, snapshot, queue, fetcher, n_providers) -> None:
        """Apply chunks in order, steering by the app's result enum
        (syncer.go:415 applyChunks): RETRY refetches one chunk,
        RETRY_SNAPSHOT restarts the sequence with the suspect chunks
        refetched, ABORT/REJECT fail the snapshot."""
        i = retries = timeouts = rounds = 0
        max_timeouts = (n_providers + 2) * max(1, snapshot.chunks)
        while i < snapshot.chunks:
            chunk = queue.wait_for(i, self.chunk_timeout)
            if chunk is None:
                # a hung fetch must not pin its slot forever
                queue.reclaim_expired(self.chunk_timeout)
                ss_stats.bump("fetch_timeouts")
                timeouts += 1
                if not fetcher.has_providers() or timeouts > max_timeouts:
                    raise StateSyncError(
                        f"chunk {i} unavailable ({timeouts} timeouts)"
                    )
                continue
            sender = queue.sender_of(i) or ""
            resp = self.app.apply_snapshot_chunk(i, chunk, sender)
            if resp is True:
                resp = abci.ResponseApplySnapshotChunk()
            elif resp is False:
                resp = abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_RETRY
                )
            for s in resp.reject_senders:  # app-identified bad senders
                fetcher.punish(s)
                fetcher.punish(s)  # named rejection = instant drop
            if resp.result == abci.APPLY_CHUNK_ACCEPT:
                ss_stats.bump("chunks_applied")
                i += 1
                retries = 0
                continue
            if resp.result == abci.APPLY_CHUNK_RETRY:
                fetcher.punish(queue.retry(i))
                retries += 1
                if retries > n_providers + 1:
                    self._clear_cache(snapshot)
                    raise StateSyncError(f"app rejected chunk {i}")
                continue
            if resp.result == abci.APPLY_CHUNK_RETRY_SNAPSHOT:
                ss_stats.bump("retry_snapshot_rounds")
                rounds += 1
                if rounds > 3:
                    self._clear_cache(snapshot)
                    raise StateSyncError(
                        "snapshot kept failing verification"
                    )
                # senders of the refetched chunks are suspects (the hash
                # can't name the culprit) — ONE strike per provider per
                # round, or the honest peer that served most chunks
                # would be dropped before the one that poisoned one
                suspects = set()
                for idx in resp.refetch_chunks:
                    suspects.add(queue.retry(idx))
                for s in suspects:
                    fetcher.punish(s)
                if not self.app.offer_snapshot(snapshot):
                    self._clear_cache(snapshot)
                    raise StateSyncError("app closed the restore session")
                i = 0
                continue
            self._clear_cache(snapshot)
            raise StateSyncError(
                f"app aborted snapshot restore (result={resp.result})"
            )

    def _sync_one(self, snapshot: abci.Snapshot, providers) -> State:
        from cometbft_tpu.statesync.chunks import ChunkFetcher, ChunkQueue

        if callable(providers):  # single bare fetch fn (test shims)
            providers = {"p0": providers}
        # trusted target state FIRST: the app hash to verify against
        # comes from the light client, never from the snapshot sender
        state = self.state_provider.state_at(snapshot.height)
        if not self.app.offer_snapshot(snapshot):
            raise StateSyncError("app rejected snapshot offer")
        ss_stats.bump("snapshots_offered")
        cache = None
        if self.cache_dir:
            cache = os.path.join(
                self.cache_dir, f"{snapshot.height}-{snapshot.format}"
            )
        queue = ChunkQueue(snapshot.chunks, cache_dir=cache)
        fetcher = ChunkFetcher(queue, providers,
                               chunk_timeout=self.chunk_timeout)
        fetcher.start()
        try:
            self._apply_chunks(snapshot, queue, fetcher, len(providers))
        finally:
            fetcher.stop()
        # verify the restored app (syncer.go verifyApp): height + hash
        # must match the light-client-trusted header
        info = self.app.info(abci.RequestInfo())
        if info.last_block_height != snapshot.height:
            raise StateSyncError(
                f"app restored height {info.last_block_height}, "
                f"want {snapshot.height}"
            )
        if info.last_block_app_hash != state.app_hash:
            raise StateSyncError(
                "restored app hash does not match trusted header"
            )
        ss_stats.bump("snapshots_restored")
        return state
