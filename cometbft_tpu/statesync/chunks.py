"""Statesync chunk engine: parallel multi-peer fetch with retry.

Reference: statesync/chunks.go — the chunk queue allocates slot indices
to concurrent fetchers, accepts the first copy of each chunk (persisting
it so a restart doesn't refetch), lets the applier retry/refetch, and
tracks which provider served what so bad senders can be punished;
syncer.go:358-445 drives it with one fetcher per peer and a per-chunk
timeout (`chunkTimeout`) that re-requests from a different peer.

The engine is transport-agnostic: providers are callables
`fetch(index) -> Optional[bytes]` keyed by an opaque provider id (the
p2p reactor registers one per peer serving the snapshot).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.statesync import stats as ss_stats

_log = logging.getLogger(__name__)

fp.register("statesync.fetch",
            "statesync chunk fetch (per provider worker, before the "
            "transport call) — raise/flake fault a provider without "
            "touching the others")


def _mono() -> float:
    """The LEDGER clock in seconds: virtual under the simnet's
    installed module clock, perf_counter otherwise. Chunk request ages
    and applier deadlines used raw ``time.monotonic()`` before PR 18,
    which made the simnet bootstrap scenario non-replayable — the same
    wall-clock-in-a-deadline bug PR 7 fixed for BULK sheds."""
    return tracing.monotonic_ns() / 1e9

# provider is dropped after this many failures (timeout, None, or a
# chunk the app rejected) — syncer.go bans the peer outright
MAX_PROVIDER_FAILURES = 2

PENDING, REQUESTED, RECEIVED = 0, 1, 2


class ChunkQueue:
    """Slot state for one snapshot's chunks (chunks.go chunkQueue).

    Thread-safe: fetcher threads allocate() slots and add() payloads;
    the applier next() blocks for chunk i and retry()s rejects."""

    def __init__(self, n_chunks: int, cache_dir: Optional[str] = None):
        self.n = n_chunks
        self.cache_dir = cache_dir
        self._status = [PENDING] * n_chunks
        self._data: List[Optional[bytes]] = [None] * n_chunks
        self._sender: List[Optional[str]] = [None] * n_chunks
        self._req_at = [0.0] * n_chunks
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            for i in range(n_chunks):
                p = self._path(i)
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        self._data[i] = f.read()
                    self._status[i] = RECEIVED
                    self._sender[i] = "cache"

    def _path(self, i: int) -> str:
        return os.path.join(self.cache_dir, f"chunk-{i:06d}")

    def allocate(self) -> Optional[int]:
        """Next pending slot -> REQUESTED, or None when nothing pending
        (chunks.go Allocate)."""
        with self._lock:
            for i in range(self.n):
                if self._status[i] == PENDING:
                    self._status[i] = REQUESTED
                    self._req_at[i] = _mono()
                    return i
            return None

    def reclaim_expired(self, max_age: float) -> int:
        """REQUESTED slots older than max_age back to PENDING — a hung
        provider must not pin a slot forever (the chunkTimeout
        re-request of syncer.go:415). Returns how many were reclaimed."""
        now = _mono()
        n = 0
        with self._cond:
            for i in range(self.n):
                if self._status[i] == REQUESTED \
                        and now - self._req_at[i] > max_age:
                    self._status[i] = PENDING
                    n += 1
            if n:
                self._cond.notify_all()
        return n

    def add(self, i: int, data: bytes, sender: str) -> bool:
        """First copy of chunk i wins; duplicates return False
        (chunks.go Add). Persists to the cache dir for restart safety."""
        with self._cond:
            if self._status[i] == RECEIVED:
                return False
            if self.cache_dir:
                tmp = self._path(i) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, self._path(i))
            self._data[i] = data
            self._sender[i] = sender
            self._status[i] = RECEIVED
            ss_stats.bump("chunks_fetched")
            self._cond.notify_all()
            return True

    def release(self, i: int) -> None:
        """REQUESTED -> PENDING (fetch failed; another worker retries)."""
        with self._cond:
            if self._status[i] == REQUESTED:
                self._status[i] = PENDING
                self._cond.notify_all()

    def retry(self, i: int) -> Optional[str]:
        """Discard a received chunk the app rejected so it refetches;
        returns who sent it (to punish). chunks.go Retry + GetSender."""
        with self._cond:
            sender = self._sender[i]
            self._data[i] = None
            self._sender[i] = None
            self._status[i] = PENDING
            if self.cache_dir:
                try:
                    os.unlink(self._path(i))
                except OSError:
                    pass
            self._cond.notify_all()
            return sender

    def wait_for(self, i: int, timeout: float) -> Optional[bytes]:
        """Block until chunk i is RECEIVED (the applier side)."""
        deadline = _mono() + timeout
        with self._cond:
            while self._status[i] != RECEIVED:
                left = deadline - _mono()
                if left <= 0:
                    return None
                self._cond.wait(left)
            return self._data[i]

    def sender_of(self, i: int) -> Optional[str]:
        with self._lock:
            return self._sender[i]

    def done(self) -> bool:
        with self._lock:
            return all(s == RECEIVED for s in self._status)


class ChunkFetcher:
    """Parallel fetch of a ChunkQueue from multiple scored providers.

    One worker per provider (like the reference's per-peer fetch
    routines, syncer.go:358): each worker allocates a slot, asks ITS
    provider, and on timeout/failure releases the slot for another
    worker — so a slow or dead peer degrades throughput instead of
    stalling the sync. Providers accumulate failures and are dropped at
    MAX_PROVIDER_FAILURES."""

    def __init__(self, queue: ChunkQueue,
                 providers: Dict[str, Callable[[int], Optional[bytes]]],
                 chunk_timeout: float = 10.0):
        self.q = queue
        self.providers = dict(providers)
        self.chunk_timeout = chunk_timeout
        self.failures: Dict[str, int] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()

    def punish(self, provider_id: Optional[str]) -> None:
        """Count a failure against a provider; drop it at the limit
        (the syncer calls this for rejected chunks too)."""
        if provider_id is None:
            return
        ss_stats.bump("providers_punished")
        with self._lock:
            self.failures[provider_id] = self.failures.get(
                provider_id, 0) + 1
            if self.failures[provider_id] >= MAX_PROVIDER_FAILURES:
                if self.providers.pop(provider_id, None) is not None:
                    ss_stats.bump("providers_dropped")
                    _log.warning("statesync: dropping provider %s",
                                 provider_id)

    def _alive(self, pid: str) -> bool:
        with self._lock:
            return pid in self.providers

    def _worker(self, pid: str,
                fetch: Callable[[int], Optional[bytes]]) -> None:
        # workers never exit on queue.done(): the applier may RETRY a
        # received chunk the app rejected, turning slots pending again.
        # They idle until stop() (the syncer's finally) shuts them down.
        while not self._stop.is_set() and self._alive(pid):
            i = self.q.allocate()
            if i is None:
                time.sleep(0.05)  # nothing pending right now
                continue
            try:
                fp.fail_point("statesync.fetch")
                data = fetch(i)
            except Exception as e:  # noqa: BLE001 - provider transport
                _log.warning("statesync: provider %s chunk %d: %s",
                             pid, i, e)
                data = None
            if data is None:
                self.q.release(i)
                self.punish(pid)
            elif not self.q.add(i, data, pid):
                pass  # duplicate; someone else was faster

    def start(self) -> None:
        for pid, fetch in list(self.providers.items()):
            th = threading.Thread(
                target=self._worker, args=(pid, fetch),
                daemon=True, name=f"chunk-fetch-{pid}",
            )
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=2.0)

    def has_providers(self) -> bool:
        with self._lock:
            return bool(self.providers)
