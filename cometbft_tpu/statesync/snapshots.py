"""Archival snapshot serving: merkle-chunked snapshots + the serve gate.

The serving half of the bootstrap plane (PR 18). Two weaknesses in the
reference-shaped statesync this module fixes:

  * **Unattributable chunks.** The kvstore reference hashes the WHOLE
    snapshot blob, so a single poisoned chunk forces RETRY_SNAPSHOT on
    everything (and the honest provider that served most chunks eats a
    punish strike alongside the liar). Format-2 snapshots hash the
    chunk list into a MERKLE root (crypto/merkle, the block-parts
    discipline) and every served chunk carries its inclusion proof —
    the restoring peer verifies each chunk on arrival, names the exact
    bad one, and punishes only its sender.

  * **Unbounded serving.** The p2p reactor answered every ``chunk_req``
    unconditionally, so a bootstrap storm (hundreds of joining nodes
    sampling a few archival hosts) would starve the donor's own
    consensus. The :class:`ServeGate` is a per-peer token bucket on the
    LEDGER clock: over-budget requests are shed with an EXPLICIT
    retry-hinted verdict (:class:`SnapshotServeOverloaded`, the
    ``PlaneOverloaded`` contract), never silently dropped — and the
    CONSENSUS lane is structurally untouchable because serving work
    never enters it at all.

Snapshot generation rides :class:`SnapshotArchive`: any state blob
(the app's committed state, or a document assembled from the
block/state stores) becomes a chunked, merkle-rooted, servable
snapshot. The archive is store-agnostic on purpose — the persistent
soak app and bench both feed it directly.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from cometbft_tpu.abci import types as abci
from cometbft_tpu.crypto import merkle
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.statesync import stats as ss_stats
from cometbft_tpu.verifyplane import PlaneOverloaded

fp.register("snapshot.serve",
            "snapshot/chunk serving seam in the statesync p2p reactor "
            "(after gate admission, before the store read)")

SNAPSHOT_FORMAT_MERKLE = 2
CHUNK_SIZE = 64 * 1024


class SnapshotServeOverloaded(PlaneOverloaded):
    """A serving shed: the donor is over its per-peer serving budget.

    Carries ``retry_after_ms`` (inherited) so the verdict is a retry
    hint, not a failure — the requesting peer backs off instead of
    punishing the donor or hammering it harder."""


# -- merkle-chunked snapshots ----------------------------------------------


def chunk_blob(blob: bytes, chunk_size: int = CHUNK_SIZE) -> List[bytes]:
    return [blob[i:i + chunk_size]
            for i in range(0, max(len(blob), 1), chunk_size)]


def proof_doc(p: merkle.Proof) -> dict:
    """Wire form of a chunk inclusion proof (hex, JSON-safe)."""
    return {"t": p.total, "i": p.index, "l": p.leaf_hash.hex(),
            "a": [a.hex() for a in p.aunts]}


def proof_from_doc(doc: dict) -> merkle.Proof:
    return merkle.Proof(
        total=int(doc["t"]), index=int(doc["i"]),
        leaf_hash=bytes.fromhex(doc["l"]),
        aunts=[bytes.fromhex(a) for a in doc.get("a", [])],
    )


def verify_chunk(root: bytes, chunk: bytes, doc: dict) -> bool:
    """Client-side: does this chunk belong at this index under the
    snapshot's merkle root? A False here names the bad chunk (and its
    sender) without waiting for the whole blob to mis-hash."""
    try:
        return proof_from_doc(doc).verify(root, chunk)
    except (KeyError, ValueError, TypeError):
        return False


class SnapshotArchive:
    """Format-2 snapshots generated from any state blob, kept bounded.

    ``generate(height, blob)`` chunks the blob, roots the chunk list
    (``hash`` = merkle root, so offers are self-authenticating down to
    the chunk), and retains the last ``keep`` snapshots — the same
    bounded retention the kvstore reference applies to its format-1
    set. Thread-safe: generation happens on the commit path while the
    p2p reactor serves from another thread."""

    def __init__(self, keep: int = 3, chunk_size: int = CHUNK_SIZE):
        self.keep = max(1, int(keep))
        self.chunk_size = int(chunk_size)
        # {(height, format): (snapshot, chunks, proofs)}
        self._snaps: Dict[Tuple[int, int], tuple] = {}
        self._lock = threading.Lock()

    def generate(self, height: int, blob: bytes) -> abci.Snapshot:
        chunks = chunk_blob(blob, self.chunk_size)
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        snap = abci.Snapshot(
            height=int(height), format=SNAPSHOT_FORMAT_MERKLE,
            chunks=len(chunks), hash=root,
        )
        with self._lock:
            self._snaps[(snap.height, snap.format)] = (snap, chunks,
                                                       proofs)
            for key in sorted(self._snaps)[:-self.keep]:
                del self._snaps[key]
        return snap

    def list_snapshots(self) -> List[abci.Snapshot]:
        with self._lock:
            return [s for s, _, _ in
                    (self._snaps[k] for k in sorted(self._snaps))]

    def load_chunk(self, height: int, fmt: int, idx: int) -> bytes:
        with self._lock:
            ent = self._snaps.get((height, fmt))
        if ent is None or not 0 <= idx < len(ent[1]):
            return b""
        return ent[1][idx]

    def proof_for(self, height: int, fmt: int,
                  idx: int) -> Optional[merkle.Proof]:
        with self._lock:
            ent = self._snaps.get((height, fmt))
        if ent is None or not 0 <= idx < len(ent[2]):
            return None
        return ent[2][idx]


class SnapshotCatalog:
    """Per-chunk merkle proofs for snapshots an APP serves (format 1
    included): the chunk list is read once through
    ``app.load_snapshot_chunk``, rooted, and cached bounded — so even
    legacy whole-blob-hash snapshots get chunk-level attribution on the
    wire (the root rides the offer metadata; the trusted app-hash check
    at the end of restore still anchors end-to-end integrity)."""

    def __init__(self, app: abci.Application, max_entries: int = 4):
        self.app = app
        self.max_entries = max(1, int(max_entries))
        self._cache: Dict[Tuple[int, int], tuple] = {}
        self._lock = threading.Lock()

    def _build(self, height: int, fmt: int, n_chunks: int):
        chunks = [self.app.load_snapshot_chunk(height, fmt, i)
                  for i in range(n_chunks)]
        return merkle.proofs_from_byte_slices(chunks)

    def root_and_proofs(self, height: int, fmt: int,
                        n_chunks: int) -> Optional[tuple]:
        key = (height, fmt)
        with self._lock:
            ent = self._cache.get(key)
        if ent is not None:
            return ent
        try:
            ent = self._build(height, fmt, n_chunks)
        except Exception:  # noqa: BLE001 - a sick app must not kill serving
            return None
        with self._lock:
            self._cache[key] = ent
            while len(self._cache) > self.max_entries:
                del self._cache[min(self._cache)]
        return ent


# -- the serve gate ---------------------------------------------------------


class ServeGate:
    """Per-peer token bucket for snapshot/chunk serving, on the ledger
    clock (virtual under simnet — a chaos soak's sheds replay
    byte-identically).

    Each peer holds ``burst`` tokens refilled at ``rate_per_s``; a
    request costs one. Over-budget requests raise
    :class:`SnapshotServeOverloaded` with the exact ``retry_after_ms``
    until the next token — the donor degrades HONESTLY under a
    bootstrap storm instead of silently starving. The peer table is
    bounded: least-recently-active peers are evicted past
    ``max_peers`` (a Sybil flood can't grow donor memory)."""

    def __init__(self, rate_per_s: float = 16.0, burst: int = 8,
                 max_peers: int = 256):
        self.rate_per_s = float(rate_per_s)
        self.burst = float(max(1, burst))
        self.max_peers = int(max_peers)
        self._peers: Dict[str, List[float]] = {}  # pid -> [tokens, at_ns]
        self._lock = threading.Lock()
        self.served = 0
        self.sheds = 0

    def admit(self, peer_id: str, kind: str = "chunk") -> None:
        """Charge one token or shed with a retry hint."""
        now = tracing.monotonic_ns()
        with self._lock:
            ent = self._peers.get(peer_id)
            if ent is None:
                ent = self._peers[peer_id] = [self.burst, now]
                if len(self._peers) > self.max_peers:
                    oldest = min(self._peers,
                                 key=lambda p: self._peers[p][1])
                    del self._peers[oldest]
            tokens, at = ent
            tokens = min(self.burst,
                         tokens + (now - at) * self.rate_per_s / 1e9)
            if tokens >= 1.0:
                ent[0], ent[1] = tokens - 1.0, now
                self.served += 1
                return
            ent[0], ent[1] = tokens, now
            self.sheds += 1
            retry_ms = (1.0 - tokens) / self.rate_per_s * 1000.0
        ss_stats.bump("chunks_shed" if kind == "chunk"
                      else "snapshots_shed")
        raise SnapshotServeOverloaded(
            f"serving budget exhausted for peer {peer_id} ({kind})",
            retry_after_ms=retry_ms,
        )

    def stats(self) -> dict:
        with self._lock:
            return {"served": self.served, "sheds": self.sheds,
                    "peers": len(self._peers),
                    "rate_per_s": self.rate_per_s, "burst": self.burst}
