"""BlockStore: persisted blocks, commits and seen-commits by height.

Reference: store/store.go:53 (BlockStore over cometbft-db), SaveBlock
(:401), LoadBlock/LoadBlockCommit/LoadSeenCommit (:254-300), Base/Height
bookkeeping, PruneBlocks (:301). sqlite3 (stdlib) plays the role of
cometbft-db: single writer, transactional batch save.
"""
from __future__ import annotations

import sqlite3
import threading
from typing import Optional

from cometbft_tpu.types import serde
from cometbft_tpu.types.block import Block
from cometbft_tpu.types.commit import Commit


class BlockStore:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._db:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS blocks ("
                "height INTEGER PRIMARY KEY, hash BLOB, block TEXT, "
                "commit_json TEXT, seen_commit TEXT, ext_commit TEXT)"
            )
            # migrate pre-extension databases (5-column schema)
            cols = [r[1] for r in
                    self._db.execute("PRAGMA table_info(blocks)")]
            if "ext_commit" not in cols:
                self._db.execute(
                    "ALTER TABLE blocks ADD COLUMN ext_commit TEXT"
                )
            self._db.execute(
                "CREATE INDEX IF NOT EXISTS blocks_hash ON blocks(hash)"
            )

    def base(self) -> int:
        with self._lock:
            cur = self._db.execute("SELECT MIN(height) FROM blocks")
            r = cur.fetchone()[0]
            return r if r is not None else 0

    def height(self) -> int:
        with self._lock:
            cur = self._db.execute("SELECT MAX(height) FROM blocks")
            r = cur.fetchone()[0]
            return r if r is not None else 0

    def save_block(self, block: Block, seen_commit: Commit,
                   extended_commit=None) -> None:
        """SaveBlock (store.go:401) / SaveBlockWithExtendedCommit
        (store.go:254): block + its own SeenCommit (+ the ExtendedCommit
        with vote extensions, when enabled); the block's LastCommit rides
        inside the block."""
        h = block.header.height
        ext = (serde.json.dumps(serde.extcommit_to_j(extended_commit))
               if extended_commit is not None else None)
        with self._lock, self._db:
            self._db.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?,?,?,?,?,?)",
                (
                    h,
                    block.hash(),
                    serde.block_to_json(block),
                    serde.json.dumps(serde.commit_to_j(block.last_commit)),
                    serde.json.dumps(serde.commit_to_j(seen_commit)),
                    ext,
                ),
            )

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        """Store a commit with NO block (store.go:277 SaveSeenCommit):
        statesync persists the restore height's commit so a freshly
        synced proposer can build height+1's LastCommit."""
        with self._lock, self._db:
            # upsert ONLY the seen_commit column: a plain REPLACE would
            # null out an existing block row at this height
            self._db.execute(
                "INSERT INTO blocks(height, seen_commit) VALUES (?,?) "
                "ON CONFLICT(height) DO UPDATE SET "
                "seen_commit=excluded.seen_commit",
                (height, serde.json.dumps(serde.commit_to_j(commit))),
            )

    def load_block(self, height: int) -> Optional[Block]:
        with self._lock:
            cur = self._db.execute(
                "SELECT block FROM blocks WHERE height=?", (height,)
            )
            row = cur.fetchone()
            return serde.block_from_json(row[0]) if row and row[0] else None

    def load_block_by_hash(self, h: bytes) -> Optional[Block]:
        with self._lock:
            cur = self._db.execute(
                "SELECT block FROM blocks WHERE hash=?", (h,)
            )
            row = cur.fetchone()
            return serde.block_from_json(row[0]) if row else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The commit FOR block `height`, stored in block height+1's
        LastCommit (store.go LoadBlockCommit loads it directly)."""
        with self._lock:
            cur = self._db.execute(
                "SELECT commit_json FROM blocks WHERE height=?", (height + 1,)
            )
            row = cur.fetchone()
        if row and row[0]:
            return serde.commit_from_j(serde.json.loads(row[0]))
        return self.load_seen_commit(height)

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        with self._lock:
            cur = self._db.execute(
                "SELECT seen_commit FROM blocks WHERE height=?", (height,)
            )
            row = cur.fetchone()
            return (
                serde.commit_from_j(serde.json.loads(row[0]))
                if row and row[0] else None
            )

    def load_extended_commit(self, height: int):
        """LoadBlockExtendedCommit (store.go:286): the seen commit WITH
        vote extensions, present only when extensions were enabled at
        save time."""
        with self._lock:
            cur = self._db.execute(
                "SELECT ext_commit FROM blocks WHERE height=?", (height,)
            )
            row = cur.fetchone()
            return (
                serde.extcommit_from_j(serde.json.loads(row[0]))
                if row and row[0] else None
            )

    def remove_block(self, height: int) -> None:
        """Delete one block row (rollback --remove-block;
        state/rollback.go's store arm)."""
        with self._lock, self._db:
            self._db.execute("DELETE FROM blocks WHERE height=?",
                             (height,))

    def prune_blocks(self, retain_height: int) -> int:
        """Delete blocks below retain_height (store.go:301)."""
        with self._lock, self._db:
            cur = self._db.execute(
                "DELETE FROM blocks WHERE height < ?", (retain_height,)
            )
            return cur.rowcount

    def close(self) -> None:
        with self._lock:
            self._db.close()
