"""Operator CLI: init / start / testnet / show-node-id / reset.

Reference: cmd/cometbft/commands/ (cobra): init.go, run_node.go,
testnet.go, show_node_id.go, reset.go. `python -m cometbft_tpu <cmd>`.
"""
from __future__ import annotations

import argparse
import os
import shutil
import signal
import sys
import time

from cometbft_tpu.config.config import (
    Config,
    default_home,
    load_config,
    save_config,
)


def _home_arg(p):
    p.add_argument("--home", default=default_home(),
                   help="node home directory")


def _config_path(home):
    return os.path.join(home, "config", "config.toml")


def cmd_init(args) -> int:
    """init.go: write config.toml, genesis.json, node_key.json,
    priv_validator_key.json."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    home = args.home
    cfgdir = os.path.join(home, "config")
    datadir = os.path.join(home, "data")
    os.makedirs(cfgdir, exist_ok=True)
    os.makedirs(datadir, exist_ok=True)

    cfg = Config()
    if args.chain_id:
        cfg.base.chain_id = args.chain_id
    cfg.crypto.verifier = args.verifier
    save_config(cfg, _config_path(home))

    pv = FilePV.generate(cfgdir) if not os.path.exists(
        os.path.join(cfgdir, "priv_validator_key.json")
    ) else FilePV.load(cfgdir)
    NodeKey.load_or_gen(os.path.join(cfgdir, "node_key.json"))

    gpath = os.path.join(cfgdir, "genesis.json")
    if not os.path.exists(gpath):
        doc = GenesisDoc(
            chain_id=cfg.base.chain_id,
            genesis_time=Timestamp.now(),
            validators=[GenesisValidator(pv.pub_key(), 10, "validator")],
        )
        doc.save_as(gpath)
    print(f"Initialized node in {home}")
    return 0


def build_node(home: str, cfg=None):
    """Assemble a Node from a home directory (run_node.go -> NewNode)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc

    cfg = cfg or load_config(_config_path(home))
    # arm configured failpoints before any instrumented module runs a
    # seam (CBT_FAILPOINTS env arming happens lazily regardless), and
    # install the tracer first so node assembly itself is traceable
    cfg.tracing.apply()
    cfg.failpoints.apply()
    # incident watchdog thresholds + the config fingerprint frozen
    # into every snapshot (what this node was RUNNING when it fired)
    cfg.incidents.apply(fingerprint={
        "chain_id": cfg.base.chain_id,
        "moniker": cfg.base.moniker,
        "verifier": cfg.crypto.verifier,
        "verify_plane": cfg.verify_plane.enable,
        "mesh": cfg.verify_plane.mesh,
        "pipeline_flights": cfg.verify_plane.pipeline_flights,
        "mempool_admission": cfg.mempool.admission,
        "tracing": cfg.tracing.enable,
    })
    cfgdir = os.path.join(home, "config")
    doc = GenesisDoc.from_file(os.path.join(cfgdir, "genesis.json"))
    pa = cfg.base.proxy_app
    if pa == "kvstore":
        app = KVStoreApplication()
    elif "://" in pa or ":" in pa:
        # out-of-process app: tcp:// socket or grpc:// server
        # (proxy/client.go DefaultClientCreator address dispatch)
        from cometbft_tpu.abci.proxy import AppConns

        app = AppConns.from_addr(pa)
    else:
        raise SystemExit(
            f"unknown proxy_app {pa!r} (use 'kvstore', 'tcp://h:p' "
            f"for a socket ABCI server, or 'grpc://h:p' for gRPC)"
        )
    import json as _json

    node = Node(
        app,
        doc.make_state(),
        privval=FilePV.load(cfgdir),
        home=os.path.join(home, "data"),
        timeouts=cfg.consensus.timeout_params(),
        batch_fn=cfg.crypto.batch_fn(),
        verify_plane=cfg.verify_plane,
        mempool_config=cfg.mempool,
        lightgate=cfg.lightgate,
        controller=cfg.controller,
        p2p=True,
        node_key=NodeKey.load_or_gen(os.path.join(cfgdir, "node_key.json")),
        blocksync=cfg.base.blocksync,
        app_state_bytes=(_json.dumps(doc.app_state).encode()
                         if doc.app_state else b""),
    )
    # the full doc backs the genesis/genesis_chunked RPCs
    node.genesis_doc = _json.loads(doc.to_json())
    return node, cfg


def _parse_addr(laddr: str):
    hostport = laddr.split("://", 1)[-1]
    host, _, port = hostport.rpartition(":")
    return host or "0.0.0.0", int(port)


def cmd_start(args) -> int:
    """run_node.go: assemble, listen, dial persistent peers, serve RPC."""
    from cometbft_tpu.p2p.key import NetAddress

    node, cfg = build_node(args.home)
    host, port = _parse_addr(cfg.p2p.laddr)
    node.start()
    addr = node.listen(host, port)
    print(f"p2p listening on {addr.host}:{addr.port} (id {addr.node_id})")
    if cfg.rpc.enabled:
        rh, rp = _parse_addr(cfg.rpc.laddr)
        url = node.rpc_listen(rh, rp, unsafe=cfg.rpc.unsafe)
        print(f"rpc listening on {url}")
    for peer in filter(None, cfg.p2p.persistent_peers.split(",")):
        pid, hostport = peer.strip().split("@")
        h, _, p = hostport.rpartition(":")
        node.dial(NetAddress(pid, h, int(p)))

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop and (args.run_for <= 0
                            or time.time() < args._t0 + args.run_for):
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def cmd_testnet(args) -> int:
    """testnet.go: generate n validator home dirs wired to each other."""
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.types.timestamp import Timestamp

    n = args.v
    homes = [os.path.join(args.output, f"node{i}") for i in range(n)]
    pvs, keys = [], []
    for home in homes:
        cfgdir = os.path.join(home, "config")
        os.makedirs(cfgdir, exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pvs.append(FilePV.generate(cfgdir))
        keys.append(NodeKey.load_or_gen(
            os.path.join(cfgdir, "node_key.json")))
    doc = GenesisDoc(
        chain_id=args.chain_id or "cbt-testnet",
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(pv.pub_key(), 10, f"node{i}")
                    for i, pv in enumerate(pvs)],
    )
    # two ports per node (p2p, rpc) so the ranges can never collide
    # (testnet.go allocates per-node port pairs the same way)
    base_p2p, base_rpc = args.p2p_port, args.rpc_port
    p2p_port = lambda i: base_p2p + 2 * i
    rpc_port = lambda i: base_rpc + 2 * i
    for i, home in enumerate(homes):
        cfg = Config()
        cfg.base.chain_id = doc.chain_id
        cfg.base.blocksync = False  # all start at genesis together
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port(i)}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port(i)}"
        cfg.p2p.persistent_peers = ",".join(
            f"{keys[j].node_id}@127.0.0.1:{p2p_port(j)}"
            for j in range(n) if j != i
        )
        save_config(cfg, _config_path(home))
        doc.save_as(os.path.join(home, "config", "genesis.json"))
    print(f"Generated {n}-node testnet in {args.output}")
    return 0


def cmd_show_node_id(args) -> int:
    from cometbft_tpu.p2p.key import NodeKey

    nk = NodeKey.load_or_gen(
        os.path.join(args.home, "config", "node_key.json"))
    print(nk.node_id)
    return 0


def cmd_reset(args) -> int:
    """reset.go unsafe-reset-all: wipe data, keep config + keys."""
    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    state = os.path.join(args.home, "config", "priv_validator_state.json")
    if os.path.exists(state):
        os.remove(state)
    print(f"Reset {data}")
    return 0


def cmd_rollback(args) -> int:
    """rollback.go: rewind state by one height so the node re-applies
    the last block (e.g. after a bad upgrade produced a wrong app hash).
    With --remove-block the block itself is deleted too."""
    from cometbft_tpu.state.state import StateStore
    from cometbft_tpu.store.blockstore import BlockStore

    data = os.path.join(args.home, "data")
    if not os.path.isdir(data):
        print(f"nothing to roll back (no data dir at {data})")
        return 1
    ss = StateStore(os.path.join(data, "state.db"))
    bs = BlockStore(os.path.join(data, "blockstore.db"))
    state = ss.load()
    if state is None or state.last_block_height < 1:
        print("nothing to roll back")
        return 1
    h = state.last_block_height
    rolled = rollback_state(state, ss, bs)
    ss.save(rolled)
    if args.remove_block:
        bs.remove_block(h)
    print(f"Rolled back state to height {rolled.last_block_height} "
          f"and app hash {rolled.app_hash.hex()}")
    return 0


def rollback_state(state, ss, bs):
    """state/rollback.go Rollback: reconstruct the post-(H-1) state from
    block H's header + the validator-set history."""
    from dataclasses import replace

    h = state.last_block_height
    block = bs.load_block(h)
    if block is None:
        raise SystemExit(f"block {h} not found; cannot roll back")
    prev = bs.load_block(h - 1)
    vals = ss.load_validators(h)
    next_vals = ss.load_validators(h + 1) or state.validators
    last_vals = ss.load_validators(h - 1)
    if vals is None:
        raise SystemExit(f"no validator history for height {h}")
    return replace(
        state,
        last_block_height=h - 1,
        last_block_id=block.header.last_block_id,
        last_block_time=(prev.header.time if prev is not None
                         else state.last_block_time),
        validators=vals,
        next_validators=next_vals,
        last_validators=last_vals,
        app_hash=block.header.app_hash,
        last_results_hash=block.header.last_results_hash,
    )


def cmd_compact(args) -> int:
    """compact.go analog: VACUUM every sqlite database in data/."""
    import sqlite3

    data = os.path.join(args.home, "data")
    n = 0
    for name in sorted(os.listdir(data) if os.path.isdir(data) else []):
        if not name.endswith(".db"):
            continue
        path = os.path.join(data, name)
        before = os.path.getsize(path)
        conn = sqlite3.connect(path)
        conn.execute("VACUUM")
        conn.close()
        after = os.path.getsize(path)
        print(f"{name}: {before} -> {after} bytes")
        n += 1
    print(f"Compacted {n} databases")
    return 0


def cmd_reindex_event(args) -> int:
    """reindex_event.go: rebuild the tx + block indexes from stored
    blocks and FinalizeBlock responses — operator recovery after an
    index wipe or an indexing bug. Node must be stopped (the command
    opens the data dir directly, like the reference)."""
    from cometbft_tpu.abci.types import ExecTxResult
    from cometbft_tpu.state.indexer import BlockIndexer, TxIndexer
    from cometbft_tpu.state.state import StateStore
    from cometbft_tpu.store.blockstore import BlockStore

    data = os.path.join(args.home, "data")
    if not os.path.isdir(data):
        print(f"no data dir at {data}", file=sys.stderr)
        return 1
    bs = BlockStore(os.path.join(data, "blockstore.db"))
    ss = StateStore(os.path.join(data, "state.db"))
    txi = TxIndexer(os.path.join(data, "tx_index.db"))
    bli = BlockIndexer(os.path.join(data, "block_index.db"))
    base, head = bs.base(), bs.height()
    start = max(args.start_height or base, base, 1)
    end = min(args.end_height or head, head)
    if start > end:
        print(f"invalid height range [{start}, {end}] "
              f"(store has [{base}, {head}])", file=sys.stderr)
        return 1
    n_txs = 0
    skipped = 0
    for h in range(start, end + 1):
        block = bs.load_block(h)
        if block is None:
            print(f"height {h}: block missing (pruned?), skipping")
            continue
        doc = ss.load_abci_responses(h)
        results = (doc or {}).get("tx_results", [])
        if block.data.txs and len(results) < len(block.data.txs):
            # never fabricate results: indexing a failed tx as code=0
            # would corrupt tx_search (the reference requires stored
            # ABCI responses for every reindexed height)
            print(f"height {h}: FinalizeBlock responses missing/pruned "
                  f"({len(results)}/{len(block.data.txs)} results); "
                  f"skipping its txs")
            skipped += 1
        else:
            for i, tx in enumerate(block.data.txs):
                rj = results[i]
                res = ExecTxResult(
                    code=rj.get("code", 0),
                    data=bytes.fromhex(rj.get("data", "")),
                    log=rj.get("log", ""),
                    gas_wanted=rj.get("gas_wanted", 0),
                    gas_used=rj.get("gas_used", 0),
                )
                txi.index(h, i, tx, res, rj.get("events") or {})
                n_txs += 1
        bli.index(h, {"block.proposer":
                      [block.header.proposer_address.hex().upper()]})
    for dbh in (bs, ss, txi, bli):
        dbh.close()
    print(f"reindexed heights [{start}, {end}]: {n_txs} txs"
          + (f" ({skipped} heights skipped: no stored results)"
             if skipped else ""))
    return 0


def _debug_collect(rpc_url: str, home: str, out_dir: str) -> list:
    """One debug snapshot: RPC state + config + pprof-analog dumps
    (debug/util.go dumpStatus/dumpNetInfo/dumpConsensusState +
    copyConfig)."""
    import urllib.request

    os.makedirs(out_dir, exist_ok=True)
    wrote = []

    def fetch(path, name):
        try:
            with urllib.request.urlopen(rpc_url + path, timeout=5) as r:
                body = r.read()
            p = os.path.join(out_dir, name)
            with open(p, "wb") as f:
                f.write(body)
            wrote.append(name)
        except Exception as e:  # noqa: BLE001 - collect what we can
            print(f"  {name}: unavailable ({e})")

    fetch("/status", "status.json")
    fetch("/net_info", "net_info.json")
    fetch("/dump_consensus_state", "consensus_state.json")
    fetch("/debug/pprof/goroutine", "stacks.txt")
    fetch("/debug/pprof/heap", "heap.txt")
    cfg = os.path.join(home, "config", "config.toml")
    if os.path.exists(cfg):
        shutil.copy(cfg, os.path.join(out_dir, "config.toml"))
        wrote.append("config.toml")
    return wrote


def cmd_debug(args) -> int:
    """debug.go: `debug kill <pid> <out.zip>` (capture state then kill
    the node) and `debug dump <out-dir>` (periodic snapshots)."""
    import tempfile
    import zipfile

    if args.debug_sub == "kill":
        with tempfile.TemporaryDirectory() as td:
            wrote = _debug_collect(args.rpc_laddr, args.home, td)
            with zipfile.ZipFile(args.out, "w") as z:
                for name in wrote:
                    z.write(os.path.join(td, name), name)
        print(f"wrote {args.out} ({len(wrote)} files)")
        try:
            os.kill(args.pid, signal.SIGTERM)
            print(f"sent SIGTERM to {args.pid}")
        except ProcessLookupError:
            print(f"no such pid {args.pid}", file=sys.stderr)
            return 1
        return 0
    # dump mode: one snapshot per --frequency seconds until --count
    os.makedirs(args.out, exist_ok=True)
    n = 0
    while args.count <= 0 or n < args.count:
        ts = time.strftime("%Y%m%d-%H%M%S")
        out = os.path.join(args.out, ts)
        wrote = _debug_collect(args.rpc_laddr, args.home, out)
        print(f"snapshot {ts}: {len(wrote)} files")
        n += 1
        if args.count > 0 and n >= args.count:
            break
        time.sleep(args.frequency)
    return 0


def cmd_inspect(args) -> int:
    """inspect.go: read-only RPC over a stopped node's data dirs."""
    from cometbft_tpu.inspect import InspectServer

    host, port = _parse_addr(args.laddr)
    srv = InspectServer(os.path.join(args.home, "data"), host, port)
    srv.start()
    print(f"inspect rpc listening on {srv.address} (read-only)")
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop and (args.run_for <= 0
                            or time.time() < args._t0 + args.run_for):
            time.sleep(0.2)
    finally:
        srv.stop()
    return 0


def cmd_light(args) -> int:
    """light.go: run a verifying light-client RPC proxy against a
    primary full node + witnesses."""
    from cometbft_tpu.light.proxy import LightProxy

    resumable = False
    if args.home:
        # durable trust (light/store/db/db.go): a persisted store with a
        # non-expired latest block IS a trust root — no TrustOptions
        # needed on restart
        db_path = os.path.join(args.home, "light.db")
        if os.path.exists(db_path):
            from cometbft_tpu.light.store import DBStore
            from cometbft_tpu.light.verifier import header_expired
            from cometbft_tpu.types.timestamp import Timestamp

            st = DBStore(db_path)
            latest = st.latest()
            st.close()
            resumable = latest is not None and not header_expired(
                latest.signed_header.header, 14 * 24 * 3600.0,
                Timestamp.now(),
            )
    if not args.trusted_hash and not args.insecure_trust and not resumable:
        print("light: refusing to start without --trusted-hash; a "
              "lying primary could pick your trust root. Pass "
              "--insecure-trust to accept trust-on-first-use (dev only), "
              "or point --home at a light store with persisted trust.",
              file=sys.stderr)
        return 1
    if args.trusted_hash and args.trusted_height <= 0:
        print("light: --trusted-hash requires --trusted-height > 0 "
              "(the hash pins a specific header, not 'latest')",
              file=sys.stderr)
        return 1

    host, port = _parse_addr(args.laddr)
    proxy = LightProxy(
        chain_id=args.chain_id,
        primary=args.primary,
        witnesses=[w for w in args.witnesses.split(",") if w],
        trusted_height=args.trusted_height,
        trusted_hash=bytes.fromhex(args.trusted_hash)
        if args.trusted_hash else b"",
        host=host, port=port,
        db_path=(os.path.join(args.home, "light.db")
                 if args.home else None),
        # --insecure-trust also covers mid-run expiry of a persisted
        # root; without it the proxy errors instead of re-rooting TOFU
        insecure_allow_reroot=bool(args.insecure_trust),
    )
    proxy.start()
    print(f"light proxy listening on {proxy.address} "
          f"(primary {args.primary})")
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop and (args.run_for <= 0
                            or time.time() < args._t0 + args.run_for):
            time.sleep(0.2)
    finally:
        proxy.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cometbft_tpu",
        description="TPU-native CometBFT: BFT consensus with device-"
                    "batched signature verification",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize a node home directory")
    _home_arg(p)
    p.add_argument("--chain-id", default="")
    p.add_argument("--verifier", default="tpu", choices=["tpu", "cpu"])
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run a node")
    _home_arg(p)
    p.add_argument("--run-for", type=float, default=0,
                   help="exit after N seconds (0 = forever)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("testnet", help="generate a localhost testnet")
    p.add_argument("--v", type=int, default=4, help="validator count")
    p.add_argument("--output", default="./testnet")
    p.add_argument("--chain-id", default="")
    p.add_argument("--p2p-port", type=int, default=26656)
    p.add_argument("--rpc-port", type=int, default=26657)
    p.set_defaults(fn=cmd_testnet)

    p = sub.add_parser("show-node-id", help="print this node's p2p id")
    _home_arg(p)
    p.set_defaults(fn=cmd_show_node_id)

    p = sub.add_parser("unsafe-reset-all",
                       help="wipe chain data (keeps keys + config)")
    _home_arg(p)
    p.set_defaults(fn=cmd_reset)

    p = sub.add_parser("rollback", help="rewind state by one height")
    _home_arg(p)
    p.add_argument("--remove-block", action="store_true",
                   help="also delete the rolled-back block")
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser("compact", help="VACUUM the sqlite databases")
    _home_arg(p)
    p.set_defaults(fn=cmd_compact)

    p = sub.add_parser("inspect",
                       help="read-only RPC over a stopped node's data")
    _home_arg(p)
    p.add_argument("--laddr", default="tcp://127.0.0.1:26661")
    p.add_argument("--run-for", type=float, default=0)
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("reindex-event",
                       help="rebuild tx/block indexes from stored "
                            "blocks (reindex_event.go)")
    _home_arg(p)
    p.add_argument("--start-height", type=int, default=0)
    p.add_argument("--end-height", type=int, default=0)
    p.set_defaults(fn=cmd_reindex_event)

    p = sub.add_parser("debug",
                       help="capture node state for an incident "
                            "(debug.go dump/kill)")
    dsub = p.add_subparsers(dest="debug_sub", required=True)
    q = dsub.add_parser("kill", help="collect state then SIGTERM")
    q.add_argument("pid", type=int)
    q.add_argument("out", help="output zip path")
    _home_arg(q)
    q.add_argument("--rpc-laddr", default="http://127.0.0.1:26657")
    q.set_defaults(fn=cmd_debug)
    q = dsub.add_parser("dump", help="periodic state snapshots")
    q.add_argument("out", help="output directory")
    _home_arg(q)
    q.add_argument("--rpc-laddr", default="http://127.0.0.1:26657")
    q.add_argument("--frequency", type=float, default=30.0)
    q.add_argument("--count", type=int, default=0,
                   help="stop after N snapshots (0 = forever)")
    q.set_defaults(fn=cmd_debug)

    from cometbft_tpu.abci.cli import add_abci_subcommands

    add_abci_subcommands(sub)

    p = sub.add_parser("light", help="verifying light-client RPC proxy")
    p.add_argument("chain_id")
    p.add_argument("--primary", required=True,
                   help="primary full-node RPC url")
    p.add_argument("--witnesses", default="",
                   help="comma-separated witness RPC urls")
    p.add_argument("--trusted-height", type=int, default=0)
    p.add_argument("--trusted-hash", default="")
    p.add_argument("--insecure-trust", action="store_true",
                   help="allow trust-on-first-use without a pinned hash")
    p.add_argument("--home", default="",
                   help="light-client home dir; persists verified trust "
                        "to <home>/light.db (light/store/db)")
    # 8888 like the reference light proxy — NOT in the 2665x node-port
    # range (26658 is the conventional ABCI proxy_app port)
    p.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    p.add_argument("--run-for", type=float, default=0)
    p.set_defaults(fn=cmd_light)

    args = parser.parse_args(argv)
    args._t0 = time.time()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
