"""Verified-pair LRU for the light-client gateway.

A gateway serving thousands of light clients sees the same sync shapes
over and over: popular (trusted, target) header pairs — wallet fleets
pinned to the same release snapshot all jumping to the same tip. Once
one of them has paid for the skipping verification, the pair
(trusted_hash, target_hash) is a proven fact; repeat syncs over it are
pure cache hits that never touch the verify plane.

Entries carry the TARGET header's expiry on the gateway's trusting
period: a hit whose target has aged past the trusting period is
useless as a client's new trust root and must not be served — it is
dropped and counted (`expired`), and the request falls through to a
fresh verification. This is what keeps the LRU honest against
`Client.prune_expired`: the trusted store and the cache expire on the
same clock, so a pruned store can never be shadowed by a stale cache.

Thread-safe: one lock around the OrderedDict; `stats()` is scrape-safe
(one lock acquire, plain ints — /metrics samples it on every scrape).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class CacheEntry:
    """One verified (trusted, target) fact."""

    target_height: int
    target_hash: bytes
    expires_ns: int     # target header time + trusting period, in ns
    verify_steps: int   # bisection steps the original verification paid


class VerifiedLRU:
    """Bounded LRU of verified (trusted_hash, target_hash) pairs."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._od: "OrderedDict[Tuple[bytes, bytes], CacheEntry]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expired = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def get(self, key: Tuple[bytes, bytes],
            now_ns: Optional[int] = None) -> Optional[CacheEntry]:
        """Hit moves the pair to the MRU end; an entry whose target has
        expired (>= now_ns) is dropped and reported as a miss."""
        with self._lock:
            ent = self._od.get(key)
            if ent is None:
                self.misses += 1
                return None
            if now_ns is not None and now_ns >= ent.expires_ns:
                del self._od[key]
                self.expired += 1
                self.misses += 1
                return None
            self._od.move_to_end(key)
            self.hits += 1
            return ent

    def put(self, key: Tuple[bytes, bytes], entry: CacheEntry) -> None:
        with self._lock:
            self._od[key] = entry
            self._od.move_to_end(key)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)
                self.evictions += 1

    def prune_expired(self, now_ns: int) -> int:
        """Drop every entry whose target is past the trusting period
        (the cache-side half of Client.prune_expired)."""
        with self._lock:
            dead = [k for k, e in self._od.items()
                    if now_ns >= e.expires_ns]
            for k in dead:
                del self._od[k]
            self.expired += len(dead)
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._od.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": len(self._od),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "expired": self.expired,
            }
