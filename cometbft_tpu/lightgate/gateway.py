"""Light-client gateway: coalesced skipping verification as a service.

A full node that serves light-client sync to thousands of concurrent
clients faces a workload the light client alone cannot amortize: every
client independently runs skipping verification over largely the SAME
header ranges ("Practical Light Clients for Committee-Based
Blockchains" analyzes exactly this committee-scale serving problem).
The gateway turns the node into a verification service with three
compounding layers of sharing:

  1. request coalescing — N clients asking to verify the same
     (trusted_height, target_height) pair produce ONE verification
     (one leader runs it, everyone gets the result fanned out), so the
     verify plane sees one submission stream instead of N;
  2. a shared trusted store — one `light.Client` (now internally
     locked) backs every request, so a height verified for one client
     is a store hit for every later client, whatever their trust root;
  3. a verified-pair LRU — popular (trusted_hash, target_hash) pairs
     short-circuit to pure cache hits that never touch the client at
     all (expiry-checked: stale trust is never served).

Device traffic rides the verify plane's dedicated GATEWAY QoS lane:
client-serving header verifies drain after the node's own CONSENSUS
traffic and ahead of mempool BULK, and under overload they are SHED
with explicit retry-hinted `GatewayOverloaded` verdicts — never silent
drops, and never at the expense of the node's own liveness (README
"Overload behavior"; the lane-choice rationale lives in the README's
"Light-client gateway" section).

Attack handling: a client may attach the signed header IT was served
by its own primary. When that header diverges from the gateway's
verified view, the gateway drives the light client's existing
`_make_attack_evidence` path and submits the resulting
`LightClientAttackEvidence` to the node's evidence pool — one
malicious feed yields committed evidence while every other client
keeps syncing ("Polynomial Multiproofs" motivates hardening exactly
this serving edge).
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from cometbft_tpu.light.client import Client, NoSuchBlockError, Provider
from cometbft_tpu.light.verifier import (
    LightBlock,
    LightClientError,
    SignedHeader,
    header_expired,
)
from cometbft_tpu.lightgate.cache import CacheEntry, VerifiedLRU
from cometbft_tpu.types import serde
from cometbft_tpu.types.timestamp import Timestamp

_log = logging.getLogger(__name__)

DEFAULT_TRUSTING_PERIOD = 14 * 24 * 3600.0
DEFAULT_COALESCE_TIMEOUT = 30.0
DEFAULT_MAX_BATCH_HEADERS = 64


class GatewayError(Exception):
    """Gateway-side failure (bad request, no trust root, provider
    gap); RPC surfaces it as an error verdict."""


class GatewayOverloaded(GatewayError):
    """The verify plane shed this request's header verification (the
    GATEWAY lane aged it past its deadline or the lane is full). An
    explicit verdict with an honest backoff hint — every coalesced
    waiter on the shed flight receives it; nothing is dropped
    silently."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


def gateway_batch_fn(chain_id: Optional[str] = None) -> Callable:
    """batch_fn(pubs, msgs, sigs) -> (n,) bool riding the verify
    plane's GATEWAY lane when a plane runs. A PlaneOverloaded shed is
    re-raised as GatewayOverloaded (hint preserved) so it surfaces to
    the RPC client instead of silently burning the 1-core host on the
    fallback path. With no plane (or a plane stopping mid-call) rows
    verify on the inline per-row host reference path — exactly what a
    plane-less light client does, and jax-free so the gateway serves
    on host-only nodes (tier-1 smoke) without touching a kernel.
    `chain_id` keys GATEWAY rows to their tenant so a shared plane
    attributes (and quota-gates) them per hosted chain."""

    def fn(pubs, msgs, sigs):
        import numpy as np

        from cometbft_tpu import verifyplane as vp

        p = vp.global_plane()
        if p is not None:
            try:
                return p.submit_and_wait(pubs, msgs, sigs,
                                         lane=vp.LANE_GATEWAY,
                                         chain_id=chain_id)
            except vp.PlaneOverloaded as e:
                raise GatewayOverloaded(
                    str(e), retry_after_ms=e.retry_after_ms) from e
            except vp.PlaneError:
                pass
        from cometbft_tpu.verifyplane.plane import _host_verdicts

        return np.asarray(
            _host_verdicts(list(zip(pubs, msgs, sigs))), np.bool_)

    return fn


def node_light_provider(node) -> Provider:
    """Light blocks straight from the node's own stores — the gateway
    is MOUNTED on the full node, so there is no RPC hop: header +
    commit from the block store, the validator set from the state
    store's history."""
    chain_id = node.consensus.state.chain_id
    block_store = node.block_store
    state_store = node.state_store

    def fetch(height: int) -> Optional[LightBlock]:
        blk = block_store.load_block(height)
        if blk is None:
            return None
        commit = block_store.load_seen_commit(height) \
            or block_store.load_block_commit(height)
        if commit is None:
            return None
        vals = state_store.load_validators(height)
        if vals is None:
            return None
        return LightBlock(SignedHeader(blk.header, commit), vals)

    return Provider(chain_id, fetch)


class _Flight:
    """One in-progress coalesced verification: the leader resolves it,
    every follower waits on the event and reads the shared outcome."""

    __slots__ = ("ev", "result", "err")

    def __init__(self):
        self.ev = threading.Event()
        self.result = None
        self.err: Optional[BaseException] = None


class LightGateway:
    """The serving subsystem: coalescer + shared client + LRU.

    `provider` is the gateway's header source (the node's own stores
    via :func:`node_light_provider` when mounted; any LightBlock source
    in tests/benches). `root_fn` fetches the trust root the shared
    client self-roots on — for a mounted gateway that is the node's own
    earliest retained block, which the node already trusts by
    construction (it executed that chain)."""

    def __init__(self, chain_id: str, provider: Provider,
                 evidence_pool=None, *,
                 store=None,
                 cache_size: int = 4096,
                 trusting_period: float = DEFAULT_TRUSTING_PERIOD,
                 coalesce_timeout: float = DEFAULT_COALESCE_TIMEOUT,
                 max_batch_headers: int = DEFAULT_MAX_BATCH_HEADERS,
                 batch_fn: Optional[Callable] = None,
                 root_fn: Optional[Callable[[], LightBlock]] = None):
        self.chain_id = chain_id
        self.provider = provider
        self.evidence_pool = evidence_pool
        self.trusting_period = float(trusting_period)
        self.coalesce_timeout = float(coalesce_timeout)
        self.max_batch_headers = max(1, int(max_batch_headers))
        self.client = Client(
            chain_id, provider,
            trusting_period=self.trusting_period,
            batch_fn=batch_fn if batch_fn is not None
            else gateway_batch_fn(chain_id),
            store=store,
        )
        self.cache = VerifiedLRU(cache_size)
        self._root_fn = root_fn
        self._root_lock = threading.Lock()
        # coalescer: (trusted_height, target_height) -> _Flight
        self._flights: Dict[Tuple[int, int], _Flight] = {}
        self._flock = threading.Lock()
        # counters (scrape-safe under one small lock)
        self._stats_lock = threading.Lock()
        self.requests = 0
        self.verifies = 0       # leader verifications actually run
        self.coalesced = 0      # requests that rode another's flight
        self.divergences = 0    # forged-header verdicts
        self.overloaded = 0     # explicit shed verdicts handed out
        self.evidence_submitted = 0
        self._running = False
        # post-evidence hook (simnet wires gossip here; a p2p node's
        # evidence reactor broadcasts on its own pull cycle)
        self.on_attack_evidence = None

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def for_node(cls, node, **kw) -> "LightGateway":
        """Mount on a full node: provider/evidence pool/root from the
        node's own stores."""
        provider = node_light_provider(node)
        kw.setdefault("root_fn", lambda: _node_root(node, provider))
        return cls(node.consensus.state.chain_id, provider,
                   evidence_pool=node.evidence_pool, **kw)

    def start(self, register: bool = True) -> None:
        """`register=False` serves without claiming the process-global
        mount (the simnet runs one gateway per scenario inside a shared
        test process — a global registration there would leak into
        unrelated proxies' "auto" resolution)."""
        self._running = True
        if register:
            set_global_gateway(self)

    def stop(self) -> None:
        self._running = False
        clear_global_gateway(self)

    def is_running(self) -> bool:
        return self._running

    # -- trust root --------------------------------------------------------

    def ensure_root(self, now: Optional[Timestamp] = None) -> None:
        """Self-root the shared client when its store is empty or its
        newest trust has expired. A MOUNTED gateway roots on its own
        node's chain — sound by construction (the node executed every
        block it serves), unlike a light proxy trusting a remote
        primary, which is why re-rooting here needs no pinned hash."""
        now = now or Timestamp.now()
        with self._root_lock:
            latest = self.client.store.latest()
            if latest is not None and not header_expired(
                latest.signed_header.header, self.trusting_period, now
            ):
                return
            if self._root_fn is None:
                if latest is not None:
                    return  # pre-seeded store (tests): serve as-is
                raise GatewayError(
                    "gateway has no trust root: seed the store or "
                    "provide root_fn"
                )
            lb = self._root_fn()
            if lb is None:
                raise GatewayError("gateway root_fn produced no block")
            self.client.trust_light_block(lb)

    # -- serving -----------------------------------------------------------

    def verify(self, trusted_height: int, target_height: int, *,
               trusted_hash: Optional[bytes] = None,
               claimed: Optional[dict] = None,
               now: Optional[Timestamp] = None,
               with_validators: bool = False) -> dict:
        """One client sync step: verify `target_height` from the
        client's `trusted_height` through the coalesced pipeline.

        `trusted_hash` pins the client's root (a mismatch means the
        client's trust is not on our chain — an error, not a silent
        re-root). `claimed` optionally carries the signed header the
        client's own primary served it ({"header": .., "commit": ..});
        a divergent claim drives the attack-evidence path."""
        now = now or Timestamp.now()
        with self._stats_lock:
            self.requests += 1
        trusted_height = int(trusted_height)
        target_height = int(target_height)
        if target_height < trusted_height:
            raise GatewayError(
                f"target {target_height} below trusted "
                f"{trusted_height}: nothing to verify forward"
            )
        self.ensure_root(now)
        t_lb = self._fetch(trusted_height)
        t_hash = t_lb.signed_header.header.hash()
        if trusted_hash and t_hash != trusted_hash:
            raise GatewayError(
                f"trust root mismatch at height {trusted_height}: "
                f"client pins {trusted_hash.hex()[:16]}, this chain "
                f"has {t_hash.hex()[:16]}"
            )
        tgt_lb = self._fetch(target_height)
        tgt_hash = tgt_lb.signed_header.header.hash()

        claimed_sh = self._parse_claim(claimed, target_height) \
            if claimed else None
        divergent = (claimed_sh is not None and
                     claimed_sh.header.hash() != tgt_hash)

        key = (t_hash, tgt_hash)
        ent = self.cache.get(key, now_ns=now.to_ns())
        if ent is not None:
            verdict = self._verdict(tgt_lb, cached=True, coalesced=False,
                                    steps=0,
                                    with_validators=with_validators)
        else:
            # expired trust is never served from ANY layer: the LRU
            # already refused (entry expiry == this same bound), and
            # this guard closes the shared-store path too — a target
            # past the trusting period is useless as the client's new
            # root, so a stale store hit must not masquerade as a
            # fresh verification
            if header_expired(tgt_lb.signed_header.header,
                              self.trusting_period, now):
                raise GatewayError(
                    f"target header {target_height} is past the "
                    f"trusting period; cannot serve it as a trust root"
                )
            verdict = self._verify_coalesced(
                t_lb, target_height, key, now,
                with_validators=with_validators)
        if divergent:
            # our own view is verified by now — only then accuse
            return self._handle_divergence(tgt_lb, claimed_sh, verdict)
        return verdict

    def headers(self, heights: List[int],
                with_validators: bool = False) -> dict:
        """Batched header/proof serving: signed headers (+ valsets on
        request) for up to max_batch_headers heights in one response —
        the proof-batching edge ("Polynomial Multiproofs" motivation)
        so a syncing client pulls its bisection pivots in one round
        trip instead of one per height."""
        # slice BEFORE the int() copy: the cap must bound allocation,
        # not just the response
        hs = [int(h) for h in list(heights)[: self.max_batch_headers]]
        out, missing = [], []
        for h in hs:
            try:
                lb = self.provider.light_block(h)
            except NoSuchBlockError:
                missing.append(h)
                continue
            out.append(self._lb_to_j(lb, with_validators))
        return {"headers": out, "missing": missing,
                "truncated": len(heights) > len(hs)}

    # -- internals ---------------------------------------------------------

    def _fetch(self, height: int) -> LightBlock:
        try:
            return self.provider.light_block(height)
        except NoSuchBlockError:
            raise GatewayError(f"no block at height {height}")

    def _parse_claim(self, claimed: dict, target_height: int
                     ) -> SignedHeader:
        try:
            sh = SignedHeader(
                header=serde.header_from_j(claimed["header"]),
                commit=serde.commit_from_j(claimed["commit"]),
            )
            sh.validate_basic(self.chain_id)
        except LightClientError:
            raise
        except Exception as e:  # noqa: BLE001 - client input
            raise GatewayError(f"malformed claimed header: {e}")
        if sh.height != target_height:
            raise GatewayError(
                f"claimed header height {sh.height} != target "
                f"{target_height}"
            )
        return sh

    def _verify_coalesced(self, t_lb: LightBlock, target_height: int,
                          key: Tuple[bytes, bytes], now: Timestamp,
                          with_validators: bool) -> dict:
        fkey = (t_lb.height, target_height)
        with self._flock:
            fl = self._flights.get(fkey)
            leader = fl is None
            if leader:
                fl = _Flight()
                self._flights[fkey] = fl
        if leader:
            try:
                fl.result = self._verify_leader(t_lb, target_height,
                                                key, now)
            except BaseException as e:  # noqa: BLE001 - fanned out
                fl.err = e
            finally:
                with self._flock:
                    self._flights.pop(fkey, None)
                fl.ev.set()
        else:
            with self._stats_lock:
                self.coalesced += 1
            if not fl.ev.wait(self.coalesce_timeout):
                raise GatewayError(
                    f"coalesced verification of {fkey} timed out"
                )
        if fl.err is not None:
            if isinstance(fl.err, GatewayOverloaded):
                # the shed fans out too: every waiter gets the explicit
                # retry-hinted verdict, not a hang or a silent drop
                with self._stats_lock:
                    self.overloaded += 1
                raise fl.err
            if isinstance(fl.err, (GatewayError, LightClientError)):
                raise fl.err
            raise GatewayError(f"verification failed: {fl.err}")
        lb, steps = fl.result
        return self._verdict(lb, cached=False, coalesced=not leader,
                             steps=steps,
                             with_validators=with_validators)

    def _verify_leader(self, t_lb: LightBlock, target_height: int,
                       key: Tuple[bytes, bytes], now: Timestamp
                       ) -> Tuple[LightBlock, int]:
        with self._stats_lock:
            self.verifies += 1
        # seed the shared store at the client's root (idempotent: the
        # root is a block of our own chain), then let the shared client
        # verify — an already-verified target is a store hit, and the
        # device wait happens with NO gateway lock held, so concurrent
        # leaders for different pairs coalesce inside the plane
        self.client.store.save(t_lb)
        # thread-local step window: a delta over the shared
        # verifications counter would absorb concurrent leaders' steps
        self.client.begin_step_count()
        try:
            lb = self.client.verify_light_block_at_height(target_height,
                                                          now=now)
        finally:
            steps = self.client.end_step_count()
        self.cache.put(key, CacheEntry(
            target_height=target_height,
            target_hash=lb.signed_header.header.hash(),
            expires_ns=lb.signed_header.header.time.to_ns()
            + int(self.trusting_period * 1e9),
            verify_steps=steps,
        ))
        return lb, steps

    def _handle_divergence(self, verified: LightBlock,
                           claimed_sh: SignedHeader,
                           verdict: dict) -> dict:
        """The client's primary served it a header that conflicts with
        our verified view: drive the light client's attack-evidence
        construction and feed the node's evidence pool. The serving
        verdict stays useful — the honest view rides along so the
        client can re-root on it."""
        with self._stats_lock:
            self.divergences += 1
        conflicting = LightBlock(claimed_sh, verified.validator_set)
        ev = self.client._make_attack_evidence(verified, conflicting)
        added = False
        if ev is not None and self.evidence_pool is not None:
            try:
                added = self.evidence_pool.add_evidence(ev)
            except Exception:  # noqa: BLE001 - forged-but-underpowered
                # commits fail pool verification; the client still gets
                # its divergence verdict
                _log.exception(
                    "lightgate: divergent header's evidence rejected "
                    "by the pool"
                )
        if added:
            with self._stats_lock:
                self.evidence_submitted += 1
            if self.on_attack_evidence is not None:
                try:
                    self.on_attack_evidence(ev)
                except Exception:  # noqa: BLE001 - reporter hook
                    pass
        out = dict(verdict)
        out["status"] = "divergent"
        out["evidence_hash"] = ev.hash().hex() if ev is not None else None
        out["evidence_added"] = added
        return out

    def _verdict(self, lb: LightBlock, *, cached: bool, coalesced: bool,
                 steps: int, with_validators: bool) -> dict:
        return {
            "status": "verified",
            "height": lb.height,
            "target_hash": lb.signed_header.header.hash().hex(),
            "cached": cached,
            "coalesced": coalesced,
            "verify_steps": steps,
            "target": self._lb_to_j(lb, with_validators),
        }

    @staticmethod
    def _lb_to_j(lb: LightBlock, with_validators: bool) -> dict:
        out = {
            "height": lb.height,
            "signed_header": {
                "header": serde.header_to_j(lb.signed_header.header),
                "commit": serde.commit_to_j(lb.signed_header.commit),
            },
        }
        if with_validators:
            out["validators"] = [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": {"type": v.pub_key.key_type,
                                "value": v.pub_key.data.hex()},
                    "voting_power": v.voting_power,
                    "proposer_priority": v.proposer_priority,
                }
                for v in lb.validator_set.validators
            ]
        return out

    # -- maintenance / observability ---------------------------------------

    def prune_expired(self, now: Optional[Timestamp] = None) -> dict:
        """Expire trust on both layers together: the shared client's
        store AND the verified-pair cache — so an LRU hit can never
        outlive the store trust it was derived from."""
        now = now or Timestamp.now()
        dropped = self.client.prune_expired(now)
        pruned = self.cache.prune_expired(now.to_ns())
        return {"store_dropped": dropped, "cache_dropped": pruned}

    def cache_stats(self) -> dict:
        """Scrape-safe LRU counters (/metrics samples this)."""
        return self.cache.stats()

    def stats(self) -> dict:
        with self._stats_lock:
            out = {
                "running": self._running,
                "requests": self.requests,
                "verifies": self.verifies,
                "coalesced": self.coalesced,
                "divergences": self.divergences,
                "overloaded": self.overloaded,
                "evidence_submitted": self.evidence_submitted,
            }
        out["cache"] = self.cache.stats()
        out["client_verifications"] = self.client.verifications
        out["store_heights"] = len(self.client.store.heights())
        with self._flock:
            out["inflight"] = len(self._flights)
        return out


def _node_root(node, provider: Provider) -> LightBlock:
    """The mounted gateway's self-root: the node's LATEST committed
    block. The latest block is the one header guaranteed inside the
    trusting period on a live chain — rooting on the earliest retained
    block would hand ensure_root an already-expired anchor on any
    full-history chain older than the trusting period, making the
    gateway unserviceable. Heights below the root are served by the
    backwards hash-walk (cheap, signature-free), and ensure_root
    re-invokes this whenever the stored root ages out, so the anchor
    tracks the chain tip."""
    tip = node.block_store.height() or 1
    return provider.light_block(max(1, tip))


# --------------------------------------------------------------------------
# the process-global gateway (node lifecycle owns it; /metrics sampling
# and the light proxy's shared-verifier path read it)
# --------------------------------------------------------------------------

_GLOBAL: Optional[LightGateway] = None
_LAST: Optional[LightGateway] = None
_GLOBAL_LOCK = threading.Lock()


def set_global_gateway(gw: Optional[LightGateway]) -> None:
    global _GLOBAL, _LAST
    with _GLOBAL_LOCK:
        _GLOBAL = gw
        if gw is not None:
            _LAST = gw


def clear_global_gateway(gw: LightGateway) -> None:
    """Unregister `gw` if (and only if) it is the current global — a
    stopping node must not unmount another node's gateway."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is gw:
            _GLOBAL = None


def global_gateway() -> Optional[LightGateway]:
    gw = _GLOBAL
    if gw is None or not gw.is_running():
        return None
    return gw


def last_gateway() -> Optional[LightGateway]:
    """The current global gateway — or, after a stop, the LAST one
    that was global (scrape-time /metrics sampling reads counters as
    history, like the verify plane's ledger)."""
    return _GLOBAL or _LAST
