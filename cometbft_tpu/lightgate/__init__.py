"""Light-client gateway: serve thousands of concurrent light clients
from one full node — coalesced skipping verification, a shared trusted
store, a verified-pair LRU, and the existing light-client-attack
evidence pipeline at the serving edge.
"""
from cometbft_tpu.lightgate.cache import CacheEntry, VerifiedLRU
from cometbft_tpu.lightgate.gateway import (
    GatewayError,
    GatewayOverloaded,
    LightGateway,
    clear_global_gateway,
    gateway_batch_fn,
    global_gateway,
    last_gateway,
    node_light_provider,
    set_global_gateway,
)

__all__ = [
    "CacheEntry",
    "GatewayError",
    "GatewayOverloaded",
    "LightGateway",
    "VerifiedLRU",
    "clear_global_gateway",
    "gateway_batch_fn",
    "global_gateway",
    "last_gateway",
    "node_light_provider",
    "set_global_gateway",
]
