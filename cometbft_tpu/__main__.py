from cometbft_tpu.cmd.cli import main

raise SystemExit(main())
