"""Typed node configuration + TOML persistence.

Reference: config/config.go:76-1312 (Config with Base/RPC/P2P/Mempool/
Blocksync/Consensus/Storage sections, ValidateBasic per section),
config/toml.go (template render). New here per SURVEY §5: the `[crypto]`
section selecting the signature-verification backend — `verifier =
"tpu"` routes commit verification through the Pallas device kernels,
"cpu" forces the host path.
"""
from __future__ import annotations

import os

try:
    import tomllib
except ImportError:  # Python < 3.11: the vendored tomli is identical
    import tomli as tomllib
from dataclasses import dataclass, field


class ConfigError(Exception):
    pass


@dataclass
class BaseConfig:
    chain_id: str = "cometbft-tpu-chain"
    moniker: str = "node"
    proxy_app: str = "kvstore"      # in-process app by name
    blocksync: bool = True          # sync before joining consensus


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    enabled: bool = True
    # serve dial_seeds/dial_peers/unsafe_flush_mempool + /debug/pprof
    # (config.go RPCConfig.Unsafe + PprofListenAddress)
    unsafe: bool = False


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    persistent_peers: str = ""      # comma-separated id@host:port
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10


@dataclass
class MempoolConfig:
    size: int = 5000
    cache_size: int = 10000
    recheck: bool = True
    # node-side sigtx envelope verification through the verify plane's
    # BULK lane (mempool/sigtx.py); unsigned txs are unaffected
    verify_sigs: bool = True
    # CheckTx admission control (mempool/admission.py); `admission =
    # false` removes the gate entirely (every CheckTx runs)
    admission: bool = True
    max_inflight_checktx: int = 64
    # tightened in-flight bound while the device breaker is OPEN (all
    # verification is on the 1-core host then)
    breaker_inflight_checktx: int = 8
    # pool-fill watermarks with hysteresis: fast-reject broadcast_tx at
    # high, resume below low
    high_watermark: float = 0.9
    low_watermark: float = 0.7
    # backoff hint attached to OVERLOADED responses (Retry-After analog)
    retry_after_ms: float = 500.0

    def build_admission(self, fill_fn=None, breaker_open_fn=None):
        """An AdmissionController per this config, or None when the
        gate is disabled."""
        if not self.admission:
            return None
        from cometbft_tpu.mempool.admission import AdmissionController

        return AdmissionController(
            max_inflight=self.max_inflight_checktx,
            breaker_inflight=self.breaker_inflight_checktx,
            high_watermark=self.high_watermark,
            low_watermark=self.low_watermark,
            retry_after_ms=self.retry_after_ms,
            fill_fn=fill_fn, breaker_open_fn=breaker_open_fn,
        )


@dataclass
class ConsensusConfig:
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0

    def timeout_params(self):
        from cometbft_tpu.consensus.ticker import TimeoutParams

        return TimeoutParams(
            propose=self.timeout_propose,
            propose_delta=self.timeout_propose_delta,
            prevote=self.timeout_prevote,
            prevote_delta=self.timeout_prevote_delta,
            precommit=self.timeout_precommit,
            precommit_delta=self.timeout_precommit_delta,
            commit=self.timeout_commit,
        )


@dataclass
class CryptoConfig:
    """SURVEY §5: the TPU verifier seam lives in config."""

    verifier: str = "tpu"   # "tpu" | "cpu"
    device: str = ""        # informational (e.g. "v5e-1")
    # device circuit breaker (crypto/batch.py): consecutive kernel
    # faults before batches fall back to the host verify path, and how
    # often an open breaker re-probes the device
    breaker_failure_threshold: int = 2
    breaker_cooldown: float = 30.0
    # bounded valset-table caches (ops/table_cache.py): how many built
    # window tables / sharded table sets / identity-memo entries stay
    # resident across epoch rotations. Each retired epoch's table is
    # LRU-evictable dead weight; these bound it (min 2 per cache — a
    # next-epoch warm insert must never evict the LIVE table).
    # table_cache_stats()/resident_bytes ride /metrics at scrape time.
    table_cache_tables: int = 8
    table_cache_shard_tables: int = 4
    table_cache_memo_entries: int = 8

    def apply_table_cache(self) -> None:
        """Push the cache capacities into the (jax-free) table-cache
        core; safe to call before any device module loads."""
        from cometbft_tpu.ops import table_cache as tcache

        tcache.set_capacities(
            tables=self.table_cache_tables,
            shard_tables=self.table_cache_shard_tables,
            key_memo=self.table_cache_memo_entries * 2,
            valset_memo=self.table_cache_memo_entries,
        )

    def batch_fn(self):
        from cometbft_tpu.crypto import batch as cbatch

        self.apply_table_cache()
        cbatch.configure_breaker(self.breaker_failure_threshold,
                                 self.breaker_cooldown)
        if self.verifier == "cpu":
            return None
        from cometbft_tpu.types import validation

        return validation.device_batch_fn()


@dataclass
class VerifyPlaneConfig:
    """The always-on cross-caller batch-verification scheduler
    (cometbft_tpu.verifyplane). `enable = true` starts it with the node;
    every verification consumer (gossiped votes, vote extensions, light
    client, crypto.batch callers) then coalesces into shared device
    passes."""

    enable: bool = False
    window_ms: float = 1.5      # micro-batch deadline (added latency cap)
    max_batch: int = 1024       # flush early at this many pending rows
    max_queue: int = 8192       # CONSENSUS-lane backpressure bound
    # QoS BULK lane (mempool CheckTx, backfill): its own coalescing
    # window (bulk favors batch fullness over latency; 0 = 4x window_ms),
    # queue bound (0 = max_queue), and shed deadline — a BULK submission
    # older than this is answered with an explicit Overloaded verdict
    # (0 disables deadline shedding)
    bulk_window_ms: float = 0.0
    bulk_max_queue: int = 0
    bulk_deadline_ms: float = 250.0
    # QoS GATEWAY lane (light-client gateway header verifies): drains
    # after CONSENSUS, ahead of BULK; window 0 = 2x window_ms, queue
    # bound 0 = max_queue, shed deadline answered with explicit
    # retry-hinted Overloaded verdicts (0 disables deadline shedding)
    gateway_window_ms: float = 0.0
    gateway_max_queue: int = 0
    gateway_deadline_ms: float = 500.0
    # Multichip sharded dispatch: mesh = true shards eligible fused
    # flushes across the local device mesh (per-shard device-resident
    # valset tables, on-device psum tally — one cross-chip pass for
    # commits past a single chip's valset ceiling). mesh_devices caps
    # the fan-out (0 = all local devices); mesh_min_rows keeps small
    # flushes on one chip.
    mesh: bool = False
    mesh_devices: int = 0
    mesh_min_rows: int = 256
    # Pipelined mesh halves (the flight deck): pipeline_flights > 1
    # keeps up to that many flushes airborne at once on DISJOINT
    # sub-mesh halves — while one half verifies flush k, the other
    # half flies flush k+1, so no chip idles between collect and
    # dispatch. Needs a >=4-device mesh for real halves (each half
    # runs the sharded program on >=2 chips); otherwise the deck
    # degrades to the classic single-flight double buffer.
    # half_mesh_rows caps how many rows a flush may carry and still
    # ride a half (0 = budget-only: any flush whose stride count fits
    # the half's 65536-slot/device budget takes it); a flush past the
    # cap takes the full mesh and drains the deck first.
    pipeline_flights: int = 1
    # controller headroom: the self-tuning loop ([controller]) may
    # grow the deck up to this ceiling at runtime (0 = no headroom,
    # the deck stays at pipeline_flights). The staging pool and mesh
    # halves are sized for the CEILING at construction, and the
    # table_cache_shard_tables cross-check below applies to it.
    pipeline_flights_max: int = 0
    half_mesh_rows: int = 0
    # Next-epoch table warmer (verifyplane/warmer.py): when the block
    # executor applies validator updates, a background thread builds
    # the epoch e+1 valset's window tables (sharded too, when a mesh
    # is configured) while epoch e is still live — the first commit
    # after a rotation then hits a warm cache instead of paying the
    # build inline. Pure optimization: warmer faults/skips degrade to
    # the cold path and never touch live verdicts.
    warm_next_epoch: bool = True

    def build(self, metrics=None):
        """A VerifyPlane per this config, or None when disabled."""
        if not self.enable:
            return None
        from cometbft_tpu.verifyplane import VerifyPlane

        return VerifyPlane(
            window_ms=self.window_ms,
            max_batch=self.max_batch,
            max_queue=self.max_queue, metrics=metrics,
            bulk_window_ms=self.bulk_window_ms or None,
            bulk_max_queue=self.bulk_max_queue or None,
            bulk_deadline_ms=self.bulk_deadline_ms,
            gateway_window_ms=self.gateway_window_ms or None,
            gateway_max_queue=self.gateway_max_queue or None,
            gateway_deadline_ms=self.gateway_deadline_ms,
            mesh_devices=self.mesh_devices if self.mesh else None,
            mesh_min_rows=self.mesh_min_rows,
            pipeline_flights=self.pipeline_flights,
            pipeline_flights_max=self.pipeline_flights_max or None,
            half_mesh_rows=self.half_mesh_rows,
        )

    def build_warmer(self):
        """The next-epoch TableWarmer, or None when the plane or the
        warm_next_epoch knob is off."""
        if not (self.enable and self.warm_next_epoch):
            return None
        from cometbft_tpu.verifyplane.warmer import TableWarmer

        return TableWarmer()


@dataclass
class LightGateConfig:
    """The light-client gateway (cometbft_tpu.lightgate): serve
    skipping verification to many concurrent light clients with
    request coalescing, a shared trusted store, and a verified-pair
    LRU. `enable = true` mounts it on the node and exposes the
    lightgate_* JSON-RPC routes."""

    enable: bool = False
    cache_size: int = 4096          # verified (trusted, target) pairs
    trusting_period: float = 14 * 24 * 3600.0
    coalesce_timeout: float = 30.0  # follower wait on a shared flight
    max_batch_headers: int = 64     # heights per lightgate_headers call

    def build(self, node):
        """A LightGateway mounted on `node`, or None when disabled."""
        if not self.enable:
            return None
        from cometbft_tpu.lightgate import LightGateway

        return LightGateway.for_node(
            node,
            cache_size=self.cache_size,
            trusting_period=self.trusting_period,
            coalesce_timeout=self.coalesce_timeout,
            max_batch_headers=self.max_batch_headers,
        )


@dataclass
class ControllerConfig:
    """The closed-loop self-tuning control plane (libs/controller).
    Off by default: `enable = true` mounts it on the node, poked from
    the consensus-step and dispatcher-drain seams. The SLO knobs are
    the operator's declaration; everything else is loop mechanics with
    safe defaults. Every actuator the loop may move carries explicit
    clamp bounds here (validated against the static sections), so a
    runaway loop degrades to the static config, never past it."""

    enable: bool = False
    # the operator-declared SLOs: commit p99 (the height ledger's
    # apply-latency percentile) and the per-lane wait targets (these
    # double as widen ceilings — a coalescing window IS added latency
    # on its lane, so the controller never widens past half the target)
    slo_commit_p99_ms: float = 500.0
    slo_gateway_wait_ms: float = 250.0
    slo_bulk_wait_ms: float = 1000.0
    # loop mechanics: pokes per evaluation, per-actuator cooldown (in
    # evaluations), the hysteresis exit threshold (pressure enters at
    # SLO violation / fill_high, exits only below pressure_low AND
    # fill_low — the PR-7 admission-hysteresis template)
    decision_interval: int = 8
    cooldown: int = 4
    pressure_low: float = 0.5
    fill_high: float = 0.6
    fill_low: float = 0.3
    # per-move step sizes (multiplicative for windows/deadline,
    # additive for watermarks)
    window_step: float = 1.5
    watermark_step: float = 0.08
    deadline_step: float = 0.75
    util_low: float = 0.5
    # actuator clamp bounds (satellite hardening): the window maxima,
    # the deadline floor (must cover at least one flush window — a
    # deadline under the window sheds EVERYTHING), and the admission
    # floor (the high watermark may never be tightened below it)
    bulk_window_max_ms: float = 24.0
    gateway_window_max_ms: float = 12.0
    bulk_deadline_min_ms: float = 50.0
    admission_floor: float = 0.2

    def build(self):
        """A Controller per this config, or None when disabled."""
        if not self.enable:
            return None
        from cometbft_tpu.libs.controller import Controller

        return Controller(
            slo_commit_p99_ms=self.slo_commit_p99_ms,
            slo_gateway_wait_ms=self.slo_gateway_wait_ms,
            slo_bulk_wait_ms=self.slo_bulk_wait_ms,
            decision_interval=self.decision_interval,
            cooldown=self.cooldown,
            pressure_low=self.pressure_low,
            fill_high=self.fill_high,
            fill_low=self.fill_low,
            window_step=self.window_step,
            watermark_step=self.watermark_step,
            deadline_step=self.deadline_step,
            util_low=self.util_low,
        )

    def bounds(self, verify_plane: "VerifyPlaneConfig",
               mempool: "MempoolConfig") -> dict:
        """Actuator name -> (min, max) clamps, anchored at the static
        sections' effective bases (the values the loop relaxes back
        to and may never cross)."""
        bulk_base = verify_plane.bulk_window_ms \
            or 4 * verify_plane.window_ms
        gw_base = verify_plane.gateway_window_ms \
            or 2 * verify_plane.window_ms
        return {
            "bulk_window_ms": (
                bulk_base, max(bulk_base, self.bulk_window_max_ms)),
            "gateway_window_ms": (
                gw_base, max(gw_base, self.gateway_window_max_ms)),
            "bulk_deadline_ms": (
                min(self.bulk_deadline_min_ms,
                    verify_plane.bulk_deadline_ms),
                verify_plane.bulk_deadline_ms),
            "admission_high_watermark": (
                min(self.admission_floor, mempool.high_watermark),
                mempool.high_watermark),
        }


@dataclass
class TracingConfig:
    """The span/event trace plane (libs/tracing.py). Off by default
    and near-free while off. `enable = true` installs the global
    tracer (ring of `buffer` events, served by GET /dump_traces and
    the dump_traces RPC as perfetto-loadable Chrome trace JSON).
    `profile_dir` additionally arms the jax.profiler bracket around
    verify-plane device flights — device traces land in that directory
    aligned with the host spans (expensive; profiling runs only)."""

    enable: bool = False
    buffer: int = 16384     # ring capacity, in events
    profile_dir: str = ""

    def apply(self) -> None:
        """Symmetric: applying a config with tracing off DISABLES the
        global tracer and clears the profile dir — rebuilding a node
        from an edited config must not leave the previous config's
        tracer (or jax.profiler arming) running."""
        from cometbft_tpu.libs import tracing

        tracing.set_profile_dir(self.profile_dir)
        if self.enable:
            tracing.enable(capacity=self.buffer)
        else:
            tracing.disable()


@dataclass
class IncidentsConfig:
    """The incident flight recorder (libs/incidents.py). ALWAYS ON —
    there is no enable knob, only thresholds: the recorder's poke path
    costs a clock read + integer compares per consensus step, and the
    snapshot only allocates when a trigger actually fires. Knob costs:
    lowering commit_stall_s / round_limit makes drills fire earlier
    (more ring churn, same per-poke cost); cooldown_s bounds how often
    one persistent condition re-freezes."""

    commit_stall_s: float = 20.0   # no commit for this long => incident
    round_limit: int = 4           # a height reaching this round fires
    breaker_flaps: int = 4         # breaker transitions inside window_s
    shed_storm: int = 256          # sheddable-lane sheds inside window_s
    peer_starvation: int = 64      # p2p send-queue stalls inside window_s
    compile_storm: int = 3         # steady-state recompiles inside window_s
    window_s: float = 10.0         # flap/storm evaluation window
    cooldown_s: float = 30.0       # per-trigger-kind re-arm time

    def apply(self, fingerprint=None) -> None:
        from cometbft_tpu.libs import incidents

        incidents.configure(
            commit_stall_s=self.commit_stall_s,
            round_limit=self.round_limit,
            breaker_flaps=self.breaker_flaps,
            shed_storm=self.shed_storm,
            peer_starvation=self.peer_starvation,
            compile_storm=self.compile_storm,
            window_s=self.window_s,
            cooldown_s=self.cooldown_s,
        )
        if fingerprint is not None:
            incidents.recorder().set_fingerprint(fingerprint)


@dataclass
class FailpointsConfig:
    """Deterministic fault injection (libs/failpoints.py). `spec` uses
    the same syntax as the CBT_FAILPOINTS env var:
    ``name=action[:arg][*count][;...]`` with actions
    crash|raise|delay|flake. Empty = nothing armed."""

    spec: str = ""

    def apply(self) -> None:
        if self.spec:
            from cometbft_tpu.libs import failpoints

            failpoints.arm_from_spec(self.spec)


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)
    verify_plane: VerifyPlaneConfig = field(
        default_factory=VerifyPlaneConfig)
    lightgate: LightGateConfig = field(default_factory=LightGateConfig)
    controller: ControllerConfig = field(
        default_factory=ControllerConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    incidents: IncidentsConfig = field(default_factory=IncidentsConfig)
    failpoints: FailpointsConfig = field(default_factory=FailpointsConfig)

    def validate_basic(self) -> None:
        if not self.base.chain_id:
            raise ConfigError("chain_id must not be empty")
        if self.crypto.verifier not in ("tpu", "cpu"):
            raise ConfigError(
                f"[crypto] verifier must be tpu|cpu, "
                f"got {self.crypto.verifier!r}"
            )
        if self.crypto.breaker_failure_threshold < 1:
            raise ConfigError(
                "[crypto] breaker_failure_threshold must be >= 1"
            )
        if self.crypto.breaker_cooldown < 0:
            raise ConfigError("[crypto] breaker_cooldown must be >= 0")
        for name in ("table_cache_tables", "table_cache_shard_tables",
                     "table_cache_memo_entries"):
            if getattr(self.crypto, name) < 2:
                raise ConfigError(
                    f"[crypto] {name} must be >= 2 — capacity 1 would "
                    f"let a next-epoch warm insert evict the LIVE "
                    f"epoch's table mid-flush")
        if self.verify_plane.pipeline_flights_max < 0:
            raise ConfigError(
                "[verify_plane] pipeline_flights_max must be >= 0 "
                "(0 = no controller headroom)")
        if self.verify_plane.pipeline_flights_max and \
                self.verify_plane.pipeline_flights_max \
                < self.verify_plane.pipeline_flights:
            raise ConfigError(
                "[verify_plane] pipeline_flights_max must be >= "
                "pipeline_flights (it is the controller's grow "
                "ceiling, not a second starting value)")
        flights_ceiling = max(self.verify_plane.pipeline_flights,
                              self.verify_plane.pipeline_flights_max)
        if flights_ceiling > 1 \
                and self.crypto.table_cache_shard_tables < 4:
            raise ConfigError(
                "[crypto] table_cache_shard_tables must be >= 4 with "
                "[verify_plane] pipeline_flights (or the controller "
                "ceiling pipeline_flights_max) > 1 — the deck keeps "
                "a LIVE sharded table per mesh half (two), so a "
                "next-epoch warm of both halves needs headroom or it "
                "evicts a live half's table mid-flush")
        if self.verify_plane.window_ms < 0:
            raise ConfigError("[verify_plane] window_ms must be >= 0")
        if self.verify_plane.max_batch < 1:
            raise ConfigError("[verify_plane] max_batch must be >= 1")
        if self.verify_plane.max_queue < self.verify_plane.max_batch:
            raise ConfigError(
                "[verify_plane] max_queue must be >= max_batch")
        for name in ("bulk_window_ms", "bulk_max_queue",
                     "bulk_deadline_ms", "gateway_window_ms",
                     "gateway_max_queue", "gateway_deadline_ms",
                     "mesh_devices", "mesh_min_rows",
                     "half_mesh_rows"):
            if getattr(self.verify_plane, name) < 0:
                raise ConfigError(f"[verify_plane] {name} must be >= 0")
        if self.verify_plane.mesh_devices == 1:
            raise ConfigError(
                "[verify_plane] mesh_devices must be 0 (all) or >= 2 — "
                "a 1-device mesh is just the single-device path")
        if self.verify_plane.pipeline_flights < 1:
            raise ConfigError(
                "[verify_plane] pipeline_flights must be >= 1 "
                "(1 = classic single-flight dispatch)")
        lg = self.lightgate
        if lg.cache_size < 1:
            raise ConfigError("[lightgate] cache_size must be >= 1")
        if lg.trusting_period <= 0:
            raise ConfigError("[lightgate] trusting_period must be > 0")
        if lg.coalesce_timeout <= 0:
            raise ConfigError("[lightgate] coalesce_timeout must be > 0")
        if lg.max_batch_headers < 1:
            raise ConfigError("[lightgate] max_batch_headers must be >= 1")
        mp = self.mempool
        if mp.size < 1:
            raise ConfigError("[mempool] size must be >= 1")
        if mp.max_inflight_checktx < 1 or mp.breaker_inflight_checktx < 1:
            raise ConfigError(
                "[mempool] inflight CheckTx bounds must be >= 1")
        if not 0.0 < mp.high_watermark <= 1.0:
            raise ConfigError(
                "[mempool] high_watermark must be in (0, 1]")
        if not 0.0 <= mp.low_watermark <= mp.high_watermark:
            raise ConfigError(
                "[mempool] low_watermark must be in [0, high_watermark]")
        if mp.retry_after_ms < 0:
            raise ConfigError("[mempool] retry_after_ms must be >= 0")
        ctl = self.controller
        for name in ("slo_commit_p99_ms", "slo_gateway_wait_ms",
                     "slo_bulk_wait_ms"):
            if getattr(ctl, name) <= 0:
                raise ConfigError(f"[controller] {name} must be > 0")
        if ctl.decision_interval < 1:
            raise ConfigError(
                "[controller] decision_interval must be >= 1")
        if ctl.cooldown < 0:
            raise ConfigError("[controller] cooldown must be >= 0")
        if not 0.0 < ctl.pressure_low < 1.0:
            raise ConfigError(
                "[controller] pressure_low must be in (0, 1) — it is "
                "the hysteresis EXIT threshold under the SLO")
        if not 0.0 < ctl.fill_low < ctl.fill_high <= 1.0:
            raise ConfigError(
                "[controller] fill thresholds must satisfy "
                "0 < fill_low < fill_high <= 1 (enter high, exit low "
                "— equal thresholds flap at one boundary)")
        if ctl.window_step <= 1.0:
            raise ConfigError(
                "[controller] window_step must be > 1 "
                "(a multiplicative widen factor)")
        if not 0.0 < ctl.deadline_step < 1.0:
            raise ConfigError(
                "[controller] deadline_step must be in (0, 1) "
                "(a multiplicative tighten factor)")
        if ctl.watermark_step <= 0:
            raise ConfigError(
                "[controller] watermark_step must be > 0")
        if not 0.0 < ctl.util_low <= 1.0:
            raise ConfigError(
                "[controller] util_low must be in (0, 1]")
        # actuator clamp hardening: the bounds a runaway loop degrades
        # to must themselves be sane against the STATIC sections
        if ctl.bulk_deadline_min_ms < self.verify_plane.window_ms:
            raise ConfigError(
                "[controller] bulk_deadline_min_ms must be >= "
                "[verify_plane] window_ms — a shed deadline under one "
                "flush window sheds every BULK submission before a "
                "flush can reach it")
        if not 0.0 < ctl.admission_floor <= 1.0:
            raise ConfigError(
                "[controller] admission_floor must be in (0, 1]")
        if ctl.admission_floor > mp.high_watermark:
            raise ConfigError(
                "[controller] admission_floor must be <= [mempool] "
                "high_watermark (the floor is a tighten LIMIT, not a "
                "second watermark)")
        for name in ("bulk_window_max_ms", "gateway_window_max_ms"):
            if getattr(ctl, name) <= 0:
                raise ConfigError(f"[controller] {name} must be > 0")
        if self.tracing.buffer < 16:
            raise ConfigError("[tracing] buffer must be >= 16 events")
        inc = self.incidents
        for name in ("commit_stall_s", "window_s", "cooldown_s"):
            if getattr(inc, name) < 0:
                raise ConfigError(f"[incidents] {name} must be >= 0")
        if inc.round_limit < 1 or inc.breaker_flaps < 1 \
                or inc.shed_storm < 1 or inc.peer_starvation < 1 \
                or inc.compile_storm < 1:
            raise ConfigError(
                "[incidents] round_limit/breaker_flaps/shed_storm/"
                "peer_starvation/compile_storm must be >= 1")
        if self.failpoints.spec:
            # parse-validate without arming: a typo'd spec must fail at
            # config load, not silently never fire
            from cometbft_tpu.libs.failpoints import parse_spec

            try:
                parse_spec(self.failpoints.spec)
            except ValueError as e:
                raise ConfigError(f"[failpoints] bad spec: {e}")
        for name in ("timeout_propose", "timeout_prevote",
                     "timeout_precommit", "timeout_commit"):
            if getattr(self.consensus, name) < 0:
                raise ConfigError(f"[consensus] {name} must be >= 0")


def _render(cfg: Config) -> str:
    """TOML template (config/toml.go analog)."""

    def v(x):
        if isinstance(x, bool):
            return "true" if x else "false"
        if isinstance(x, (int, float)):
            return repr(x)
        return f'"{x}"'

    out = ["# cometbft-tpu node configuration\n"]
    for section, obj in [
        ("base", cfg.base), ("rpc", cfg.rpc), ("p2p", cfg.p2p),
        ("mempool", cfg.mempool), ("consensus", cfg.consensus),
        ("crypto", cfg.crypto), ("verify_plane", cfg.verify_plane),
        ("lightgate", cfg.lightgate),
        ("controller", cfg.controller),
        ("tracing", cfg.tracing), ("incidents", cfg.incidents),
        ("failpoints", cfg.failpoints),
    ]:
        out.append(f"[{section}]")
        for k, val in vars(obj).items():
            out.append(f"{k} = {v(val)}")
        out.append("")
    return "\n".join(out)


def save_config(cfg: Config, path: str) -> None:
    with open(path, "w") as f:
        f.write(_render(cfg))


def load_config(path: str) -> Config:
    with open(path, "rb") as f:
        doc = tomllib.load(f)
    cfg = Config()
    for section, obj in [
        ("base", cfg.base), ("rpc", cfg.rpc), ("p2p", cfg.p2p),
        ("mempool", cfg.mempool), ("consensus", cfg.consensus),
        ("crypto", cfg.crypto), ("verify_plane", cfg.verify_plane),
        ("lightgate", cfg.lightgate),
        ("controller", cfg.controller),
        ("tracing", cfg.tracing), ("incidents", cfg.incidents),
        ("failpoints", cfg.failpoints),
    ]:
        for k, val in doc.get(section, {}).items():
            if not hasattr(obj, k):
                raise ConfigError(f"unknown key [{section}] {k}")
            setattr(obj, k, val)
    cfg.validate_basic()
    return cfg


def default_home() -> str:
    return os.path.expanduser(os.environ.get("CBT_HOME", "~/.cometbft-tpu"))
