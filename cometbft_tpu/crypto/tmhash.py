"""tmhash: SHA-256 and the 20-byte truncated variant.

Reference: crypto/tmhash/hash.go (Sum, SumTruncated, TruncatedSize=20).
Addresses are SumTruncated(pubkey) — crypto/crypto.go:18-20.
"""
import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum(b: bytes) -> bytes:  # noqa: A001 - mirrors reference name
    return hashlib.sha256(b).digest()


def sum_truncated(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()[:TRUNCATED_SIZE]
