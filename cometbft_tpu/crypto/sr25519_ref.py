"""sr25519 (schnorrkel) host reference: keys, sign, verify.

Protocol per the schnorrkel spec (what curve25519-voi implements and the
reference wires in at crypto/sr25519/batch.go:44-77, pubkey.go:50-62,
privkey.go:17 `signingCtx = NewSigningContext([]byte{})`):

  t = merlin.Transcript("SigningContext"); t.append("", ctx)
  t.append("sign-bytes", msg)
  t.append("proto-name", "Schnorr-sig")
  t.append("sign:pk", pk_ristretto_bytes)
  t.append("sign:R", R_ristretto_bytes)
  k = reduce_mod_L(t.challenge("sign:c", 64 bytes))
  accept iff s*B - k*A == R  (ristretto equality), with the signature's
  s carrying schnorrkel's high-bit marker (sig[63] |= 0x80) and required
  canonical (< L) after clearing it.

The merlin layer underneath is validated byte-exact against the published
merlin conformance vector (tests/test_sr25519.py), so transcript
challenges here match voi's.
"""
from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

from cometbft_tpu.crypto import ed25519_ref as ed
from cometbft_tpu.crypto import ristretto_ref as rist
from cometbft_tpu.crypto.merlin import Transcript

L = ed.L

SIGNING_CTX_LABEL = b"SigningContext"
CTX = b""  # the reference uses the empty signing context (privkey.go:17)


def _signing_prefix() -> Transcript:
    t = Transcript(SIGNING_CTX_LABEL)
    t.append_message(b"", CTX)
    return t


def signing_transcript(msg: bytes) -> Transcript:
    t = _signing_prefix()
    t.append_message(b"sign-bytes", msg)
    return t


def challenge_scalar(msg: bytes, pk: bytes, r_bytes: bytes) -> int:
    t = signing_transcript(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pk)
    t.append_message(b"sign:R", r_bytes)
    return int.from_bytes(t.challenge_bytes(b"sign:c", 64), "little") % L


def expand_ed25519(seed: bytes) -> Tuple[int, bytes]:
    """MiniSecretKey -> (scalar, nonce), schnorrkel ExpandEd25519 mode:
    sha512, ed25519 clamp, then divide the scalar by the cofactor."""
    h = hashlib.sha512(seed).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3  # divide by 8
    return scalar, h[32:]


def pubkey_from_seed(seed: bytes) -> bytes:
    scalar, _ = expand_ed25519(seed)
    return rist.encode(ed.pt_mul(scalar * 8 % L, ed.BASE_EXT))


def _scalar_mul_base(k: int):
    # schnorrkel public = scalar * 8 * B? No: public = scalar * B in the
    # ristretto group; the ExpandEd25519 scalar was pre-divided by 8 so
    # that scalar*8 equals the clamped ed25519 scalar. Multiplying the
    # ristretto basepoint by `scalar` directly is the group-level value.
    return ed.pt_mul(k % L, ed.BASE_EXT)


def sign(seed: bytes, msg: bytes, rng: Optional[bytes] = None) -> bytes:
    scalar, nonce = expand_ed25519(seed)
    scalar = scalar * 8 % L  # undo the storage division for group math
    pk = rist.encode(ed.pt_mul(scalar, ed.BASE_EXT))
    # witness scalar: hash nonce + msg + randomness (spec uses a
    # transcript witness; any high-entropy r is protocol-compatible)
    rnd = rng if rng is not None else os.urandom(32)
    r = int.from_bytes(
        hashlib.sha512(nonce + msg + rnd).digest(), "little"
    ) % L
    R = rist.encode(ed.pt_mul(r, ed.BASE_EXT))
    k = challenge_scalar(msg, pk, R)
    s = (k * scalar + r) % L
    sig = bytearray(R + s.to_bytes(32, "little"))
    sig[63] |= 0x80  # schnorrkel signature marker bit
    return bytes(sig)


def verify(pk_bytes: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64 or len(pk_bytes) != 32:
        return False
    if not sig[63] & 0x80:
        return False  # missing schnorrkel marker
    s_bytes = bytearray(sig[32:])
    s_bytes[63 - 32] &= 0x7F
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return False
    A = rist.decode(pk_bytes)
    R = rist.decode(sig[:32])
    if A is None or R is None:
        return False
    k = challenge_scalar(msg, pk_bytes, sig[:32])
    # s*B - k*A == R  <=>  s*B + k*(-A) - R ~ identity coset
    sB = ed.pt_mul(s, ed.BASE_EXT)
    kA = ed.pt_mul(k, A)
    lhs = ed.pt_add(sB, ed.pt_neg(kA))
    return rist.equals(lhs, R)


def keygen(seed: Optional[bytes] = None) -> Tuple[bytes, bytes]:
    """Returns (seed/mini-secret, pubkey bytes)."""
    if seed is None:
        seed = os.urandom(32)
    return seed, pubkey_from_seed(seed)
