"""RFC 6962 merkle tree: hashing, proofs, verification.

Reference: crypto/merkle/tree.go (HashFromByteSlices, leaf/inner prefixes,
getSplitPoint), crypto/merkle/proof.go (Proof, ProofsFromByteSlices,
Verify). Every block hash, validator-set hash, and part-set root in the
framework flows through these functions, so the 0x00/0x01 domain
separation and the largest-power-of-two-less-than split rule are
consensus-critical.

Host-side sequential hashing for now. The batched-leaf-hash device kernel
(thousands of leaves per block at blocksync rates) is a planned pallas op;
the tree shape logic here stays the single source of truth for it.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def empty_hash() -> bytes:
    """Hash of an empty input set: SHA256("")."""
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(_LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(_INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (tree.go getSplitPoint)."""
    assert n > 1
    return 1 << (n.bit_length() - 1 if n & (n - 1) else n.bit_length() - 2)


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(
        hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:])
    )


@dataclass
class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go:21-27)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root(self) -> bytes:
        h = self.leaf_hash
        idx, total = self.index, self.total
        path = []
        while total > 1:
            k = _split_point(total)
            if idx < k:
                path.append((False, None))  # sibling is the right subtree
                total = k
            else:
                path.append((True, None))
                idx -= k
                total -= k
        # walk back up pairing with aunts (deepest aunt first)
        for (right_side, _), aunt in zip(reversed(path), self.aunts):
            h = inner_hash(aunt, h) if right_side else inner_hash(h, aunt)
        return h

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total <= 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        if len(self.aunts) != _depth(self.total, self.index):
            return False
        return self.compute_root() == root


def _depth(total: int, index: int) -> int:
    d = 0
    while total > 1:
        k = _split_point(total)
        if index < k:
            total = k
        else:
            index -= k
            total -= k
        d += 1
    return d


def proofs_from_byte_slices(items: Sequence[bytes]):
    """Returns (root, [Proof per item]) — proof.go ProofsFromByteSlices."""
    proofs: List[Optional[Proof]] = [None] * max(len(items), 0)

    def build(lo: int, hi: int) -> bytes:
        n = hi - lo
        if n == 0:
            return empty_hash()
        if n == 1:
            lh = leaf_hash(items[lo])
            proofs[lo] = Proof(len(items), lo, lh, [])
            return lh
        k = _split_point(n)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            proofs[i].aunts.append(right)
        for i in range(lo + k, hi):
            proofs[i].aunts.append(left)
        return inner_hash(left, right)

    root = build(0, len(items))
    # recursion unwinds deepest-join first, so aunts are already
    # deepest-first — the order computeHashFromAunts consumes
    # (proof.go innerHashes[len-1] = top-level sibling)
    return root, proofs
