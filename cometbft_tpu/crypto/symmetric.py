"""Symmetric AEAD: XChaCha20-Poly1305.

Reference: crypto/xchacha20poly1305/xchachapoly.go (24-byte-nonce AEAD
used for key-file sealing). The construction is standard (draft-irtf-
cfrg-xchacha): HChaCha20(key, nonce[:16]) derives a subkey, then
ChaCha20-Poly1305 runs with nonce (4 zero bytes || nonce[16:24]).
HChaCha20 is implemented here (the `cryptography` library ships only
the 12-byte-nonce IETF ChaCha20-Poly1305); test vectors from the CFRG
draft pin the construction.
"""
from __future__ import annotations

import os
import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )
except ImportError:  # pure-Python RFC 8439 fallback
    from cometbft_tpu.crypto.aead_ref import ChaCha20Poly1305

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_MASK = 0xFFFFFFFF


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _quarter(s, a, b, c, d) -> None:
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha §2.2)."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20: key must be 32B, nonce 16B")
    s = list(_SIGMA) + list(struct.unpack("<8L", key)) + \
        list(struct.unpack("<4L", nonce16))
    for _ in range(10):
        _quarter(s, 0, 4, 8, 12)
        _quarter(s, 1, 5, 9, 13)
        _quarter(s, 2, 6, 10, 14)
        _quarter(s, 3, 7, 11, 15)
        _quarter(s, 0, 5, 10, 15)
        _quarter(s, 1, 6, 11, 12)
        _quarter(s, 2, 7, 8, 13)
        _quarter(s, 3, 4, 9, 14)
    return struct.pack("<4L", *s[0:4]) + struct.pack("<4L", *s[12:16])


class XChaCha20Poly1305:
    """AEAD with a 24-byte nonce (xchachapoly.go New)."""

    def __init__(self, key: bytes):
        if len(key) != KEY_SIZE:
            raise ValueError("xchacha20poly1305: bad key length")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple:
        if len(nonce) != NONCE_SIZE:
            raise ValueError("xchacha20poly1305: bad nonce length")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00" * 4 + nonce[16:]

    def seal(self, nonce: bytes, plaintext: bytes,
             aad: bytes = b"") -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, plaintext, aad or None)

    def open(self, nonce: bytes, ciphertext: bytes,
             aad: bytes = b"") -> bytes:
        """Raises cryptography.exceptions.InvalidTag on tamper."""
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, ciphertext, aad or None)


def seal_with_random_nonce(key: bytes, plaintext: bytes,
                           aad: bytes = b"") -> bytes:
    """nonce || ciphertext convenience (key-file sealing shape)."""
    nonce = os.urandom(NONCE_SIZE)
    return nonce + XChaCha20Poly1305(key).seal(nonce, plaintext, aad)


def open_sealed(key: bytes, sealed: bytes, aad: bytes = b"") -> bytes:
    if len(sealed) < NONCE_SIZE + TAG_SIZE:
        raise ValueError("sealed blob too short")
    return XChaCha20Poly1305(key).open(
        sealed[:NONCE_SIZE], sealed[NONCE_SIZE:], aad
    )
