"""Pure-Python ed25519: RFC 8032 signing + ZIP-215 verification semantics.

This is the host-side reference implementation of the curve. It serves three
roles in the framework:

1. The differential-test oracle for the batched JAX/TPU verifier
   (`cometbft_tpu.ops.ed25519_kernel`).
2. The CPU fallback for sub-threshold batches, mirroring the reference's
   single-verify path (reference: crypto/ed25519/ed25519.go:181
   ``PubKey.VerifySignature``).

It is NOT the production signing path: `sign` here is variable-time Python
bigint arithmetic, fine for tests and fallback verification but leaky for a
long-term validator key. Production signing (`cometbft_tpu.crypto.keys`)
routes through the constant-time OpenSSL implementation in `cryptography`
(reference: crypto/ed25519/ed25519.go:109 ``PrivKey.Sign``).

Verification semantics are ZIP-215 (cofactored equation, non-canonical point
encodings accepted), exactly matching the verification options the reference
pins for consensus compatibility (crypto/ed25519/ed25519.go:40-42:
cofactorless=false, canonical A/R not required, S < L required). Getting
these edge cases identical on CPU and TPU is consensus-critical: a divergence
forks the chain.
"""
from __future__ import annotations

import hashlib
from typing import Tuple

# --- field / curve constants -------------------------------------------------

P = 2**255 - 19  # base field prime
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P  # edwards d
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# Base point
_By = 4 * pow(5, P - 2, P) % P


def _sqrt_ratio(u: int, v: int) -> Tuple[bool, int]:
    """Return (ok, sqrt(u/v)) in GF(p); ok=False if u/v is not a square."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    if check == u % P:
        return True, r
    if check == (P - u) % P:
        return True, r * SQRT_M1 % P
    return False, 0


_ok, _Bx = _sqrt_ratio(_By * _By - 1, D * _By * _By + 1)
assert _ok
if _Bx % 2 != 0:
    _Bx = P - _Bx
BASE = (_Bx, _By)
BASE_EXT = (_Bx, _By, 1, _Bx * _By % P)  # extended coords, the one authoritative copy

# --- extended-coordinate point arithmetic ------------------------------------
# Points are (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z.

IDENT = (0, 1, 1, 0)


def pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 % P * D % P
    Dv = 2 * Z1 * Z2 % P
    E, F, G, H = (B - A) % P, (Dv - C) % P, (Dv + C) % P, (B + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = (A + B) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - B) % P
    F = (C + G) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def pt_mul(k: int, p):
    q = IDENT
    while k > 0:
        if k & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        k >>= 1
    return q


def pt_equal(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def pt_is_small_order(p) -> bool:
    return pt_equal(pt_double(pt_double(pt_double(p))), IDENT)


# --- encoding ----------------------------------------------------------------


def pt_compress(p) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x, y = X * zi % P, Y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def pt_decompress(s: bytes, zip215: bool = True):
    """Decode a 32-byte point encoding. Returns (point|None, was_canonical).

    ZIP-215 mode accepts non-canonical y (y >= p) and the x=0/sign=1
    encodings; strict RFC 8032 mode rejects both.
    """
    if len(s) != 32:
        return None, False
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    y_canonical = y < P
    if not zip215 and not y_canonical:
        return None, False
    y %= P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    ok, x = _sqrt_ratio(u, v)
    if not ok:
        return None, y_canonical
    canonical = y_canonical and not (x == 0 and sign == 1)
    if x == 0 and sign == 1:
        if not zip215:
            return None, canonical
        # ZIP-215: -0 == 0; accept and use x = 0.
    elif (x & 1) != sign:
        x = P - x
    return (x, y, 1, x * y % P), canonical


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


# --- keys / sign / verify ----------------------------------------------------


def pubkey_from_seed(seed: bytes) -> bytes:
    assert len(seed) == 32
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    return pt_compress(pt_mul(a, BASE_EXT))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signature (deterministic nonce)."""
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    A = pubkey_from_seed(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    Rb = pt_compress(pt_mul(r, BASE_EXT))
    k = int.from_bytes(hashlib.sha512(Rb + A + msg).digest(), "little") % L
    s = (r + k * a) % L
    return Rb + int.to_bytes(s, 32, "little")


def challenge_scalar(sig_r: bytes, pubkey: bytes, msg: bytes) -> int:
    """h = SHA512(R || A || M) mod L — the per-signature challenge.

    The wire bytes of R and A are hashed as received (even when they are
    non-canonical encodings), which is why the TPU kernel takes this value
    precomputed on host rather than re-deriving it from decoded points.
    """
    return int.from_bytes(hashlib.sha512(sig_r + pubkey + msg).digest(), "little") % L


def verify(pubkey: bytes, msg: bytes, sig: bytes, zip215: bool = True) -> bool:
    """ZIP-215 (default) or strict-RFC8032 ed25519 verification.

    ZIP-215 accepts iff [8][S]B == [8]R + [8][h]A with S < L and both point
    encodings decodable (canonicity not required). Mirrors the exact option
    set the reference uses (crypto/ed25519/ed25519.go:40-42).
    """
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    A, _ = pt_decompress(pubkey, zip215=zip215)
    if A is None:
        return False
    Rb, sb = sig[:32], sig[32:]
    R, _ = pt_decompress(Rb, zip215=zip215)
    if R is None:
        return False
    s = int.from_bytes(sb, "little")
    if s >= L:
        return False  # malleability check: required in both modes
    # (strict mode: non-canonical encodings were already rejected inside
    # pt_decompress, so no further canonicity check is needed here)
    h = challenge_scalar(Rb, pubkey, msg)
    # [S]B - [h]A - R, then multiply by 8 and compare with identity.
    sB = pt_mul(s, BASE_EXT)
    hA = pt_mul(h, A)
    diff = pt_add(pt_add(sB, pt_neg(hA)), pt_neg(R))
    if zip215:
        return pt_is_small_order(diff)
    return pt_equal(diff, IDENT)
