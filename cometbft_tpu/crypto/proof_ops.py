"""ProofOps: chained verifiable proofs from a leaf to a trusted root.

Reference: crypto/merkle/proof_op.go — ProofOp {type, key, data},
ProofOperator (Run one step: value(s) -> next value), ProofRuntime
(registry of decoders + VerifyValue/VerifyAbsence walking the op chain
against a key path). An ABCI app answers `query(prove=true)` with a
ProofOps list; the light proxy verifies it against the app_hash of a
light-client-verified header, making query results trustless.

Op wire form is JSON (this framework's charter wire format); the only
built-in operator is the kv merkle op the in-tree kvstore emits
(`cbt:kv`): an RFC-6962 inclusion proof of the canonical k/v leaf
encoding in the sorted-state merkle root. Apps register their own
operator types on a ProofRuntime exactly like the reference's
DefaultProofRuntime + custom registrations.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List

from cometbft_tpu.crypto import merkle

OP_KV = "cbt:kv"


class ProofError(Exception):
    pass


@dataclass
class ProofOp:
    """One verification step (crypto/merkle/proof_op.go ProofOp)."""

    type: str
    key: bytes = b""
    data: bytes = b""  # operator-specific payload (JSON here)

    def to_j(self) -> dict:
        return {"type": self.type, "key": self.key.hex(),
                "data": self.data.hex()}

    @classmethod
    def from_j(cls, j: dict) -> "ProofOp":
        return cls(j["type"], bytes.fromhex(j.get("key", "")),
                   bytes.fromhex(j.get("data", "")))


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Canonical injective k/v leaf encoding the kv op proves."""
    return len(key).to_bytes(4, "big") + key + value


def make_kv_op(key: bytes, proof: merkle.Proof) -> ProofOp:
    data = json.dumps({
        "total": proof.total, "index": proof.index,
        "leaf_hash": proof.leaf_hash.hex(),
        "aunts": [a.hex() for a in proof.aunts],
    }).encode()
    return ProofOp(OP_KV, key, data)


def _run_kv_op(op: ProofOp, values: List[bytes]) -> List[bytes]:
    """value -> merkle root; the chain's next (usually last) input."""
    if len(values) != 1:
        raise ProofError("kv op takes exactly one value")
    try:
        j = json.loads(op.data.decode())
        proof = merkle.Proof(
            int(j["total"]), int(j["index"]),
            bytes.fromhex(j["leaf_hash"]),
            [bytes.fromhex(a) for a in j["aunts"]],
        )
    except (ValueError, KeyError, TypeError) as e:
        raise ProofError(f"malformed kv proof op: {e}")
    leaf = kv_leaf(op.key, values[0])
    if merkle.leaf_hash(leaf) != proof.leaf_hash:
        raise ProofError("kv op: value does not match proof leaf")
    root = proof.compute_root()
    if not proof.verify(root, leaf):
        raise ProofError("kv op: inconsistent proof")
    return [root]


class ProofRuntime:
    """Registry + chain walker (proof_op.go ProofRuntime)."""

    def __init__(self):
        self._ops: Dict[str, Callable[[ProofOp, List[bytes]],
                                      List[bytes]]] = {}

    def register(self, op_type: str, run) -> None:
        self._ops[op_type] = run

    def verify_value(self, ops: List[ProofOp], root: bytes,
                     key: bytes, value: bytes) -> None:
        """Walk the chain: value at key must hash up to root
        (proof_op.go VerifyValue). Raises ProofError on any mismatch."""
        if not ops:
            raise ProofError("empty proof op chain")
        if ops[0].key != key:
            raise ProofError(
                f"proof is for key {ops[0].key!r}, want {key!r}"
            )
        values = [value]
        for op in ops:
            run = self._ops.get(op.type)
            if run is None:
                raise ProofError(f"unregistered proof op {op.type!r}")
            values = run(op, values)
        if len(values) != 1 or values[0] != root:
            raise ProofError(
                "proof chain does not land on the trusted root"
            )


def default_runtime() -> ProofRuntime:
    rt = ProofRuntime()
    rt.register(OP_KV, _run_kv_op)
    return rt
