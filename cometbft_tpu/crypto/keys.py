"""Key types: ed25519 keys, signing, addresses — the crypto.PubKey /
crypto.PrivKey surface.

Reference: crypto/crypto.go:22-42 (interfaces, Address = SumTruncated),
crypto/ed25519/ed25519.go:109 (Sign), :156 (GenPrivKey), :181
(VerifySignature).

Signing uses OpenSSL (`cryptography` package) when available —
constant-time, C speed — and degrades to the pure-Python RFC 8032 path
(ed25519_ref.sign) when the package is missing: key handling must not
take the node down with it (same gate-don't-require rule as the device
backends). Single verification uses the pure-Python ZIP-215 oracle
(crypto/ed25519_ref.py), NOT OpenSSL: OpenSSL's Ed25519 verify is
cofactorless and rejects some encodings ZIP-215 accepts, and the
reference pins ZIP-215 semantics for consensus compatibility
(crypto/ed25519/ed25519.go:40-42). CPU-vs-device agreement matters more
than single-verify speed — bulk verification routes to the TPU kernel.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        NoEncryption,
        PrivateFormat,
        PublicFormat,
    )

    _HAVE_OPENSSL = True
except ImportError:  # pure-Python fallback below
    Ed25519PrivateKey = None
    _HAVE_OPENSSL = False

from cometbft_tpu.crypto import ed25519_ref
from cometbft_tpu.crypto import tmhash

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"
SR25519_KEY_TYPE = "sr25519"


@dataclass(frozen=True)
class PubKey:
    """A public key: ed25519 (32 raw bytes) or secp256k1 (33 compressed)."""

    data: bytes
    key_type: str = ED25519_KEY_TYPE

    def address(self) -> bytes:
        """20-byte address: SHA256(pubkey)[:20] for ed25519 and sr25519
        (crypto/crypto.go:18, crypto/sr25519/pubkey.go:27),
        RIPEMD160(SHA256(pubkey)) for secp256k1 (secp256k1.go:131)."""
        if self.key_type == SECP256K1_KEY_TYPE:
            from cometbft_tpu.crypto import secp256k1_ref

            return secp256k1_ref.address(self.data)
        return tmhash.sum_truncated(self.data)

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Single verify: ZIP-215 for ed25519 (crypto/ed25519/ed25519.go:181),
        low-S-enforcing ECDSA for secp256k1 (secp256k1.go:192-220),
        schnorrkel for sr25519 (crypto/sr25519/pubkey.go:50)."""
        if self.key_type == SECP256K1_KEY_TYPE:
            from cometbft_tpu.crypto import secp256k1_ref

            return secp256k1_ref.verify(self.data, msg, sig)
        if self.key_type == SR25519_KEY_TYPE:
            from cometbft_tpu.crypto import sr25519_ref

            return sr25519_ref.verify(self.data, msg, sig)
        if self.key_type != ED25519_KEY_TYPE:
            raise ValueError(f"unsupported key type {self.key_type!r}")
        return ed25519_ref.verify(self.data, msg, sig)

    def __bytes__(self) -> bytes:
        return self.data


@dataclass(frozen=True)
class PrivKey:
    """An ed25519 private key: 64 bytes = seed || pubkey (RFC 8032 / Go
    crypto/ed25519 layout, which the reference inherits)."""

    data: bytes

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "PrivKey":
        if seed is None:
            import os as _os

            seed = _os.urandom(32)
        assert len(seed) == 32
        if _HAVE_OPENSSL:
            pub = (
                Ed25519PrivateKey.from_private_bytes(seed)
                .public_key()
                .public_bytes(Encoding.Raw, PublicFormat.Raw)
            )
        else:
            pub = ed25519_ref.pubkey_from_seed(seed)
        return PrivKey(seed + pub)

    @property
    def seed(self) -> bytes:
        return self.data[:32]

    def pub_key(self) -> PubKey:
        return PubKey(self.data[32:])

    def sign(self, msg: bytes) -> bytes:
        """RFC 8032 deterministic signature (OpenSSL when present, the
        pure-Python reference path otherwise — identical output)."""
        if _HAVE_OPENSSL:
            return Ed25519PrivateKey.from_private_bytes(self.seed).sign(msg)
        return ed25519_ref.sign(self.seed, msg)


@dataclass(frozen=True)
class Secp256k1PrivKey:
    """A secp256k1 private key (32-byte big-endian scalar).

    Reference: crypto/secp256k1/secp256k1.go:24-129 (GenPrivKey, Sign
    producing 64-byte r||s with low-S normalization)."""

    data: bytes

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Secp256k1PrivKey":
        from cometbft_tpu.crypto import secp256k1_ref as sref

        if seed is None:
            import os as _os

            seed = _os.urandom(32)
        # fold the seed onto [1, N) like the reference's rejection loop
        d = int.from_bytes(seed, "big") % (sref.N - 1) + 1
        return Secp256k1PrivKey(d.to_bytes(32, "big"))

    @property
    def secret(self) -> int:
        return int.from_bytes(self.data, "big")

    def pub_key(self) -> PubKey:
        from cometbft_tpu.crypto import secp256k1_ref as sref

        return PubKey(
            sref.pubkey_from_secret(self.secret), SECP256K1_KEY_TYPE
        )

    def sign(self, msg: bytes) -> bytes:
        from cometbft_tpu.crypto import secp256k1_ref as sref

        return sref.sign(self.secret, msg)


@dataclass(frozen=True)
class Sr25519PrivKey:
    """An sr25519 (schnorrkel) private key: 32-byte mini-secret.

    Reference: crypto/sr25519/privkey.go:27-60 (MiniSecretKey expanded
    ExpandEd25519-style; signing over the empty-context merlin
    transcript)."""

    data: bytes  # mini-secret seed

    @staticmethod
    def generate(seed: Optional[bytes] = None) -> "Sr25519PrivKey":
        if seed is None:
            import os as _os

            seed = _os.urandom(32)
        assert len(seed) == 32
        return Sr25519PrivKey(seed)

    def pub_key(self) -> PubKey:
        from cometbft_tpu.crypto import sr25519_ref

        return PubKey(
            sr25519_ref.pubkey_from_seed(self.data), SR25519_KEY_TYPE
        )

    def sign(self, msg: bytes) -> bytes:
        from cometbft_tpu.crypto import sr25519_ref

        return sr25519_ref.sign(self.data, msg)
