"""STROBE-128 + merlin transcripts (scalar and numpy-batched).

The sr25519 (schnorrkel) challenge scalar is a merlin transcript
challenge; merlin is STROBE-128 instantiated on keccak-f[1600] with
protocol label "Merlin v1.0". Reference seam: crypto/sr25519/batch.go:69
(signingCtx.NewTranscriptBytes -> transcript passed to voi's verifier).

The batched classes run N transcripts in lockstep over a (N, 200)-byte
state array: every operation must be applied to all N transcripts with
the SAME label and SAME message length (data bytes differ) — exactly the
shape of a commit's signature set after grouping rows by sign-bytes
length. This makes the host-side challenge derivation for a 10k-signature
commit a handful of vectorized keccak passes instead of 10k serial
transcript walks.
"""
from __future__ import annotations

import numpy as np

from cometbft_tpu.crypto.keccak import (
    bytes_to_state,
    keccak_f1600,
    keccak_f1600_np,
    state_to_bytes,
)

R = 166  # STROBE-128 rate: 200 - 2*16 - 2
FLAG_I = 1
FLAG_A = 1 << 1
FLAG_C = 1 << 2
FLAG_T = 1 << 3
FLAG_M = 1 << 4
FLAG_K = 1 << 5


def _initial_state() -> bytes:
    st = bytearray(200)
    st[0:6] = bytes([1, R + 2, 1, 0, 1, 96])
    st[6:18] = b"STROBEv1.0.2"
    return bytes(state_to_bytes(keccak_f1600(bytes_to_state(st))))


_INIT = None


def initial_state() -> bytes:
    global _INIT
    if _INIT is None:
        _INIT = _initial_state()
    return _INIT


class Strobe128:
    """Single-stream STROBE-128 (the subset merlin uses: AD/meta-AD/PRF)."""

    def __init__(self, protocol_label: bytes):
        self.st = bytearray(initial_state())
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def _run_f(self):
        self.st[self.pos] ^= self.pos_begin
        self.st[self.pos + 1] ^= 0x04
        self.st[R + 1] ^= 0x80
        self.st = bytearray(
            state_to_bytes(keccak_f1600(bytes_to_state(self.st)))
        )
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes):
        for b in data:
            self.st[self.pos] ^= b
            self.pos += 1
            if self.pos == R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.st[self.pos])
            self.st[self.pos] = 0
            self.pos += 1
            if self.pos == R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool):
        if more:
            assert flags == self.cur_flags
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if (flags & (FLAG_C | FLAG_K)) and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool):
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool):
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)


class Transcript:
    """merlin::Transcript."""

    def __init__(self, label: bytes, _strobe: Strobe128 = None):
        if _strobe is not None:
            self.strobe = _strobe
            return
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        import copy

        s = Strobe128.__new__(Strobe128)
        s.st = bytearray(self.strobe.st)
        s.pos = self.strobe.pos
        s.pos_begin = self.strobe.pos_begin
        s.cur_flags = self.strobe.cur_flags
        return Transcript(b"", _strobe=s)

    def append_message(self, label: bytes, message: bytes):
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(len(message).to_bytes(4, "little"), True)
        self.strobe.ad(message, False)

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(n.to_bytes(4, "little"), True)
        return self.strobe.prf(n)


class BatchStrobe:
    """N STROBE-128 streams in lockstep (same ops/lengths, distinct data).

    States live in a (N, 200) uint8 array; permutations run through the
    batched keccak. Seeded either fresh or from a scalar Strobe128 whose
    prefix is shared by every stream (the cloned signing-context pattern).
    """

    def __init__(self, n: int, from_strobe: Strobe128):
        self.n = n
        self.st = np.tile(
            np.frombuffer(bytes(from_strobe.st), np.uint8), (n, 1)
        ).copy()
        self.pos = from_strobe.pos
        self.pos_begin = from_strobe.pos_begin
        self.cur_flags = from_strobe.cur_flags

    def _run_f(self):
        self.st[:, self.pos] ^= self.pos_begin
        self.st[:, self.pos + 1] ^= 0x04
        self.st[:, R + 1] ^= 0x80
        lanes = self.st.view(np.uint64).reshape(self.n, 25)
        # native batched permutation when available (~40x the numpy
        # route at 5k lanes); differential test: tests/test_native.py
        from cometbft_tpu import native

        permuted = native.batch_keccak_f1600(lanes)
        if permuted is None:
            permuted = keccak_f1600_np(lanes)
        self.st = permuted.view(np.uint8).reshape(self.n, 200).copy()
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: np.ndarray):
        """data (N, L) uint8 — same L for every stream."""
        L = data.shape[1]
        off = 0
        while off < L:
            take = min(R - self.pos, L - off)
            self.st[:, self.pos:self.pos + take] ^= data[:, off:off + take]
            self.pos += take
            off += take
            if self.pos == R:
                self._run_f()

    def _squeeze(self, n_bytes: int) -> np.ndarray:
        out = np.empty((self.n, n_bytes), np.uint8)
        off = 0
        while off < n_bytes:
            take = min(R - self.pos, n_bytes - off)
            out[:, off:off + take] = self.st[:, self.pos:self.pos + take]
            self.st[:, self.pos:self.pos + take] = 0
            self.pos += take
            off += take
            if self.pos == R:
                self._run_f()
        return out

    def _begin_op(self, flags: int, more: bool):
        if more:
            assert flags == self.cur_flags
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        hdr = np.tile(
            np.array([old_begin, flags], np.uint8), (self.n, 1)
        )
        self._absorb(hdr)
        if (flags & (FLAG_C | FLAG_K)) and self.pos != 0:
            self._run_f()

    def _bcast(self, data: bytes) -> np.ndarray:
        return np.tile(np.frombuffer(data, np.uint8), (self.n, 1))

    def meta_ad_shared(self, data: bytes, more: bool):
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(self._bcast(data))

    def ad_batch(self, data: np.ndarray, more: bool):
        self._begin_op(FLAG_A, more)
        self._absorb(np.ascontiguousarray(data, np.uint8))

    def prf(self, n_bytes: int) -> np.ndarray:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, False)
        return self._squeeze(n_bytes)


class BatchTranscript:
    """N merlin transcripts in lockstep, forked from a shared prefix."""

    def __init__(self, n: int, prefix: Transcript):
        self.strobe = BatchStrobe(n, prefix.strobe)

    def append_message_batch(self, label: bytes, messages: np.ndarray):
        """messages (N, L) uint8 — equal length across the batch."""
        self.strobe.meta_ad_shared(label, False)
        self.strobe.meta_ad_shared(
            messages.shape[1].to_bytes(4, "little"), True
        )
        self.strobe.ad_batch(messages, False)

    def append_message_shared(self, label: bytes, message: bytes):
        self.strobe.meta_ad_shared(label, False)
        self.strobe.meta_ad_shared(
            len(message).to_bytes(4, "little"), True
        )
        self.strobe.ad_batch(
            np.tile(np.frombuffer(message, np.uint8),
                    (self.strobe.n, 1)).copy()
            if message else np.empty((self.strobe.n, 0), np.uint8),
            False,
        )

    def challenge_bytes_batch(self, label: bytes, n_bytes: int) -> np.ndarray:
        self.strobe.meta_ad_shared(label, False)
        self.strobe.meta_ad_shared(n_bytes.to_bytes(4, "little"), True)
        return self.strobe.prf(n_bytes)
