"""Pure-Python AEAD + handshake primitives: the no-OpenSSL fallback.

RFC 8439 ChaCha20-Poly1305, RFC 7748 X25519, and RFC 5869 HKDF-SHA256,
API-compatible with the slices of `cryptography` that SecretConnection
and the symmetric sealer use. These exist so the p2p stack and key
handling degrade to interpreted speed — not to an ImportError — when
OpenSSL bindings are absent (the container-hardening rule: gate every
optional dependency). Correctness is pinned by RFC test vectors in
tests/test_symmetric.py / test_p2p.py interop, and the construction is
standard; throughput is good enough for handshakes and test meshes,
while production nodes should ship the `cryptography` wheel.
"""
from __future__ import annotations

import hashlib
import hmac
import struct


class InvalidTag(Exception):
    """Raised on AEAD authentication failure (cryptography.exceptions
    .InvalidTag stand-in — callers catch either via aead InvalidTag)."""


_MASK = 0xFFFFFFFF


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _quarter(s, a, b, c, d) -> None:
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 7)


_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _chacha20_block(key32: tuple, counter: int, nonce12: bytes) -> bytes:
    s = list(_SIGMA) + list(key32) + [counter] + \
        list(struct.unpack("<3L", nonce12))
    w = s[:]
    for _ in range(10):
        _quarter(w, 0, 4, 8, 12)
        _quarter(w, 1, 5, 9, 13)
        _quarter(w, 2, 6, 10, 14)
        _quarter(w, 3, 7, 11, 15)
        _quarter(w, 0, 5, 10, 15)
        _quarter(w, 1, 6, 11, 12)
        _quarter(w, 2, 7, 8, 13)
        _quarter(w, 3, 4, 9, 14)
    return struct.pack("<16L", *((a + b) & _MASK for a, b in zip(w, s)))


def _chacha20_xor(key: bytes, counter: int, nonce12: bytes,
                  data: bytes) -> bytes:
    """Keystream XOR over all blocks at once via bigint-SIMD: each of
    the 16 state words is ONE Python int holding a 32-bit lane per
    block in 64-bit slots, so every add/xor/rotate of the double-round
    is a single C-level bigint op across all blocks. The 32 bits of
    padding absorb addition carries (masked each add); rotations can't
    cross lanes because r <= 16 and the downshift lands neighbors in
    the masked padding. ~10x the per-block scalar loop on CPython —
    this is every p2p frame's cost when OpenSSL is absent."""
    n = len(data)
    if n == 0:
        return b""
    nblk = -(-n // 64)
    rep = sum(1 << (64 * i) for i in range(nblk))
    mask = 0xFFFFFFFF * rep
    key32 = struct.unpack("<8L", key)
    non3 = struct.unpack("<3L", nonce12)
    s = ([v * rep for v in _SIGMA] + [v * rep for v in key32]
         + [sum((counter + i) << (64 * i) for i in range(nblk))]
         + [v * rep for v in non3])
    w = list(s)

    def qr(a, b, c, d):
        w[a] = (w[a] + w[b]) & mask
        x = w[d] ^ w[a]
        w[d] = ((x << 16) | (x >> 16)) & mask
        w[c] = (w[c] + w[d]) & mask
        x = w[b] ^ w[c]
        w[b] = ((x << 12) | (x >> 20)) & mask
        w[a] = (w[a] + w[b]) & mask
        x = w[d] ^ w[a]
        w[d] = ((x << 8) | (x >> 24)) & mask
        w[c] = (w[c] + w[d]) & mask
        x = w[b] ^ w[c]
        w[b] = ((x << 7) | (x >> 25)) & mask

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    import numpy as _np

    # lane j of word i -> keystream word i of block j: each word-int's
    # 64-bit little-endian slots carry the value in their low 4 bytes
    words = _np.stack([
        _np.frombuffer(
            ((w[i] + s[i]) & mask).to_bytes(8 * nblk, "little"), "<u8"
        ).astype(_np.uint32)
        for i in range(16)
    ])  # (16, nblk)
    stream = words.T.astype("<u4").tobytes()[:n]
    return bytes(
        _np.bitwise_xor(
            _np.frombuffer(data, _np.uint8),
            _np.frombuffer(stream, _np.uint8),
        ).tobytes()
    )


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    """RFC 8439 §2.5 one-shot MAC."""
    r = int.from_bytes(key32[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        n = int.from_bytes(blk + b"\x01", "little")
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


class ChaCha20Poly1305:
    """RFC 8439 AEAD; drop-in for cryptography's class of the same
    name (encrypt/decrypt(nonce, data, aad))."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("chacha20poly1305: key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_block(
            struct.unpack("<8L", self._key), 0, nonce
        )[:32]
        mac_data = (aad + _pad16(aad) + ct + _pad16(ct)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(nonce) != 12:
            raise ValueError("chacha20poly1305: nonce must be 12 bytes")
        aad = aad or b""
        ct = _chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(nonce) != 12:
            raise ValueError("chacha20poly1305: nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than the tag")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        if not hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("poly1305 tag mismatch")
        return _chacha20_xor(self._key, 1, nonce, ct)


# -- X25519 (RFC 7748) -----------------------------------------------------

_P = 2 ** 255 - 19
_A24 = 121665


def _x25519_scalarmult(k: bytes, u: bytes) -> bytes:
    kn = int.from_bytes(k, "little")
    kn &= ~(7 << 0) & ((1 << 256) - 1)
    kn &= ~(128 << 248)
    kn |= 64 << 248
    un = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x1, x2, z2, x3, z3 = un, 1, 0, un, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (kn >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


class X25519PrivateKey:
    """Mirror of cryptography's class: generate/exchange/public_key."""

    def __init__(self, seed: bytes):
        self._seed = seed

    @staticmethod
    def generate() -> "X25519PrivateKey":
        import os as _os

        return X25519PrivateKey(_os.urandom(32))

    def public_key(self) -> "X25519PublicKey":
        return X25519PublicKey(
            _x25519_scalarmult(self._seed, _X25519_BASE)
        )

    def exchange(self, peer: "X25519PublicKey") -> bytes:
        out = _x25519_scalarmult(self._seed, peer._raw)
        if out == b"\x00" * 32:
            raise ValueError("x25519: low-order peer point")
        return out


class X25519PublicKey:
    def __init__(self, raw: bytes):
        self._raw = raw

    @staticmethod
    def from_public_bytes(raw: bytes) -> "X25519PublicKey":
        if len(raw) != 32:
            raise ValueError("x25519 pubkey must be 32 bytes")
        return X25519PublicKey(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw


def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes,
                length: int) -> bytes:
    """RFC 5869 extract-and-expand."""
    prk = hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]
