"""Batch-verifier dispatch: pick the device kernel by key type.

Reference: crypto/batch/batch.go:12-32 (CreateBatchVerifier switches on
key type; SupportsBatchVerifier gates the batch path). The TPU build goes
further than the reference in two ways:
- secp256k1 IS batchable here (the reference has no ECDSA batch path at
  all — batch.go:12-21 only dispatches ed25519/sr25519);
- one mixed-key commit verifies in a single call: rows are grouped by key
  type and each group goes to its kernel (the device pads per-group, so a
  mixed batch costs two kernel dispatches, not a serial fallback).

The batch_fn signature used across validation.py: fn(pubs, msgs, sigs)
with pubs a sequence of crypto.keys.PubKey; returns (n,) bool validity —
the per-signature slice the blame path needs (types/validation.go:243).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Callable, List, Sequence

import numpy as np

from cometbft_tpu.crypto.keys import (
    ED25519_KEY_TYPE,
    SECP256K1_KEY_TYPE,
    SR25519_KEY_TYPE,
    PubKey,
)

_BATCHABLE = {ED25519_KEY_TYPE, SECP256K1_KEY_TYPE, SR25519_KEY_TYPE}


def supports_batch_verifier(key_type: str) -> bool:
    """crypto/batch/batch.go:24-32 analog (plus secp256k1)."""
    return key_type in _BATCHABLE


def _accel_backend() -> bool:
    """True when an accelerator backend is actually usable. Never raises:
    a misconfigured JAX_PLATFORMS must degrade to the CPU path, not take
    signature verification down with it."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - backend init failure
        return False


def _kernel_for(key_type: str) -> Callable:
    if key_type == ED25519_KEY_TYPE:
        from cometbft_tpu.ops import ed25519_kernel

        return ed25519_kernel.verify_batch
    if key_type == SECP256K1_KEY_TYPE:
        if _accel_backend():
            from cometbft_tpu.ops import ecdsa_pallas

            return ecdsa_pallas.verify_batch
        # CPU: the XLA-composed kernel beats interpret-mode Pallas
        from cometbft_tpu.ops import ecdsa_kernel

        return ecdsa_kernel.verify_batch
    if key_type == SR25519_KEY_TYPE:
        from cometbft_tpu.ops import sr25519_kernel

        return sr25519_kernel.verify_batch
    raise ValueError(f"no batch verifier for key type {key_type!r}")


def verify_batch(
    pubs: Sequence[PubKey],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    kernels: dict = None,
) -> np.ndarray:
    """Verify a (possibly mixed-key-type) batch; (n,) bool validity.

    kernels overrides the per-type kernel (e.g. the Pallas ed25519 path)."""
    n = len(pubs)
    valid = np.zeros((n,), np.bool_)
    groups: dict = defaultdict(list)
    for i, p in enumerate(pubs):
        groups[p.key_type].append(i)
    for kt, idxs in groups.items():
        if kt not in _BATCHABLE:
            # unknown type: per-row single verify; a type with no verifier
            # at all marks the row invalid instead of raising mid-batch
            for i in idxs:
                try:
                    valid[i] = pubs[i].verify_signature(msgs[i], sigs[i])
                except ValueError:
                    valid[i] = False
            continue
        kernel = (kernels or {}).get(kt) or _kernel_for(kt)
        sub = kernel(
            [pubs[i].data for i in idxs],
            [msgs[i] for i in idxs],
            [sigs[i] for i in idxs],
        )
        valid[np.asarray(idxs)] = np.asarray(sub)
    return valid


def batch_fn() -> Callable:
    """The batch_fn validation.py consumes (CreateBatchVerifier analog)."""
    return verify_batch
