"""Batch-verifier dispatch: pick the device kernel by key type.

Reference: crypto/batch/batch.go:12-32 (CreateBatchVerifier switches on
key type; SupportsBatchVerifier gates the batch path). The TPU build goes
further than the reference in two ways:
- secp256k1 IS batchable here (the reference has no ECDSA batch path at
  all — batch.go:12-21 only dispatches ed25519/sr25519);
- one mixed-key commit verifies in a single call: rows are grouped by key
  type and each group goes to its kernel (the device pads per-group, so a
  mixed batch costs two kernel dispatches, not a serial fallback).

The batch_fn signature used across validation.py: fn(pubs, msgs, sigs)
with pubs a sequence of crypto.keys.PubKey; returns (n,) bool validity —
the per-signature slice the blame path needs (types/validation.go:243).

Degraded mode: every kernel dispatch runs under a circuit breaker. A
device fault (XLA error, tunnel loss, injected `crypto.device_dispatch`
failpoint) is caught, logged, and the batch re-verified on the host
single-signature path — a sick TPU costs throughput, never consensus
liveness. After `failure_threshold` consecutive faults the breaker
OPENS and batches go straight to the host path; every `cooldown`
seconds one batch probes the device again (half-open), and a success
closes the breaker. Measurements on committee-based consensus (arXiv:
2302.00418) put verification squarely on the liveness-critical path,
which is why the fallback is tested, not assumed.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from typing import Callable, List, Sequence

import numpy as np

from cometbft_tpu.crypto.keys import (
    ED25519_KEY_TYPE,
    SECP256K1_KEY_TYPE,
    SR25519_KEY_TYPE,
    PubKey,
)
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.libs.staging import StagingPool

_log = logging.getLogger(__name__)

_BATCHABLE = {ED25519_KEY_TYPE, SECP256K1_KEY_TYPE, SR25519_KEY_TYPE}

fp.register("crypto.device_dispatch",
            "device kernel about to run (raise = device fault; the "
            "breaker + host fallback must keep verdicts correct)")


def supports_batch_verifier(key_type: str) -> bool:
    """crypto/batch/batch.go:24-32 analog (plus secp256k1)."""
    return key_type in _BATCHABLE


class CircuitBreaker:
    """Consecutive-failure breaker with timed half-open probes.

    closed  — device healthy, every batch dispatches to it.
    open    — device sick: batches take the host path; once per
              `cooldown` seconds a single batch is let through as a
              probe (half-open). Probe success -> closed; probe
              failure -> stay open, restart the cooldown clock.
    """

    def __init__(self, failure_threshold: int = 2,
                 cooldown: float = 30.0, name: str = "device"):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown = cooldown
        self.name = name
        self._lock = threading.Lock()
        self._failures = 0
        self._open_until = 0.0
        self._is_open = False
        self.trips = 0        # times the breaker opened (ops counter)
        self.closes = 0       # open -> closed recoveries
        self.probes = 0       # half-open probes attempted

    @property
    def state(self) -> str:
        with self._lock:
            return "open" if self._is_open else "closed"

    def allow(self) -> bool:
        """True -> caller may try the device (normal or probe)."""
        with self._lock:
            if not self._is_open:
                return True
            now = time.monotonic()
            if now >= self._open_until:
                # claim the probe slot; concurrent callers keep falling
                # back until this probe resolves or the clock lapses
                self._open_until = now + self.cooldown
                self.probes += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            was_open = self._is_open
            self._failures = 0
            self._is_open = False
            if was_open:
                self.closes += 1
        if was_open:
            _log.warning("circuit breaker %s: device recovered, "
                         "breaker CLOSED", self.name)
            tracing.instant("breaker.close", cat="crypto",
                            breaker=self.name)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            now_tripping = (not self._is_open
                            and self._failures >= self.failure_threshold)
            if now_tripping:
                self._is_open = True
                self.trips += 1
            if self._is_open:
                self._open_until = time.monotonic() + self.cooldown
        if now_tripping:
            _log.error(
                "circuit breaker %s: OPEN after %d consecutive device "
                "faults; verifying on the host path, re-probing every "
                "%.1fs", self.name, self._failures, self.cooldown,
            )
            tracing.instant("breaker.open", cat="crypto",
                            breaker=self.name)

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._is_open = False
            self._open_until = 0.0


# One breaker for THE device: all kernels share the accelerator, so one
# sick tunnel should move every key type to the host path at once.
_DEVICE_BREAKER = CircuitBreaker(name="verify-device")


def device_breaker() -> CircuitBreaker:
    return _DEVICE_BREAKER


# One staging pool for THE device, mirroring the breaker: every caller
# that packs rows for upload (verify plane flushes, blocksync chunks,
# the bench) rotates through the same two persistent host buffers per
# bucket shape, so the dispatcher can pack flush k+1 while the device
# still verifies flush k (libs/staging.py). Device-resident caches
# (valset/window tables) never ride this pool — donation-safe.
_STAGING = StagingPool(slots=2)


def staging_pool() -> StagingPool:
    return _STAGING


def configure_breaker(failure_threshold: int, cooldown: float) -> None:
    """Apply [crypto] breaker knobs (config.py) to the global breaker."""
    _DEVICE_BREAKER.failure_threshold = max(1, failure_threshold)
    _DEVICE_BREAKER.cooldown = cooldown


def _accel_backend() -> bool:
    """True when an accelerator backend is actually usable. Never raises:
    a misconfigured JAX_PLATFORMS must degrade to the CPU path, not take
    signature verification down with it."""
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - backend init failure
        return False


def _kernel_for(key_type: str) -> Callable:
    if key_type == ED25519_KEY_TYPE:
        from cometbft_tpu.ops import ed25519_kernel

        return ed25519_kernel.verify_batch
    if key_type == SECP256K1_KEY_TYPE:
        if _accel_backend():
            from cometbft_tpu.ops import ecdsa_pallas

            return ecdsa_pallas.verify_batch
        # CPU: the XLA-composed kernel beats interpret-mode Pallas
        from cometbft_tpu.ops import ecdsa_kernel

        return ecdsa_kernel.verify_batch
    if key_type == SR25519_KEY_TYPE:
        from cometbft_tpu.ops import sr25519_kernel

        return sr25519_kernel.verify_batch
    raise ValueError(f"no batch verifier for key type {key_type!r}")


def _host_verify_rows(pubs, msgs, sigs, idxs, valid) -> None:
    """Host fallback: per-row single verify via the reference-path
    PubKey.verify_signature (ed25519_ref and friends). Fills `valid`
    in place for the given indices."""
    for i in idxs:
        try:
            valid[i] = pubs[i].verify_signature(msgs[i], sigs[i])
        except ValueError:
            valid[i] = False


def verify_batch(
    pubs: Sequence[PubKey],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    kernels: dict = None,
    breaker: CircuitBreaker = None,
) -> np.ndarray:
    """Verify a (possibly mixed-key-type) batch; (n,) bool validity.

    When the verify plane is running (node-lifecycle scheduler,
    cometbft_tpu.verifyplane), a default-configured call becomes a
    submit-and-wait over the plane so independent callers coalesce into
    shared device passes. Calls that pin kernels/breaker (tests, the
    plane's own dispatcher) keep the direct path.
    """
    if kernels is None and breaker is None:
        from cometbft_tpu.verifyplane import plane as _vp

        p = _vp.global_plane()
        if p is not None:
            try:
                return p.submit_and_wait(pubs, msgs, sigs)
            except _vp.PlaneError:
                pass  # plane stopped/overflowed mid-call: go direct
    return verify_batch_direct(pubs, msgs, sigs, kernels, breaker)


def verify_batch_direct(
    pubs: Sequence[PubKey],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    kernels: dict = None,
    breaker: CircuitBreaker = None,
) -> np.ndarray:
    """The direct (non-plane) batch verify: group rows by key type and
    dispatch each group to its kernel under the circuit breaker.

    kernels overrides the per-type kernel (e.g. the Pallas ed25519 path).
    breaker overrides the global device circuit breaker (tests)."""
    n = len(pubs)
    valid = np.zeros((n,), np.bool_)
    brk = breaker if breaker is not None else _DEVICE_BREAKER
    groups: dict = defaultdict(list)
    for i, p in enumerate(pubs):
        groups[p.key_type].append(i)
    for kt, idxs in groups.items():
        if kt not in _BATCHABLE:
            # unknown type: per-row single verify; a type with no verifier
            # at all marks the row invalid instead of raising mid-batch
            _host_verify_rows(pubs, msgs, sigs, idxs, valid)
            continue
        sub = None
        if brk.allow():
            kernel = (kernels or {}).get(kt) or _kernel_for(kt)
            try:
                fp.fail_point("crypto.device_dispatch")
                with tracing.span("crypto.batch.device", cat="crypto",
                                  key_type=kt, rows=len(idxs)):
                    sub = kernel(
                        [pubs[i].data for i in idxs],
                        [msgs[i] for i in idxs],
                        [sigs[i] for i in idxs],
                    )
                brk.record_success()
            except Exception:  # noqa: BLE001 - device fault, not verdict
                brk.record_failure()
                _log.exception(
                    "device batch verify failed for %s (%d sigs); "
                    "falling back to the host path", kt, len(idxs),
                )
                sub = None
        if sub is None:
            with tracing.span("crypto.batch.host", cat="crypto",
                              key_type=kt, rows=len(idxs)):
                _host_verify_rows(pubs, msgs, sigs, idxs, valid)
        else:
            valid[np.asarray(idxs)] = np.asarray(sub)
    return valid


def batch_fn() -> Callable:
    """The batch_fn validation.py consumes (CreateBatchVerifier analog)."""
    return verify_batch
