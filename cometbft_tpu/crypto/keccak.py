"""Keccak-f[1600] permutation (FIPS-202), scalar and numpy-batched.

The sr25519 verifier needs merlin transcripts, which are STROBE-128 over
keccak-f[1600] (crypto/sr25519/batch.go:69 signingCtx.NewTranscriptBytes
in the reference delegates to curve25519-voi's merlin). No keccak
primitive ships in this image (`cryptography` exposes SHA3 digests only),
so the permutation is implemented here from the spec.

Constants are DERIVED (round constants from the rc(t) LFSR, rotation
offsets from the pi-lane walk) rather than transcribed, and the
permutation is validated against hashlib.sha3_256 by running the full
sponge in tests (tests/test_sr25519.py) — an in-image ground truth.

The batched variant runs N independent states in parallel as a numpy
(N, 25) uint64 array: the merlin challenge for every signature in a
commit is computed in one vectorized pass (host-side analog of the
device batch: transcripts differ only in their absorbed bytes).
"""
from __future__ import annotations

import numpy as np

MASK64 = (1 << 64) - 1


def _derive_round_constants(n_rounds: int = 24):
    """FIPS-202 rc(t) LFSR -> per-round RC words."""

    def rc_bit(t: int) -> int:
        if t % 255 == 0:
            return 1
        r = 1
        for _ in range(t % 255):
            r <<= 1
            if r & 0x100:
                r ^= 0x171
        return r & 1

    out = []
    for ir in range(n_rounds):
        rc = 0
        for j in range(7):
            if rc_bit(j + 7 * ir):
                rc |= 1 << ((1 << j) - 1)
        out.append(rc)
    return out


def _derive_rotations():
    """Rotation offsets via the (x,y) -> (y, 2x+3y) pi walk."""
    rot = [[0] * 5 for _ in range(5)]
    x, y = 1, 0
    for t in range(24):
        rot[x][y] = ((t + 1) * (t + 2) // 2) % 64
        x, y = y, (2 * x + 3 * y) % 5
    return rot


_RC = _derive_round_constants()
_ROT = _derive_rotations()
_RC_NP = np.array(_RC, dtype=np.uint64)


def keccak_f1600(lanes):
    """One permutation of a single state: list of 25 ints (x + 5y order)."""
    a = [[lanes[x + 5 * y] for y in range(5)] for x in range(5)]

    def rol(v, n):
        return ((v << n) | (v >> (64 - n))) & MASK64

    for rnd in range(24):
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = rol(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y] & MASK64)
                                     & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= _RC[rnd]
    return [a[x][y] for y in range(5) for x in range(5)]


def keccak_f1600_np(states: np.ndarray) -> np.ndarray:
    """Batched permutation: states (N, 25) uint64 (lane order x + 5y)."""
    a = states.reshape(-1, 5, 5).transpose(0, 2, 1).copy()  # (N, x, y)

    def rol(v, n):
        if n == 0:
            return v
        return (v << np.uint64(n)) | (v >> np.uint64(64 - n))

    for rnd in range(24):
        c = a[:, :, 0] ^ a[:, :, 1] ^ a[:, :, 2] ^ a[:, :, 3] ^ a[:, :, 4]
        d = np.empty_like(c)
        for x in range(5):
            d[:, x] = c[:, (x - 1) % 5] ^ rol(c[:, (x + 1) % 5], 1)
        a ^= d[:, :, None]
        b = np.empty_like(a)
        for x in range(5):
            for y in range(5):
                b[:, y, (2 * x + 3 * y) % 5] = rol(a[:, x, y], _ROT[x][y])
        a = b ^ (~b[:, [1, 2, 3, 4, 0], :] & b[:, [2, 3, 4, 0, 1], :])
        a[:, 0, 0] ^= _RC_NP[rnd]
    return a.transpose(0, 2, 1).reshape(-1, 25)


def state_to_bytes(lanes) -> bytearray:
    out = bytearray(200)
    for i, lane in enumerate(lanes):
        out[8 * i:8 * i + 8] = int(lane).to_bytes(8, "little")
    return out


def bytes_to_state(b) -> list:
    return [int.from_bytes(bytes(b[8 * i:8 * i + 8]), "little")
            for i in range(25)]


def sha3_256(data: bytes) -> bytes:
    """SHA3-256 via this permutation — exists ONLY to differential-test
    keccak_f1600 against hashlib (tests/test_sr25519.py)."""
    rate = 136
    st = bytearray(200)
    padded = bytearray(data)
    padded.append(0x06)
    while len(padded) % rate:
        padded.append(0)
    padded[-1] |= 0x80
    for off in range(0, len(padded), rate):
        for i in range(rate):
            st[i] ^= padded[off + i]
        st = state_to_bytes(keccak_f1600(bytes_to_state(st)))
    return bytes(st[:32])
