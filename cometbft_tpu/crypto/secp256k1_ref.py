"""Pure-Python secp256k1 ECDSA oracle (host reference for the device kernel).

Semantics mirror the reference's secp256k1 component
(crypto/secp256k1/secp256k1.go):
- 33-byte compressed pubkeys (0x02/0x03 prefix),
- 64-byte r||s big-endian signatures,
- VerifySignature rejects malleable (high-S) signatures
  (secp256k1.go:204-208),
- address = RIPEMD160(SHA256(compressed pubkey)) (secp256k1.go:131).

This module is a test oracle and host-side signer; bulk verification
routes to the batched device kernel (ops/secp256k1.py). Signing uses
OpenSSL (`cryptography`) with the signature normalized to low-S when
the package is present, and a pure-Python RFC 6979 deterministic-k
path otherwise (missing optional deps must degrade, not crash).
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
from typing import Optional, Tuple

try:
    import cryptography  # noqa: F401

    _HAVE_OPENSSL = True
except ImportError:
    _HAVE_OPENSSL = False

# Curve parameters: y^2 = x^3 + 7 over F_p, group order N.
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
B = 7
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

HALF_N = N // 2


# -- affine group ops (None = point at infinity) ---------------------------


def pt_add(a: Optional[Tuple[int, int]], b: Optional[Tuple[int, int]]):
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def pt_mul(k: int, p: Optional[Tuple[int, int]]):
    acc = None
    while k:
        if k & 1:
            acc = pt_add(acc, p)
        p = pt_add(p, p)
        k >>= 1
    return acc


# -- encoding --------------------------------------------------------------


def compress(x: int, y: int) -> bytes:
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def decompress(pub: bytes) -> Optional[Tuple[int, int]]:
    """33-byte compressed key -> (x, y), or None if invalid/not on curve."""
    if len(pub) != 33 or pub[0] not in (2, 3):
        return None
    x = int.from_bytes(pub[1:], "big")
    if x >= P:
        return None
    yy = (pow(x, 3, P) + B) % P
    y = pow(yy, (P + 1) // 4, P)  # p ≡ 3 (mod 4)
    if y * y % P != yy:
        return None
    if (y & 1) != (pub[0] & 1):
        y = P - y
    return x, y


import functools


@functools.lru_cache(maxsize=16384)
def _derived_key(d: int):
    """OpenSSL EC key derivation is ~2 ms; cache it — signers reuse
    their key for every vote (mirrors the reference's cached key objects)."""
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.derive_private_key(d, ec.SECP256K1())


def pubkey_from_secret(d: int) -> bytes:
    """Compressed pubkey via OpenSSL (the pure-Python pt_mul takes ~20 ms
    per key — 10k-validator fixtures want C-speed derivation), falling
    back to pt_mul when the bindings are absent."""
    if not _HAVE_OPENSSL:
        return compress(*pt_mul(d, (GX, GY)))
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    return _derived_key(d).public_key().public_bytes(
        Encoding.X962, PublicFormat.CompressedPoint
    )


def address(pub: bytes) -> bytes:
    """RIPEMD160(SHA256(compressed pubkey)) (secp256k1.go:131)."""
    return hashlib.new("ripemd160", hashlib.sha256(pub).digest()).digest()


# -- sign / verify ---------------------------------------------------------


def _rfc6979_k(d: int, z: int) -> int:
    """RFC 6979 deterministic nonce (SHA-256) — the no-OpenSSL signing
    path must never depend on the quality of os.urandom for k."""
    x = d.to_bytes(32, "big")
    h1 = (z % N).to_bytes(32, "big")
    V = b"\x01" * 32
    K = b"\x00" * 32
    K = _hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = _hmac.new(K, V, hashlib.sha256).digest()
    K = _hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = _hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = _hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 1 <= k < N:
            return k
        K = _hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = _hmac.new(K, V, hashlib.sha256).digest()


def sign(d: int, msg: bytes) -> bytes:
    """ECDSA-SHA256, low-S normalized, 64-byte r||s big-endian."""
    if not _HAVE_OPENSSL:
        z = int.from_bytes(hashlib.sha256(msg).digest(), "big")
        while True:
            k = _rfc6979_k(d, z)
            pt = pt_mul(k, (GX, GY))
            if pt is None:
                continue
            r = pt[0] % N
            if r == 0:
                continue
            s = (z + r * d) * pow(k, N - 2, N) % N
            if s == 0:
                continue
            if s > HALF_N:
                s = N - s
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    sk = _derived_key(d)
    r, s = decode_dss_signature(sk.sign(msg, ec.ECDSA(hashes.SHA256())))
    if s > HALF_N:
        s = N - s
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """ECDSA verify with the reference's malleability rule: s > N/2 is
    rejected outright (secp256k1.go:204-208). OpenSSL-backed (C speed);
    verify_py below is the pure-Python oracle for kernel differential
    tests."""
    if len(sig) != 64:
        return False
    # only 33-byte compressed keys, like the reference (secp256k1.go:33
    # PubKeySize) — OpenSSL would happily take 65-byte uncompressed
    # points, a cross-implementation consensus divergence
    if len(pub) != 33 or pub[0] not in (2, 3):
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s <= HALF_N):
        return False
    if not _HAVE_OPENSSL:
        return verify_py(pub, msg, sig)
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        encode_dss_signature,
    )

    try:
        pk = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), pub
        )
        pk.verify(
            encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256())
        )
        return True
    except (InvalidSignature, ValueError):
        return False


def verify_py(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Pure-Python ECDSA verify (differential-test oracle)."""
    if len(sig) != 64:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s <= HALF_N):
        return False
    q = decompress(pub)
    if q is None:
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big")
    w = pow(s, N - 2, N)
    u1 = z * w % N
    u2 = r * w % N
    pt = pt_add(pt_mul(u1, (GX, GY)), pt_mul(u2, q))
    if pt is None:
        return False
    return pt[0] % N == r
