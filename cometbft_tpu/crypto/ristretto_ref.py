"""ristretto255 host reference (RFC 9496) over edwards25519 bigints.

The prime-order group sr25519/schnorrkel signs in. Decode/encode/equality
here are the oracle the device kernel (ops/sr25519_kernel.py) is
differential-tested against. Reference seam: the voi `sr25519` package
the Go code imports (crypto/sr25519/pubkey.go:50) — CometBFT itself has
no ristretto code in-tree.
"""
from __future__ import annotations

from typing import Optional, Tuple

from cometbft_tpu.crypto import ed25519_ref as ed

P = ed.P
D = ed.D
SQRT_M1 = ed.SQRT_M1


def _is_negative(x: int) -> bool:
    return (x % P) & 1 == 1


def _abs(x: int) -> int:
    x %= P
    return P - x if x & 1 else x


def sqrt_ratio_m1(u: int, v: int) -> Tuple[bool, int]:
    """RFC 9496 SQRT_RATIO_M1: (was_square, sqrt(u/v) or sqrt(i*u/v))."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u = u % P
    correct = check == u
    flipped = check == (P - u) % P
    flipped_i = check == (P - u) * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    return (correct or flipped), _abs(r)


# 1/sqrt(a - d) with a = -1 (needed by ENCODE's rotation branch)
_ok, INVSQRT_A_MINUS_D = sqrt_ratio_m1(1, (-1 - D) % P)
assert _ok


def decode(b: bytes) -> Optional[tuple]:
    """32 bytes -> extended point (X, Y, Z, T) or None if invalid.

    Enforces canonical little-endian s < p, s non-negative, and the
    square/parity conditions of RFC 9496 §4.3.1.
    """
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P or s & 1:
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2s = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2s) % P
    was_square, invsqrt = sqrt_ratio_m1(1, v * u2s % P)
    if not was_square:
        return None
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if _is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def encode(pt: tuple) -> bytes:
    """Extended point -> canonical 32-byte encoding (RFC 9496 §4.3.2)."""
    X, Y, Z, T = pt
    u1 = (Z + Y) * (Z - Y) % P
    u2 = X * Y % P
    _, invsqrt = sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * T % P
    ix = X * SQRT_M1 % P
    iy = Y * SQRT_M1 % P
    if _is_negative(T * z_inv % P):
        x, y = iy, ix
        den_inv = den1 * INVSQRT_A_MINUS_D % P
    else:
        x, y = X, Y
        den_inv = den2
    if _is_negative(x * z_inv % P):
        y = (P - y) % P
    s = _abs(den_inv * ((Z - y) % P) % P)
    return s.to_bytes(32, "little")


def equals(p: tuple, q: tuple) -> bool:
    """Coset equality: X1Y2 == Y1X2 or Y1Y2 == X1X2 (RFC 9496 §4.5)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    return (X1 * Y2 - Y1 * X2) % P == 0 or (Y1 * Y2 - X1 * X2) % P == 0


def is_identity(p: tuple) -> bool:
    return equals(p, (0, 1, 1, 0))
