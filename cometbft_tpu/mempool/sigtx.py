"""Signed-tx envelope: the node-side CheckTx signature pre-check.

The reference mempool leaves tx authentication entirely to the app,
which means every CheckTx signature verification runs wherever the app
runs — serially, per tx. This build adds an OPTIONAL envelope the node
itself understands, so tx signature checks can ride the verify plane's
BULK lane and coalesce with everything else the device verifies
(PAPERS.md "Performance of EdDSA and BLS Signatures in Committee-Based
Consensus": batch verification pays off exactly when a sustained tx
stream keeps batches full).

Wire shape (all fixed offsets, no parsing ambiguity):

    b"SGTX" | pubkey (32, ed25519) | signature (64) | payload (...)

The signature covers ``SIGN_CONTEXT + payload``. A tx without the magic
prefix is NOT an envelope and flows through CheckTx untouched — apps
that do their own auth keep working. A tx WITH the magic but malformed
(short, bad key length) is rejected by the mempool with
CODE_TYPE_BAD_SIGNATURE before the app ever sees it.

The envelope is deliberately NOT stripped: the payload's meaning stays
an app concern, and blocks commit the exact bytes gossiped (stripping
would fork the tx hash between mempool and block).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

MAGIC = b"SGTX"
PUB_LEN = 32
SIG_LEN = 64
HEADER_LEN = len(MAGIC) + PUB_LEN + SIG_LEN
# domain separation: an envelope signature must never be replayable as
# a vote / proposal / p2p handshake signature
SIGN_CONTEXT = b"cometbft-tpu/sigtx/v1\x00"


class SignedTx(NamedTuple):
    pub: bytes       # raw ed25519 key bytes
    signature: bytes
    payload: bytes


class SigTxError(ValueError):
    """Magic present but the envelope is malformed."""


def is_signed(tx: bytes) -> bool:
    return tx.startswith(MAGIC)


def sign_bytes(payload: bytes) -> bytes:
    return SIGN_CONTEXT + payload


def wrap(priv, payload: bytes) -> bytes:
    """Build an envelope over `payload` with a crypto.keys.PrivKey."""
    sig = priv.sign(sign_bytes(payload))
    return MAGIC + priv.pub_key().data + sig + payload


def parse(tx: bytes) -> Optional[SignedTx]:
    """Split an envelope; None when `tx` is not one (no magic), raises
    SigTxError when the magic is present but the frame is short."""
    if not tx.startswith(MAGIC):
        return None
    if len(tx) < HEADER_LEN:
        raise SigTxError(
            f"sigtx envelope short: {len(tx)} < {HEADER_LEN} bytes"
        )
    pub = tx[len(MAGIC):len(MAGIC) + PUB_LEN]
    sig = tx[len(MAGIC) + PUB_LEN:HEADER_LEN]
    return SignedTx(pub, sig, tx[HEADER_LEN:])
