"""CheckTx admission control: the gate in front of ABCI.

Under a sustained broadcast_tx flood the failure mode is not one big
queue — it is three queues amplifying each other: RPC handler threads
pile up in CheckTx, the mempool fills, and the verify plane's BULK lane
backs up behind them. Admission control turns that collapse into fast,
explicit rejection at the front door:

  * bounded in-flight CheckTx — at most `max_inflight` concurrent
    CheckTx calls are admitted; the rest fast-reject with a
    retry-after hint instead of stacking handler threads;
  * queue-depth watermarks with hysteresis — when the mempool is
    `high_watermark` full the broadcast_tx path flips to fast-reject
    and stays rejecting until it drains below `low_watermark`
    (no reject/accept flapping at the boundary);
  * breaker-aware host-fallback limits — when the device circuit
    breaker is OPEN every signature verify runs on the 1-core host, so
    the inflight bound tightens to `breaker_inflight`: an open breaker
    must cost throughput, never melt the host.

Every rejection carries a `retry_after_ms` hint (the Retry-After
analog), surfaced through the CheckTx log and the JSON-RPC
broadcast_tx responses, so well-behaved clients back off instead of
retry-storming.
"""
from __future__ import annotations

import threading
from typing import Callable, NamedTuple, Optional

ADMITTED = "admitted"
REJECT_INFLIGHT = "rejected_inflight"
REJECT_WATERMARK = "rejected_watermark"
REJECT_BREAKER = "rejected_breaker"


class Decision(NamedTuple):
    admitted: bool
    outcome: str          # ADMITTED / REJECT_* (metrics label)
    retry_after_ms: float  # backoff hint; 0 when admitted


class AdmissionController:
    """Shared by the mempool (local CheckTx, reactor gossip intake) and
    the RPC broadcast_tx path. Thread-safe; decisions are count-based
    (no clocks), so simnet runs of the same schedule reject the same
    txs deterministically."""

    def __init__(self,
                 max_inflight: int = 64,
                 breaker_inflight: int = 8,
                 high_watermark: float = 0.9,
                 low_watermark: float = 0.7,
                 retry_after_ms: float = 500.0,
                 fill_fn: Optional[Callable[[], float]] = None,
                 breaker_open_fn: Optional[Callable[[], bool]] = None):
        self.max_inflight = max(1, int(max_inflight))
        self.breaker_inflight = max(1, int(breaker_inflight))
        self.high_watermark = float(high_watermark)
        self.low_watermark = min(float(low_watermark),
                                 self.high_watermark)
        self.retry_after_ms = float(retry_after_ms)
        # fill_fn: current mempool fill fraction in [0, 1]
        self._fill_fn = fill_fn or (lambda: 0.0)
        # breaker_open_fn: True while the device breaker is OPEN
        self._breaker_open_fn = breaker_open_fn or (lambda: False)
        self._lock = threading.Lock()
        self._inflight = 0
        self._saturated = False  # watermark hysteresis latch
        self.counts = {ADMITTED: 0, REJECT_INFLIGHT: 0,
                       REJECT_WATERMARK: 0, REJECT_BREAKER: 0}

    # -- the gate ----------------------------------------------------------

    def try_acquire(self) -> Decision:
        """One CheckTx wants in. Pair every admitted=True with a
        release() (the mempool does this in a finally)."""
        try:
            fill = float(self._fill_fn())
        except Exception:  # noqa: BLE001 - a sick gauge must not gate
            fill = 0.0
        try:
            breaker_open = bool(self._breaker_open_fn())
        except Exception:  # noqa: BLE001
            breaker_open = False
        with self._lock:
            # watermark hysteresis: latch at high, release at low
            if self._saturated:
                if fill <= self.low_watermark:
                    self._saturated = False
            elif fill >= self.high_watermark:
                self._saturated = True
            if self._saturated:
                self.counts[REJECT_WATERMARK] += 1
                return Decision(False, REJECT_WATERMARK,
                                self.retry_after_ms)
            limit = (self.breaker_inflight if breaker_open
                     else self.max_inflight)
            if self._inflight >= limit:
                outcome = (REJECT_BREAKER if breaker_open
                           else REJECT_INFLIGHT)
                self.counts[outcome] += 1
                return Decision(False, outcome, self.retry_after_ms)
            self._inflight += 1
            self.counts[ADMITTED] += 1
            return Decision(True, ADMITTED, 0.0)

    def release(self) -> None:
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1

    # -- controller actuator (libs/controller) -----------------------------

    def set_watermarks(self, high: float, low: float) -> tuple:
        """Retune the fill watermarks live (the self-tuning control
        plane tightens them under CONSENSUS pressure and relaxes them
        back). Both move under the lock the gate reads them under, and
        the low <= high invariant is preserved unconditionally — a bad
        caller degrades to a coherent gate, never an inverted one.
        The saturation latch is left alone: the next try_acquire
        re-evaluates it against the new marks."""
        with self._lock:
            self.high_watermark = min(1.0, max(0.01, float(high)))
            self.low_watermark = min(max(0.0, float(low)),
                                     self.high_watermark)
            return (self.high_watermark, self.low_watermark)

    # -- observability -----------------------------------------------------

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def saturated(self) -> bool:
        with self._lock:
            return self._saturated

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": self._inflight,
                "saturated": self._saturated,
                "counts": dict(self.counts),
                "max_inflight": self.max_inflight,
                "breaker_inflight": self.breaker_inflight,
                "high_watermark": self.high_watermark,
                "low_watermark": self.low_watermark,
            }
