"""Mempool reactor: tx gossip with per-peer send state.

Reference: mempool/reactor.go — MempoolChannel 0x30, a per-peer send
loop over the clist that skips txs the peer already has (peers map in
mempool.txs metadata). Here each peer carries a sent/seen set: a tx is
sent to a peer at most once, never echoed to its sender, and a freshly
connected peer is brought up to date with the current pool contents.
"""
from __future__ import annotations

import threading
from typing import List

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30
MAX_SENT_TRACK = 50000  # per-peer send-state cap


class MempoolReactor(Reactor):
    def __init__(self, mempool: Mempool):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self._sent = {}  # peer -> set of tx hashes sent to / seen from
        self._lock = threading.Lock()

    def channel_descriptors(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        with self._lock:
            self._sent[peer] = set()
        # bring the newcomer up to date (reactor.go's send loop starts
        # from the clist front for a new peer)
        for tx in self.mempool.reap():
            self._send(peer, tx)

    def remove_peer(self, peer: Peer, reason: str) -> None:
        with self._lock:
            self._sent.pop(peer, None)

    # -- gossip ------------------------------------------------------------

    def _send(self, peer: Peer, tx: bytes) -> None:
        h = tmhash.sum(tx)
        with self._lock:
            sent = self._sent.get(peer)
            if sent is None or h in sent:
                return
            if len(sent) > MAX_SENT_TRACK:
                sent.clear()
            sent.add(h)
        peer.send(MEMPOOL_CHANNEL, tx)

    def broadcast_tx(self, tx: bytes) -> None:
        """Called after a local CheckTx accept (rpc broadcast_tx path)."""
        if self.switch is None:
            return
        with self.switch._peers_lock:
            peers = list(self.switch.peers.values())
        for p in peers:
            self._send(p, tx)

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        # the sender has this tx: never echo it back. get(), not
        # setdefault(): a message delivered after remove_peer must not
        # resurrect the dead peer's entry (unbounded leak under churn)
        h = tmhash.sum(msg)
        with self._lock:
            sent = self._sent.get(peer)
            if sent is not None:
                if len(sent) > MAX_SENT_TRACK:
                    sent.clear()
                sent.add(h)
        resp = self.mempool.check_tx(msg)
        # relay only txs WE accepted (first sight): the mempool cache
        # makes repeat deliveries no-ops, bounding the flood
        if resp.code == 0:
            self.broadcast_tx(msg)
