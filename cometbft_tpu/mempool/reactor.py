"""Mempool reactor: tx gossip.

Reference: mempool/reactor.go — MempoolChannel 0x30, per-peer send loops
over the clist; here a flood with a seen-cache (the mempool's own dedup
cache already bounds re-CheckTx work).
"""
from __future__ import annotations

from typing import List

from cometbft_tpu.mempool.mempool import Mempool
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30


class MempoolReactor(Reactor):
    def __init__(self, mempool: Mempool):
        super().__init__("MEMPOOL")
        self.mempool = mempool

    def channel_descriptors(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def broadcast_tx(self, tx: bytes) -> None:
        """Called after a local CheckTx accept (rpc broadcast_tx path)."""
        if self.switch is not None:
            self.switch.broadcast(MEMPOOL_CHANNEL, tx)

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        resp = self.mempool.check_tx(msg)
        # relay only txs WE accepted (first sight): the mempool cache
        # makes repeat deliveries no-ops, bounding the flood
        if resp.code == 0:
            self.switch.broadcast(MEMPOOL_CHANNEL, msg)
