"""Mempool: CheckTx-gated tx queue with cache and post-block update.

Reference: mempool/clist_mempool.go:26 (CListMempool) — CheckTx via ABCI
with an LRU dedup cache (:117), ReapMaxBytesMaxGas (:519), post-block
Update + recheck (:577). The concurrent-linked-list machinery exists for
lock-free gossip iteration; a deque + lock provides the same semantics
for the in-process build (the p2p reactor iterates snapshots).
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import List, Optional

from cometbft_tpu.abci import types as abci

CACHE_SIZE = 10000  # config.mempool.cache_size default


class Mempool:
    def __init__(self, app: abci.Application, max_txs: int = 5000):
        self.app = app
        self.max_txs = max_txs
        self._txs: deque = deque()
        self._tx_set = set()
        self._tx_gas = {}  # tx -> gas_wanted from its CheckTx
        self._cache: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def check_tx(self, tx: bytes) -> abci.ResponseCheckTx:
        """CheckTx + add (clist_mempool.go:117)."""
        with self._lock:
            if tx in self._cache:
                return abci.ResponseCheckTx(code=1, log="tx already in cache")
            self._cache[tx] = None
            if len(self._cache) > CACHE_SIZE:
                self._cache.popitem(last=False)
        resp = self.app.check_tx(abci.RequestCheckTx(tx=tx))
        if resp.code == abci.CODE_TYPE_OK:
            with self._lock:
                if tx in self._tx_set:
                    pass
                elif len(self._txs) < self.max_txs:
                    self._txs.append(tx)
                    self._tx_set.add(tx)
                    self._tx_gas[tx] = resp.gas_wanted
                else:
                    # mempool full: drop AND un-cache so a resubmission
                    # isn't silently swallowed forever (clist_mempool.go
                    # removes err'd txs from the cache); surface the drop
                    self._cache.pop(tx, None)
                    return abci.ResponseCheckTx(
                        code=1, log="mempool is full"
                    )
        else:
            # rejected txs leave the cache so they can be resubmitted once
            # valid (clist_mempool.go: KeepInvalidTxsInCache=false default)
            with self._lock:
                self._cache.pop(tx, None)
        return resp

    def reap(self, max_bytes: int = -1, max_txs: int = -1,
             max_gas: int = -1) -> List[bytes]:
        """ReapMaxBytesMaxGas (clist_mempool.go:519): byte, count, and
        gas caps; a tx whose gas_wanted would push past max_gas stops
        the reap (same early-break as the reference)."""
        out, total, gas = [], 0, 0
        with self._lock:
            for tx in self._txs:
                if max_txs >= 0 and len(out) >= max_txs:
                    break
                if max_bytes >= 0 and total + len(tx) > max_bytes:
                    break
                g = self._tx_gas.get(tx, 0)
                if max_gas >= 0 and gas + g > max_gas:
                    break
                out.append(tx)
                total += len(tx)
                gas += g
        return out

    def update(self, height: int, committed: List[bytes],
               recheck: bool = True) -> None:
        """Remove committed txs, then re-run CheckTx on the survivors
        (clist_mempool.go:577 Update + :631/:646 recheckTxs): a tx whose
        validity depended on state the block just changed must not be
        re-proposed forever."""
        with self._lock:
            committed_set = set(committed)
            survivors = [t for t in self._txs if t not in committed_set]
            self._txs = deque(survivors)
            self._tx_set -= committed_set
            for t in committed_set:
                self._tx_gas.pop(t, None)
        if not recheck or not survivors:
            return
        keep = []
        for tx in survivors:
            resp = self.app.check_tx(
                abci.RequestCheckTx(tx=tx, recheck=True)
            )
            if resp.code == abci.CODE_TYPE_OK:
                keep.append(tx)
        with self._lock:
            dropped = set(survivors) - set(keep)
            if dropped:
                self._txs = deque(
                    t for t in self._txs if t not in dropped
                )
                self._tx_set -= dropped
                for t in dropped:
                    # invalid txs leave the cache (resubmittable later)
                    self._cache.pop(t, None)
                    self._tx_gas.pop(t, None)

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._tx_set.clear()
            self._tx_gas.clear()
            self._cache.clear()
