"""Mempool: CheckTx-gated tx queue with cache, QoS verify, admission.

Reference: mempool/clist_mempool.go:26 (CListMempool) — CheckTx via ABCI
with an LRU dedup cache (:117), ReapMaxBytesMaxGas (:519), post-block
Update + recheck (:577/:631/:646). The concurrent-linked-list machinery
exists for lock-free gossip iteration; a deque + lock provides the same
semantics for the in-process build (the p2p reactor iterates snapshots).

Beyond the reference (overload resilience, ROADMAP item 5):

  * signed-tx envelopes (mempool/sigtx.py) are signature-checked by the
    NODE through the verify plane's BULK lane — CheckTx signature work
    coalesces into the same device flushes as votes instead of
    single-verifying on the host, and a shed BULK verification surfaces
    as an explicit CODE_TYPE_OVERLOADED CheckTx response with a
    retry-after hint, never a silent drop;
  * an optional AdmissionController (mempool/admission.py) gates
    CheckTx in front of ABCI: bounded in-flight calls, mempool-fill
    watermarks with hysteresis, tightened limits while the device
    breaker is open;
  * hygiene: every drop path (full queue, recheck, commit) clears the
    tx's cache/gas entries atomically — `_tx_gas` can never leak for a
    tx the pool no longer holds.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.mempool import sigtx

CACHE_SIZE = 10000  # config.mempool.cache_size default


class Mempool:
    def __init__(self, app: abci.Application, max_txs: int = 5000,
                 cache_size: int = CACHE_SIZE, recheck: bool = True,
                 verify_sigs: bool = True, admission=None, metrics=None,
                 chain_id: Optional[str] = None):
        self.app = app
        self.max_txs = max_txs
        self.cache_size = max(1, int(cache_size))
        # post-block recheck of surviving txs (clist_mempool.go:577
        # Update -> :631/:646 recheckTxs), config [mempool] recheck
        self.recheck = bool(recheck)
        # node-side sigtx envelope verification through the verify
        # plane's BULK lane (config [mempool] verify_sigs)
        self.verify_sigs = bool(verify_sigs)
        self.admission = admission  # AdmissionController or None
        self.metrics = metrics
        # tenant key for plane submissions (verifyplane/tenants.py):
        # BULK rows attribute to the hosting chain, None = "default"
        self.chain_id = chain_id
        self._txs: deque = deque()
        self._tx_set = set()
        self._tx_gas = {}  # tx -> gas_wanted from its CheckTx
        self._cache: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def fill_fraction(self) -> float:
        """Pool fullness in [0, 1] — the admission watermark input."""
        with self._lock:
            return len(self._txs) / self.max_txs if self.max_txs else 1.0

    # -- CheckTx -----------------------------------------------------------

    def _overloaded(self, reason: str, retry_after_ms: float
                    ) -> abci.ResponseCheckTx:
        if self.metrics is not None:
            self.metrics.mempool_overloaded.inc()
        return abci.ResponseCheckTx(
            code=abci.CODE_TYPE_OVERLOADED,
            log=f"{reason}; retry_after_ms={round(retry_after_ms, 1)}",
            retry_after_ms=round(retry_after_ms, 1),
        )

    def _verify_envelope(self, tx: bytes) -> Optional[abci.ResponseCheckTx]:
        """Node-side sigtx check; None = proceed to the app (valid
        envelope, or no envelope at all). Runs through the verify
        plane's BULK lane when one is running (cross-caller device
        coalescing); inline host verify otherwise. BULK sheds and
        queue-bound rejections come back as explicit OVERLOADED
        responses carrying the plane's retry hint."""
        try:
            parsed = sigtx.parse(tx)
        except sigtx.SigTxError as e:
            return abci.ResponseCheckTx(
                code=abci.CODE_TYPE_BAD_SIGNATURE, log=str(e))
        if parsed is None:
            return None  # unsigned tx: app-level auth applies
        from cometbft_tpu.crypto.keys import PubKey

        try:
            pub = PubKey(parsed.pub, "ed25519")
        except Exception as e:  # noqa: BLE001 - hostile bytes
            return abci.ResponseCheckTx(
                code=abci.CODE_TYPE_BAD_SIGNATURE,
                log=f"bad sigtx pubkey: {e}")
        msg = sigtx.sign_bytes(parsed.payload)
        from cometbft_tpu import verifyplane as vp

        plane = vp.global_plane()
        if plane is not None:
            try:
                fut = plane.submit(pub, msg, parsed.signature,
                                   lane=vp.LANE_BULK, block=False,
                                   chain_id=self.chain_id)
                ok = fut.result()[0]
            except vp.PlaneOverloaded as e:
                return self._overloaded(
                    "verify plane bulk lane overloaded",
                    e.retry_after_ms)
            except vp.PlaneError:
                # plane stopped mid-call: inline host verify
                ok = self._host_verify(pub, msg, parsed.signature)
        else:
            ok = self._host_verify(pub, msg, parsed.signature)
        if not ok:
            return abci.ResponseCheckTx(
                code=abci.CODE_TYPE_BAD_SIGNATURE,
                log="invalid sigtx signature")
        return None

    @staticmethod
    def _host_verify(pub, msg: bytes, sig: bytes) -> bool:
        try:
            return bool(pub.verify_signature(msg, sig))
        except ValueError:
            return False

    def check_tx(self, tx: bytes) -> abci.ResponseCheckTx:
        """CheckTx + add (clist_mempool.go:117), with the overload
        gates in front: cache dedup (cheapest first), admission
        control, node-side signature check, then the app."""
        with self._lock:
            if tx in self._cache:
                return abci.ResponseCheckTx(code=1,
                                            log="tx already in cache")
            self._cache[tx] = None
            if len(self._cache) > self.cache_size:
                self._cache.popitem(last=False)
        dec = None
        if self.admission is not None:
            dec = self.admission.try_acquire()
            if self.metrics is not None:
                self.metrics.mempool_admission.inc(outcome=dec.outcome)
            if not dec.admitted:
                # rejected txs leave the cache: the client was TOLD to
                # retry, so the retry must not be swallowed by dedup
                with self._lock:
                    self._cache.pop(tx, None)
                return self._overloaded(
                    f"mempool admission: {dec.outcome}",
                    dec.retry_after_ms)
        try:
            return self._check_tx_admitted(tx)
        finally:
            if dec is not None:
                self.admission.release()

    def _check_tx_admitted(self, tx: bytes) -> abci.ResponseCheckTx:
        if self.verify_sigs:
            rej = self._verify_envelope(tx)
            if rej is not None:
                # signature rejections and sheds leave the cache too —
                # a shed tx is explicitly resubmittable after backoff
                with self._lock:
                    self._cache.pop(tx, None)
                return rej
        resp = self.app.check_tx(abci.RequestCheckTx(tx=tx))
        if resp.code == abci.CODE_TYPE_OK:
            with self._lock:
                if tx in self._tx_set:
                    pass
                elif len(self._txs) < self.max_txs:
                    self._txs.append(tx)
                    self._tx_set.add(tx)
                    self._tx_gas[tx] = resp.gas_wanted
                else:
                    # mempool full: drop AND un-cache so a resubmission
                    # isn't silently swallowed forever (clist_mempool.go
                    # removes err'd txs from the cache); the gas entry
                    # must go with it (it was never added here, but a
                    # racing update() may have dropped the tx between
                    # our set check and now — pop defensively)
                    self._cache.pop(tx, None)
                    self._tx_gas.pop(tx, None)
                    return abci.ResponseCheckTx(
                        code=1, log="mempool is full"
                    )
                if self.metrics is not None:
                    self.metrics.mempool_size.set(float(len(self._txs)))
        else:
            # rejected txs leave the cache so they can be resubmitted once
            # valid (clist_mempool.go: KeepInvalidTxsInCache=false default)
            with self._lock:
                self._cache.pop(tx, None)
        return resp

    # -- reap / update -----------------------------------------------------

    def reap(self, max_bytes: int = -1, max_txs: int = -1,
             max_gas: int = -1) -> List[bytes]:
        """ReapMaxBytesMaxGas (clist_mempool.go:519): byte, count, and
        gas caps; a tx whose gas_wanted would push past max_gas stops
        the reap (same early-break as the reference)."""
        out, total, gas = [], 0, 0
        with self._lock:
            for tx in self._txs:
                if max_txs >= 0 and len(out) >= max_txs:
                    break
                if max_bytes >= 0 and total + len(tx) > max_bytes:
                    break
                g = self._tx_gas.get(tx, 0)
                if max_gas >= 0 and gas + g > max_gas:
                    break
                out.append(tx)
                total += len(tx)
                gas += g
        return out

    def update(self, height: int, committed: List[bytes],
               recheck: Optional[bool] = None) -> None:
        """Remove committed txs, then re-run CheckTx on the survivors
        (clist_mempool.go:577 Update + :631/:646 recheckTxs): a tx whose
        validity depended on state the block just changed must not be
        re-proposed forever. `recheck=None` follows the pool's config
        flag ([mempool] recheck)."""
        if recheck is None:
            recheck = self.recheck
        with self._lock:
            committed_set = set(committed)
            survivors = [t for t in self._txs if t not in committed_set]
            self._txs = deque(survivors)
            self._tx_set -= committed_set
            for t in committed_set:
                # committed txs leave gas tracking whether or not they
                # were in OUR pool (a block may commit txs we never saw
                # — popping unconditionally can't leak, not popping can)
                self._tx_gas.pop(t, None)
            if self.metrics is not None:
                self.metrics.mempool_size.set(float(len(self._txs)))
        if not recheck or not survivors:
            return
        keep = []
        for tx in survivors:
            resp = self.app.check_tx(
                abci.RequestCheckTx(tx=tx, recheck=True)
            )
            if resp.code == abci.CODE_TYPE_OK:
                keep.append(tx)
        with self._lock:
            dropped = set(survivors) - set(keep)
            if dropped:
                self._txs = deque(
                    t for t in self._txs if t not in dropped
                )
                self._tx_set -= dropped
                for t in dropped:
                    # invalid txs leave the cache (resubmittable later)
                    # AND gas tracking (the recheck-drop leak)
                    self._cache.pop(t, None)
                    self._tx_gas.pop(t, None)
            if self.metrics is not None:
                self.metrics.mempool_size.set(float(len(self._txs)))

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._tx_set.clear()
            self._tx_gas.clear()
            self._cache.clear()
            if self.metrics is not None:
                self.metrics.mempool_size.set(0.0)

    def gas_entries(self) -> int:
        """Test/ops hook: _tx_gas must track the pool exactly — any
        excess is a leak."""
        with self._lock:
            return len(self._tx_gas)
