"""Benchmark helpers that need product internals (kept out of bench.py so
the repo-root script stays a thin driver).

Currently: the BASELINE config #3 mixed ed25519/sr25519 fused-tally
commit bench — the shape crypto/batch/batch.go cannot express at all
(one BatchVerifier per key type, no cross-type tally)."""
from __future__ import annotations

import time

import numpy as np

CHAIN_ID_DEFAULT = "bench-chain"


def _now_ms():
    return time.perf_counter() * 1000


def tally_int(tally_limbs) -> int:
    """(TALLY_LIMBS,) 13-bit limbs -> Python int."""
    v = 0
    for i, limb in enumerate(np.asarray(tally_limbs).tolist()):
        v += int(limb) << (13 * i)
    return v


def mixed_commit_bench(chain_id: str, n_vals: int = 10_000,
                       steady_k: int = 8):
    """10k-validator commit, half ed25519 / half sr25519, verified as two
    fused device passes (one per key-type group, each verify+tally fused)
    with the cross-group power reduction on host (a 6-limb add)."""
    import jax

    from cometbft_tpu.crypto.keys import PrivKey, Sr25519PrivKey
    from cometbft_tpu.ops import ed25519_kernel as ek
    from cometbft_tpu.ops import ed25519_pallas as kp
    from cometbft_tpu.ops import sr25519_kernel as srk
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block_id import BlockID, PartSetHeader
    from cometbft_tpu.types.commit import (
        BLOCK_ID_FLAG_COMMIT,
        Commit,
        CommitSig,
    )
    from cometbft_tpu.types.timestamp import Timestamp
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    half = n_vals // 2
    privs = [
        PrivKey.generate((100 + i).to_bytes(4, "big") + b"\x44" * 28)
        for i in range(half)
    ] + [
        Sr25519PrivKey.generate((7 + i).to_bytes(4, "big") + b"\x55" * 28)
        for i in range(n_vals - half)
    ]
    power = 1000
    vs = ValidatorSet([Validator(p.pub_key(), power) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = BlockID(b"\xee" * 32, PartSetHeader(2, b"\xcd" * 32))
    height = 777
    t_gen = _now_ms()
    sigs = []
    msgs = []
    for idx, v in enumerate(vs.validators):
        ts = Timestamp(1_700_000_000 + idx, 0)
        sb = canonical.canonical_vote_bytes(
            chain_id, canonical.PRECOMMIT_TYPE, height, 0, bid, ts
        )
        msgs.append(sb)
        sigs.append(
            CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                      by_addr[v.address].sign(sb))
        )
    commit = Commit(height, 0, bid, sigs)
    gen_s = (_now_ms() - t_gen) / 1000

    # group rows by key type (crypto/batch.py dispatch shape)
    ed_rows = [i for i, v in enumerate(vs.validators)
               if v.pub_key.key_type == "ed25519"]
    sr_rows = [i for i, v in enumerate(vs.validators)
               if v.pub_key.key_type == "sr25519"]
    total_power = vs.total_voting_power()
    threshold = total_power * 2 // 3

    def pack_group(idxs, sr: bool):
        pubs = [vs.validators[i].pub_key.data for i in idxs]
        gmsgs = [msgs[i] for i in idxs]
        gsigs = [commit.signatures[i].signature for i in idxs]
        powers = np.asarray(
            [vs.validators[i].voting_power for i in idxs], np.int64
        )
        n = len(idxs)
        pad = kp.pad_to_tile(n)
        power5 = np.zeros((pad, ek.POWER_LIMBS), np.int32)
        power5[:n] = ek.power_limbs(powers)
        counted = np.zeros((pad,), np.bool_)
        counted[:n] = True
        cid = np.zeros((pad,), np.int32)
        # per-group threshold is a placeholder; the real quorum compare
        # happens host-side on the SUM of group tallies
        th = ek.threshold_limbs(1)
        if sr:
            return srk.pack_batch_sr(pubs, gmsgs, gsigs, pad_to=pad,
                                     power5=power5, counted=counted,
                                     commit_ids=cid, thresh=th)
        pb = ek.pack_batch(pubs, gmsgs, gsigs, pad_to=pad)
        return kp.pack_rows(pb, power5, counted, cid, th)

    t_pack = _now_ms()
    rows_ed = pack_group(ed_rows, sr=False)
    rows_sr = pack_group(sr_rows, sr=True)
    pack_ms = _now_ms() - t_pack

    import functools

    import jax.numpy as jnp

    # ONE compiled program: both key-type kernels + the cross-group
    # tally sum + the quorum compare, all device-side (round-4 verdict:
    # "fuse the ed25519+sr25519 tallies device-side into one quorum
    # answer" — the host 6-limb add also forced two separate syncs)
    @functools.partial(jax.jit, static_argnames=())
    def fused_pass(red, rsr, base, th6):
        v_ed, t_ed, _ = kp._verify_tally_rows.__wrapped__(red, base, 1)
        v_sr, t_sr, _ = srk._verify_tally_rows_sr.__wrapped__(
            rsr, base, 1)
        tot = t_ed + t_sr
        for i in range(ek.TALLY_LIMBS - 1):
            c = tot[..., i] >> ek.POWER_LIMB_BITS
            tot = tot.at[..., i].add(-(c << ek.POWER_LIMB_BITS)) \
                     .at[..., i + 1].add(c)
        return v_ed, v_sr, tot, ek.quorum_core(tot, th6)

    th6 = jnp.asarray(ek.threshold_limbs(threshold))
    base = kp.base_dev()

    def one_pass(red, rsr):
        return fused_pass(red, rsr, base, th6)

    d_ed = jax.device_put(rows_ed)
    d_sr = jax.device_put(rows_sr)
    v_ed, v_sr, tot, quorum = one_pass(d_ed, d_sr)
    ed_ok = np.asarray(v_ed)[: len(ed_rows)].all()
    sr_ok = np.asarray(v_sr)[: len(sr_rows)].all()
    got_power = tally_int(np.asarray(tot)[0])
    assert ed_ok and sr_ok, "mixed commit must verify"
    assert got_power == total_power
    assert bool(np.asarray(quorum)[0])

    # best-of-3 steady loops (r05 post-mortem): a single K-pass wall on
    # the shared tunnel carries multi-x run-to-run noise — cfg3 swung
    # 110 -> 416 ms between rounds on an identical code path. The
    # minimum is the reproducible device+transport cost.
    steady = float("inf")
    for _ in range(3):
        t = _now_ms()
        outs = None
        for _ in range(steady_k):
            outs = one_pass(jax.device_put(rows_ed),
                            jax.device_put(rows_sr))
        assert bool(np.asarray(outs[3])[0])
        steady = min(steady, (_now_ms() - t) / steady_k)

    # CPU baseline: measured OpenSSL (C-speed) ed25519 verify per-sig,
    # applied to all 10k rows (conservative: CPU schnorrkel verification
    # costs at least as much as ed25519 per signature). NOT the
    # pure-Python ZIP-215 oracle, which would inflate vs_baseline ~40x.
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    sample = 300
    pks = [
        Ed25519PublicKey.from_public_bytes(vs.validators[i].pub_key.data)
        for i in ed_rows[:sample]
    ]
    t = _now_ms()
    for j, i in enumerate(ed_rows[:sample]):
        pks[j].verify(commit.signatures[i].signature, msgs[i])
    per_sig = (_now_ms() - t) / sample
    cpu_ms = per_sig * n_vals
    return {
        "metric": "cfg3 10k mixed ed25519/sr25519 fused tally",
        "value": round(steady, 2),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / steady, 2),
        "extra": {
            "ed_rows": len(ed_rows),
            "sr_rows": len(sr_rows),
            "host_pack_ms": round(pack_ms, 1),
            "cpu_measured_ms": round(cpu_ms, 1),
            "fixture_gen_s": round(gen_s, 1),
            "sigs_per_sec": round(n_vals / (steady / 1000)),
            "note": "two fused verify+tally device passes (one per key "
                    "type) + host 6-limb tally add; the reference cannot "
                    "run this config at all in one batch verifier",
        },
    }
