"""Device-mesh sharding for the verification pipeline.

CometBFT's scale dimensions are validator-set size (up to 10k sigs per
commit, types/vote_set.go:18 MaxVotesCount) x commits in flight (blocksync
window 600, blocksync/pool.go:32). Both map to pure data parallelism: the
signature batch shards across a 1-D `batch` mesh axis, each device verifies
its slice and computes a partial voting-power tally, and one `psum` over ICI
reduces the per-commit tallies (the TPU analog of the reference's
gossip-aggregated `libs/bits` bitarrays + tally loop, SURVEY.md §2.6).

Multi-host: the same code runs over a DCN-spanning mesh — XLA routes the
psum hierarchically (ICI within pod slice, DCN across hosts).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cometbft_tpu.ops import ed25519_kernel as ek


def make_mesh(devices=None, axis: str = "batch") -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis,))


def _carry_tally(t):
    """Re-canonicalize tally limbs after a psum (limbs < ndev * 2^13)."""
    for i in range(ek.TALLY_LIMBS - 1):
        c = t[..., i] >> ek.POWER_LIMB_BITS
        t = t.at[..., i].add(-(c << ek.POWER_LIMB_BITS)).at[..., i + 1].add(c)
    return t


def sharded_verify_tally(mesh: Mesh, n_commits: int):
    """Build the sharded fused verify+tally step for a given mesh.

    Returns a jitted fn with the same signature as
    ed25519_kernel.verify_tally_kernel (minus n_commits). Batch dims shard
    over the mesh axis; tallies are psum-reduced; threshold/quorum are
    replicated.
    """
    axis = mesh.axis_names[0]
    bspec = P(axis)
    rspec = P()

    def step(ay, asign, ry, rsign, sdig, hdig, precheck, power5, counted,
             commit_ids, threshold):
        valid = ek.verify_core(ay, asign, ry, rsign, sdig, hdig, precheck)
        local = ek.tally_core(valid, power5, counted, commit_ids, n_commits)
        total = jax.lax.psum(local, axis)
        total = _carry_tally(total)
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(bspec,) * 7 + (bspec, bspec, bspec, rspec),
        out_specs=(bspec, rspec, rspec),
    )
    return jax.jit(sharded)


def sharded_verify_tally_rows(mesh: Mesh, n_commits: int):
    """The FLAGSHIP (Pallas) kernel under shard_map.

    The compact packed array (R, B) shards on its lane axis (axis 1): each
    device runs the Mosaic kernel on its B/n_dev slice (which must be a
    multiple of ed25519_pallas.B_TILE), computes its partial power tally,
    and one psum over the mesh reduces per-commit tallies. Thresholds ride
    as a separate replicated argument (they are per-commit, not per-row,
    so they must not be lane-sharded with the rows)."""
    from cometbft_tpu.ops import ed25519_pallas as kp

    axis = mesh.axis_names[0]

    def step(rows, base, threshold):
        valid = kp._verify_rows.__wrapped__(rows, base)
        pw = rows[kp.C_POW:kp.C_POW + 3]
        power5 = jax.numpy.stack(
            [pw[0] & kp._M13, pw[0] >> 13, pw[1] & kp._M13,
             pw[1] >> 13, pw[2]], axis=1)
        counted = (rows[kp.C_FLAGS] >> 3) & 1 != 0
        commit_ids = rows[kp.C_CID]
        local = ek.tally_core(valid, power5, counted, commit_ids, n_commits)
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(None, axis), P(), P()),
        out_specs=(P(axis), P(), P()),
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # the specs above pin the sharding explicitly
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_batch_arrays(mesh: Mesh, pb: ek.PackedBatch, power5, counted,
                       commit_ids):
    """Pad batch arrays to a multiple of the mesh size and device_put them
    with the batch sharding (so the jitted step does no host resharding)."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    padded = pb.padded
    if padded % n_dev:
        extra = n_dev - padded % n_dev
        pad1 = lambda a: np.pad(a, [(0, extra)] + [(0, 0)] * (a.ndim - 1))
        pb = pb._replace(
            padded=padded + extra, ay=pad1(pb.ay), asign=pad1(pb.asign),
            ry=pad1(pb.ry), rsign=pad1(pb.rsign), sdig=pad1(pb.sdig),
            hdig=pad1(pb.hdig), precheck=pad1(pb.precheck),
        )
        power5 = pad1(np.asarray(power5))
        counted = pad1(np.asarray(counted))
        commit_ids = pad1(np.asarray(commit_ids))
    sh = NamedSharding(mesh, P(axis))
    put = lambda a: jax.device_put(a, sh)
    return pb, (
        put(pb.ay), put(pb.asign), put(pb.ry), put(pb.rsign), put(pb.sdig),
        put(pb.hdig), put(pb.precheck), put(power5), put(counted),
        put(commit_ids),
    )


def sharded_stream_verify(mesh: Mesh, n_commits: int):
    """The blocksync STREAMING path (cached-valset kernel) under
    shard_map: a multi-commit chunk shards at COMMIT granularity.

    Layout contract (blocksync/pipeline.py _pack_chunk_cached): commit c
    occupies rows [c*M, (c+1)*M) with validator i at row c*M + i. The
    rows array (R, C*M) shards on its lane axis so each device holds
    C/n_dev whole commits — the per-device slice width stays a multiple
    of M, which keeps the kernel's `row mod M -> validator` and
    `tile mod M/128 -> table block` maps intact without any index
    plumbing. The valset table replicates (it is the same valset for
    every commit — the streaming shape, blocksync/reactor.go:463); rows
    carry GLOBAL commit ids, so each device's partial tally lands in
    the right commit slot and one psum over the mesh finishes every
    commit's quorum at once.
    """
    from cometbft_tpu.ops import ed25519_cached as ec

    axis = mesh.axis_names[0]

    def step(rows, tab, ok, power5, base, threshold):
        valid, local, _ = ec._verify_tally_cached.__wrapped__(
            rows, tab, ok, power5, base, n_commits
        )
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(None, axis), P(), P(), P(), P(), P()),
        out_specs=(P(axis), P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)
