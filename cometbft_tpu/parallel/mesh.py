"""Device-mesh sharding for the verification pipeline.

CometBFT's scale dimensions are validator-set size (up to 10k sigs per
commit, types/vote_set.go:18 MaxVotesCount) x commits in flight (blocksync
window 600, blocksync/pool.go:32). Both map to pure data parallelism: the
signature batch shards across a 1-D `batch` mesh axis, each device verifies
its slice and computes a partial voting-power tally, and one `psum` over ICI
reduces the per-commit tallies (the TPU analog of the reference's
gossip-aggregated `libs/bits` bitarrays + tally loop, SURVEY.md §2.6).

Multi-host: the same code runs over a DCN-spanning mesh — XLA routes the
psum hierarchically (ICI within pod slice, DCN across hosts).

Sub-meshes: every step below is memoized by the EXACT device tuple
(_mesh_key), so the verify plane's pipelined halves (fused.half_meshes
— two disjoint sub-meshes flying alternating flushes) each compile
their own program exactly once and hit the memo steady-state; a half
and the full mesh never collide in the cache. The psum in each step
reduces over its own mesh's axis only, which is what makes a flush
complete within its half — its rows, table shards, and thresholds all
live there (the deck's disjointness invariant).
"""
from __future__ import annotations

import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cometbft_tpu.ops import ed25519_kernel as ek

# jax.shard_map went top-level in 0.5.x; older containers only have the
# experimental module (and spell the unchecked-replication kwarg
# check_rep instead of check_vma). One shim keeps every builder below
# running on both.
if hasattr(jax, "shard_map"):
    _shard_map, _UNCHECKED_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - exercised on jax<0.5 containers
    from jax.experimental.shard_map import shard_map as _shard_map

    _UNCHECKED_KW = "check_rep"


def _smap(fn, mesh, in_specs, out_specs, unchecked: bool = False):
    kw = {_UNCHECKED_KW: False} if unchecked else {}
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def make_mesh(devices=None, axis: str = "batch") -> Mesh:
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis,))


# Compiled-step memo (round-5 regression fix): every builder below used
# to return a FRESH jax.jit(shard_map(...)) closure per call, so two
# calls with the same mesh re-traced — and on CPU interpret-compiled the
# Pallas kernel again, minutes each. Steps are cached by
# (builder, mesh identity, n_commits); jit's own cache handles row-shape
# specialization within a step.
_STEP_CACHE: dict = {}

# Memoization regression guard (the round-5 MULTICHIP timeout was
# per-call shard_map rebuilds): every builder counts its probe, so
# tests — and the bench's multichip smoke — can assert steady-state
# calls HIT instead of silently re-tracing. The counters are mutated
# from the verify plane's dispatcher thread AND from test/bench/scrape
# probes concurrently, so increments ride one module lock — an
# unguarded += loses counts exactly when several threads flush at once
# (the same race the plane's sheds counter fixed in PR 7).
_CACHE_STATS = {"hits": 0, "misses": 0}
_STATS_LOCK = threading.Lock()


def cache_stats() -> dict:
    with _STATS_LOCK:
        return dict(_CACHE_STATS)


def _cache_get(key):
    fn = _STEP_CACHE.get(key)
    with _STATS_LOCK:
        if fn is not None:
            _CACHE_STATS["hits"] += 1
        else:
            _CACHE_STATS["misses"] += 1
    return fn


def _cache_put(key, fn):
    """Memoize a freshly-built step, wrapped so its FIRST invocation
    attributes the lazy jit trace/compile to this builder in the
    device observatory's compile ledger (libs/deviceledger) — unless
    a richer frame (the verify plane's per-flush attribution, a bench
    config) is already active on the calling thread, in which case
    that frame keeps the credit. After the first call the wrapper is
    a list check: steady-state dispatch cost is untouched."""
    from cometbft_tpu.libs import deviceledger

    site = f"mesh.step:{key[0]}"
    done: list = []

    def wrapped(*args):
        if done:
            return fn(*args)
        fr = deviceledger.attr_begin_fallback(site)
        try:
            return fn(*args)
        finally:
            done.append(1)
            if fr is not None:
                deviceledger.attr_end(fr)

    _STEP_CACHE[key] = wrapped
    return wrapped


def _mesh_key(mesh: Mesh):
    return (tuple(mesh.axis_names), tuple(mesh.devices.flat))


def _carry_tally(t):
    """Re-canonicalize tally limbs after a psum (limbs < ndev * 2^13)."""
    for i in range(ek.TALLY_LIMBS - 1):
        c = t[..., i] >> ek.POWER_LIMB_BITS
        t = t.at[..., i].add(-(c << ek.POWER_LIMB_BITS)).at[..., i + 1].add(c)
    return t


def sharded_verify_tally(mesh: Mesh, n_commits: int):
    """Build the sharded fused verify+tally step for a given mesh.

    Returns a jitted fn with the same signature as
    ed25519_kernel.verify_tally_kernel (minus n_commits). Batch dims shard
    over the mesh axis; tallies are psum-reduced; threshold/quorum are
    replicated. Memoized per (mesh, n_commits).
    """
    key = ("xla", _mesh_key(mesh), int(n_commits))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]
    bspec = P(axis)
    rspec = P()

    def step(ay, asign, ry, rsign, sdig, hdig, precheck, power5, counted,
             commit_ids, threshold):
        valid = ek.verify_core(ay, asign, ry, rsign, sdig, hdig, precheck)
        local = ek.tally_core(valid, power5, counted, commit_ids, n_commits)
        total = jax.lax.psum(local, axis)
        total = _carry_tally(total)
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = _smap(
        step,
        mesh=mesh,
        in_specs=(bspec,) * 7 + (bspec, bspec, bspec, rspec),
        out_specs=(bspec, rspec, rspec),
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def _sharded_verify_rows_step(mesh: Mesh):
    """The EXPENSIVE half of the rows path: the Mosaic/Pallas verify
    kernel (plus cheap per-row column extraction) under shard_map.
    Independent of n_commits, so every tally width shares this one
    compiled program — the round-5 multichip regression was exactly this
    program compiling once per (call, n_commits)."""
    key = ("pallas-verify", _mesh_key(mesh))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    from cometbft_tpu.ops import ed25519_pallas as kp

    axis = mesh.axis_names[0]

    def vstep(rows, base):
        valid = kp._verify_rows.__wrapped__(rows, base)
        pw = rows[kp.C_POW:kp.C_POW + 3]
        power5 = jax.numpy.stack(
            [pw[0] & kp._M13, pw[0] >> 13, pw[1] & kp._M13,
             pw[1] >> 13, pw[2]], axis=1)
        counted = (rows[kp.C_FLAGS] >> 3) & 1 != 0
        commit_ids = rows[kp.C_CID]
        return valid, power5, counted, commit_ids

    sharded = _smap(
        vstep,
        mesh=mesh,
        in_specs=(P(None, axis), P()),
        out_specs=(P(axis), P(axis, None), P(axis), P(axis)),
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # the specs above pin the sharding explicitly
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def _sharded_tally_step(mesh: Mesh, n_commits: int):
    """The CHEAP half: per-device tally einsum + psum + quorum. A fresh
    trace per n_commits costs seconds, not the Pallas kernel's minutes."""
    key = ("pallas-tally", _mesh_key(mesh), int(n_commits))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def tstep(valid, power5, counted, commit_ids, threshold):
        local = ek.tally_core(valid, power5, counted, commit_ids, n_commits)
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return total, quorum

    sharded = _smap(
        tstep,
        mesh=mesh,
        in_specs=(P(axis), P(axis, None), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def sharded_verify_tally_rows(mesh: Mesh, n_commits: int):
    """The FLAGSHIP (Pallas) kernel under shard_map.

    The compact packed array (R, B) shards on its lane axis (axis 1): each
    device runs the Mosaic kernel on its B/n_dev slice (which must be a
    multiple of ed25519_pallas.B_TILE), computes its partial power tally,
    and one psum over the mesh reduces per-commit tallies. Thresholds ride
    as a separate replicated argument (they are per-commit, not per-row,
    so they must not be lane-sharded with the rows).

    Two compiled programs compose the step: the n_commits-independent
    Pallas verify (shared by ALL tally widths on a mesh) and a tiny
    per-n_commits tally+psum jit. Both are memoized, so repeated calls —
    the round-5 multichip regression — reuse the compiled closures
    instead of re-tracing."""
    key = ("rows", _mesh_key(mesh), int(n_commits))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    verify = _sharded_verify_rows_step(mesh)
    tally = _sharded_tally_step(mesh, n_commits)

    def fn(rows, base, threshold):
        valid, power5, counted, commit_ids = verify(rows, base)
        total, quorum = tally(valid, power5, counted, commit_ids,
                              threshold)
        return valid, total, quorum

    return _cache_put(key, fn)


def shard_batch_arrays(mesh: Mesh, pb: ek.PackedBatch, power5, counted,
                       commit_ids):
    """Pad batch arrays to a multiple of the mesh size and device_put them
    with the batch sharding (so the jitted step does no host resharding).

    Padding rows necessarily carry commit_id=0 (there is no "no commit"
    id); they are kept out of every tally by construction: counted is
    cast to bool and the padding region is set False EXPLICITLY (not
    left to zero-fill), and precheck pads False so the verify core
    rejects the rows independently. tests/test_mesh.py's padded-vs-
    unpadded tally regression guards commit 0's sum bit-for-bit."""
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    padded = pb.padded
    counted = np.asarray(counted, np.bool_)
    if padded % n_dev:
        extra = n_dev - padded % n_dev
        pad1 = lambda a: np.pad(a, [(0, extra)] + [(0, 0)] * (a.ndim - 1))
        pb = pb._replace(
            padded=padded + extra, ay=pad1(pb.ay), asign=pad1(pb.asign),
            ry=pad1(pb.ry), rsign=pad1(pb.rsign), sdig=pad1(pb.sdig),
            hdig=pad1(pb.hdig), precheck=pad1(pb.precheck),
        )
        power5 = pad1(np.asarray(power5))
        counted = pad1(counted)
        counted[padded:] = False  # padding rows are never counted
        commit_ids = pad1(np.asarray(commit_ids))
    sh = NamedSharding(mesh, P(axis))
    put = lambda a: jax.device_put(a, sh)
    return pb, (
        put(pb.ay), put(pb.asign), put(pb.ry), put(pb.rsign), put(pb.sdig),
        put(pb.hdig), put(pb.precheck), put(power5), put(counted),
        put(commit_ids),
    )


def sharded_stream_verify(mesh: Mesh, n_commits: int):
    """The blocksync STREAMING path (cached-valset kernel) under
    shard_map: a multi-commit chunk shards at COMMIT granularity.

    Layout contract (blocksync/pipeline.py _pack_chunk_cached): commit c
    occupies rows [c*M, (c+1)*M) with validator i at row c*M + i. The
    rows array (R, C*M) shards on its lane axis so each device holds
    C/n_dev whole commits — the per-device slice width stays a multiple
    of M, which keeps the kernel's `row mod M -> validator` and
    `tile mod M/128 -> table block` maps intact without any index
    plumbing. The valset table replicates (it is the same valset for
    every commit — the streaming shape, blocksync/reactor.go:463); rows
    carry GLOBAL commit ids, so each device's partial tally lands in
    the right commit slot and one psum over the mesh finishes every
    commit's quorum at once.
    """
    from cometbft_tpu.ops import ed25519_cached as ec

    key = ("stream", _mesh_key(mesh), int(n_commits))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def step(rows, tab, ok, power5, base, threshold):
        valid, local, _ = ec._verify_tally_cached.__wrapped__(
            rows, tab, ok, power5, base, n_commits
        )
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = _smap(
        step,
        mesh=mesh,
        in_specs=(P(None, axis), P(), P(), P(), P(), P()),
        out_specs=(P(axis), P(), P()),
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def sharded_fused_verify(mesh: Mesh, n_commits: int):
    """The verify PLANE's fused flush under shard_map: the cached-table
    kernel with the VALSET sharded across the mesh.

    Where sharded_stream_verify replicates one table and shards at
    commit granularity (the blocksync shape: many commits, modest
    valset), this shards the validator set itself — the 100k-validator
    commit shape, where ONE commit's valset exceeds a single chip's
    table budget (table_pad caps at 65536 slots/device). Device d holds
    the window-table shard for validators [d*M_s, (d+1)*M_s)
    (ed25519_cached.sharded_table_for_pubs) and its rows slice carries
    exactly those validators' signatures (fused.shard_positions lays
    commits out so row `d*B_loc + s*M_s + (v mod M_s)` is validator v's
    stride-s slot — the in-kernel `row mod M -> validator` map then
    resolves LOCAL indices with no plumbing). Rows carry GLOBAL commit
    ids, so each device's partial voting-power tally lands in the right
    commit slot; one psum over the mesh + a limb re-carry + quorum_core
    finish every commit's quorum bit ON DEVICE — the fused quorum
    output generalizes across chips.

    Thresholds ride as a separate replicated argument (the in-rows
    threshold rows are per-device slices and meaningless sharded; the
    kernel's own quorum output is discarded). Memoized per
    (mesh, n_commits); the expensive Pallas program recompiles per
    (mesh, local-batch-shape) under jit's own cache, exactly like the
    single-device path's bucket shapes."""
    from cometbft_tpu.ops import ed25519_cached as ec

    key = ("fused", _mesh_key(mesh), int(n_commits))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def step(rows, tab, ok, power5, base, threshold):
        valid, local, _ = ec._verify_tally_cached.__wrapped__(
            rows, tab, ok, power5, base, n_commits
        )
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = _smap(
        step,
        mesh=mesh,
        in_specs=(P(None, axis), P(axis, None), P(axis), P(axis, None),
                  P(), P()),
        out_specs=(P(axis), P(), P()),
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def sharded_stamped_verify(mesh: Mesh, n_commits: int, msg_max: int):
    """sharded_fused_verify's DELTA twin: each device stamps its own
    rows slice from the per-row deltas before the cached kernel runs.

    The staged deltas shard exactly like the rows they expand into —
    sig/ts shard on the row axis, flags on its only axis — because
    fused.shard_positions already laid row `d*B_loc + s*M_s + v_loc`
    out as device d's stride-s slot for local validator v_loc: the
    stamping prologue's `row mod pub_raw_len -> validator` gather then
    resolves against the device's OWN (M_s, 32) pub_raw shard with no
    index plumbing, and the expanded slice is bit-identical to the
    single-device oracle's slice (the shardplane prog's stamped
    phase). Template matrices replicate (a few hundred bytes, one
    family per flush); thresholds ride the replicated `threshold` arg
    as ever — the in-rows threshold rows are zeros here (t_rows=1),
    matching the sharded fused path's discard of the in-kernel quorum.

    Memoized per (mesh, n_commits, msg_max): msg_max is a static of
    the stamp trace; the template matrices' bucketed shapes retrace
    under jit's own cache like any other arg shape."""
    from cometbft_tpu.ops import ed25519_cached as ec

    key = ("stamped", _mesh_key(mesh), int(n_commits), int(msg_max))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def step(sig, ts, flags, pre_mat, pre_len, suf_mat, suf_len,
             ts_tag, pub_raw, tab, ok, power5, base, threshold):
        thr0 = jax.numpy.zeros((1, ek.TALLY_LIMBS), jax.numpy.int32)
        rows = ec._stamp_rows_core(
            sig, ts, flags, pre_mat, pre_len, suf_mat, suf_len,
            ts_tag, pub_raw, thr0, msg_max=msg_max, t_rows=1)
        valid, local, _ = ec._verify_tally_cached.__wrapped__(
            rows, tab, ok, power5, base, n_commits
        )
        total = _carry_tally(jax.lax.psum(local, axis))
        quorum = ek.quorum_core(total, threshold)
        return valid, total, quorum

    sharded = _smap(
        step,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis),
                  P(), P(), P(), P(), P(),
                  P(axis, None), P(axis, None), P(axis), P(axis, None),
                  P(), P()),
        out_specs=(P(axis), P(), P()),
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)


def sharded_stamp_rows(mesh: Mesh, msg_max: int):
    """Test/oracle step: ONLY the per-shard stamping prologue, rows
    gathered back lane-sharded — so the shardplane prog can assert the
    per-device stamped slices bit-match the single-device expansion
    without running the verify kernel."""
    from cometbft_tpu.ops import ed25519_cached as ec

    key = ("stamp-rows", _mesh_key(mesh), int(msg_max))
    cached = _cache_get(key)
    if cached is not None:
        return cached
    axis = mesh.axis_names[0]

    def step(sig, ts, flags, pre_mat, pre_len, suf_mat, suf_len,
             ts_tag, pub_raw):
        thr0 = jax.numpy.zeros((1, ek.TALLY_LIMBS), jax.numpy.int32)
        return ec._stamp_rows_core(
            sig, ts, flags, pre_mat, pre_len, suf_mat, suf_len,
            ts_tag, pub_raw, thr0, msg_max=msg_max, t_rows=1)

    sharded = _smap(
        step,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis),
                  P(), P(), P(), P(), P(), P(axis, None)),
        out_specs=P(None, axis),
        unchecked=True,
    )
    fn = jax.jit(sharded)
    return _cache_put(key, fn)
