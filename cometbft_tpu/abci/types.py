"""ABCI: the application boundary interface and message types.

Reference: abci/types/application.go:9-60 (the 14-method Application
interface), proto/tendermint/abci (message fields — represented here as
dataclasses; the socket/grpc wire codecs serialize them when the app runs
out of process).

The in-process path (proxy.local_client analog) passes these dataclasses
directly — no serialization, mirroring abci/client/local_client.go.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CODE_TYPE_OK = 0
# Non-OK CheckTx codes the NODE itself (not the app) may answer with.
# The reference leaves code semantics to the app; these two sit far
# above the small codes sample apps use so they can never collide.
# OVERLOADED is the explicit load-shed verdict: admission control
# fast-rejected the tx, or the verify plane shed its BULK-lane
# signature check past the deadline. The log carries a
# `retry_after_ms=N` hint (the Retry-After analog for JSON-RPC).
CODE_TYPE_OVERLOADED = 1001
# the node-side signature pre-check (mempool sigtx envelope) failed —
# the tx never reached the app
CODE_TYPE_BAD_SIGNATURE = 1002


@dataclass
class ValidatorUpdate:
    pub_key: bytes  # raw ed25519 key bytes
    power: int
    key_type: str = "ed25519"


@dataclass
class Snapshot:
    """abci Snapshot (proto/tendermint/abci Snapshot)."""

    height: int = 0
    format: int = 1
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


# ResponseApplySnapshotChunk.Result (abci/types.proto ApplySnapshotChunk
# result enum) — lets the app direct the statesync chunk engine:
APPLY_CHUNK_ACCEPT = 0          # chunk applied, move on
APPLY_CHUNK_ABORT = 1           # abort all snapshot restoration
APPLY_CHUNK_RETRY = 2           # refetch + reapply THIS chunk
APPLY_CHUNK_RETRY_SNAPSHOT = 3  # restart the whole snapshot
APPLY_CHUNK_REJECT_SNAPSHOT = 4  # never try this snapshot again


@dataclass
class ResponseApplySnapshotChunk:
    """Rich apply result (abci Response.ApplySnapshotChunk). Apps may
    also return a bare bool (True == ACCEPT, False == RETRY)."""

    result: int = APPLY_CHUNK_ACCEPT
    refetch_chunks: list = field(default_factory=list)
    reject_senders: list = field(default_factory=list)


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class RequestInitChain:
    time_seconds: int = 0
    chain_id: str = ""
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class ResponseInitChain:
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    recheck: bool = False


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    # structured backoff hint for CODE_TYPE_OVERLOADED responses (0 =
    # none): the machine-readable source for the RPC layer's
    # `retry_after_ms` field — the log carries the same number for
    # humans, but clients must never have to parse it out of a string
    retry_after_ms: float = 0.0


@dataclass
class VoteInfo:
    """abci.VoteInfo: one LastCommit entry for the app's incentive
    logic (execution.go:443 buildLastCommitInfo)."""

    validator_address: bytes = b""
    power: int = 0
    block_id_flag: int = 0  # types/block.go BlockIDFlag values


@dataclass
class CommitInfo:
    round: int = 0
    votes: List[VoteInfo] = field(default_factory=list)


@dataclass
class ExtendedVoteInfo:
    """abci.ExtendedVoteInfo: VoteInfo + the validator's vote extension
    (execution.go:472 buildExtendedCommitInfo)."""

    validator_address: bytes = b""
    power: int = 0
    block_id_flag: int = 0
    vote_extension: bytes = b""
    extension_signature: bytes = b""


@dataclass
class ExtendedCommitInfo:
    round: int = 0
    votes: List[ExtendedVoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    """abci.Misbehavior (evidence reported to the app in FinalizeBlock)."""

    type: str = "duplicate_vote"  # or "light_client_attack"
    validator_address: bytes = b""
    height: int = 0
    time_seconds: int = 0
    total_voting_power: int = 0


@dataclass
class RequestPrepareProposal:
    max_tx_bytes: int = 0
    txs: List[bytes] = field(default_factory=list)
    height: int = 0
    proposer_address: bytes = b""
    # extensions from the previous height's precommits, when enabled
    # (the app may fold them into the proposed txs)
    local_last_commit: Optional[ExtendedCommitInfo] = None


@dataclass
class ResponsePrepareProposal:
    txs: List[bytes] = field(default_factory=list)


@dataclass
class RequestProcessProposal:
    txs: List[bytes] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    proposer_address: bytes = b""


PROCESS_PROPOSAL_ACCEPT = 1
PROCESS_PROPOSAL_REJECT = 2


@dataclass
class ResponseProcessProposal:
    status: int = PROCESS_PROPOSAL_ACCEPT


@dataclass
class RequestFinalizeBlock:
    txs: List[bytes] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    proposer_address: bytes = b""
    time_seconds: int = 0
    # who signed the block's LastCommit + flags (incentive logic)
    decided_last_commit: Optional[CommitInfo] = None
    # evidence committed in this block (execution.go extendedCommitInfo)
    misbehavior: List[Misbehavior] = field(default_factory=list)


@dataclass
class RequestExtendVote:
    """ExtendVote (application.go, execution.go:318): the app attaches
    arbitrary data to this validator's precommit."""

    hash: bytes = b""
    height: int = 0
    round: int = 0


@dataclass
class ResponseExtendVote:
    vote_extension: bytes = b""


@dataclass
class RequestVerifyVoteExtension:
    """VerifyVoteExtension (execution.go:349): validate another
    validator's extension before accepting its precommit."""

    hash: bytes = b""
    validator_address: bytes = b""
    height: int = 0
    vote_extension: bytes = b""


VERIFY_VOTE_EXTENSION_ACCEPT = 1
VERIFY_VOTE_EXTENSION_REJECT = 2


@dataclass
class ResponseVerifyVoteExtension:
    status: int = VERIFY_VOTE_EXTENSION_ACCEPT


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0


@dataclass
class ResponseFinalizeBlock:
    tx_results: List[ExecTxResult] = field(default_factory=list)
    validator_updates: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseCommit:
    retain_height: int = 0


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    key: bytes = b""
    value: bytes = b""
    height: int = 0
    log: str = ""
    # crypto.proof_ops.ProofOp list when the request set prove=True
    # (abci ResponseQuery.proof_ops) — chains value -> app_hash
    proof_ops: list = field(default_factory=list)


class Application:
    """The 14-method ABCI++ surface (abci/types/application.go:9-60).

    Base implementations are accept-everything no-ops, mirroring
    abci/types/application.go BaseApplication."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    def prepare_proposal(
        self, req: RequestPrepareProposal
    ) -> ResponsePrepareProposal:
        return ResponsePrepareProposal(txs=list(req.txs))

    def process_proposal(
        self, req: RequestProcessProposal
    ) -> ResponseProcessProposal:
        return ResponseProcessProposal()

    def finalize_block(
        self, req: RequestFinalizeBlock
    ) -> ResponseFinalizeBlock:
        return ResponseFinalizeBlock(
            tx_results=[ExecTxResult() for _ in req.txs]
        )

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    # vote extensions (application.go ExtendVote/VerifyVoteExtension;
    # consensus calls these for precommits once
    # ConsensusParams.abci.vote_extensions_enable_height is reached)
    def extend_vote(self, req: RequestExtendVote) -> ResponseExtendVote:
        return ResponseExtendVote()

    def verify_vote_extension(
        self, req: RequestVerifyVoteExtension
    ) -> ResponseVerifyVoteExtension:
        return ResponseVerifyVoteExtension()

    # state-sync snapshots (abci/types/application.go:9 ListSnapshots/
    # OfferSnapshot/LoadSnapshotChunk/ApplySnapshotChunk)
    def list_snapshots(self) -> list:
        return []

    def offer_snapshot(self, snapshot: "Snapshot") -> bool:
        return False

    def load_snapshot_chunk(self, height, fmt, chunk) -> bytes:
        return b""

    def apply_snapshot_chunk(self, index, chunk, sender):
        """Returns bool (True == ACCEPT, False == RETRY) or a
        ResponseApplySnapshotChunk for refetch/reject control."""
        return False
