"""abci-cli: serve the example app over a socket and poke ABCI
servers from the command line.

Reference: abci/cmd/abci-cli/abci-cli.go (serve/kvstore, console with
info/query/check_tx, one-shot commands). Wire format is the framed
JSON codec in abci/server.py.
"""
from __future__ import annotations

import argparse
import shlex
import signal
import sys
import time

from cometbft_tpu.abci import types as abci


def _tx_arg(s: str) -> bytes:
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    return s.encode()


def _connect(addr: str):
    from cometbft_tpu.abci.server import ABCISocketClient

    host, _, port = addr.rpartition(":")
    return ABCISocketClient(host or "127.0.0.1", int(port))


def cmd_serve(args) -> int:
    """abci-cli kvstore: run the example app as a socket or gRPC
    server (abci-cli.go --abci / grpc_server.go)."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication

    if getattr(args, "transport", "socket") == "grpc":
        from cometbft_tpu.abci.grpc import ABCIGRPCServer as Server
    else:
        from cometbft_tpu.abci.server import ABCISocketServer as Server

    srv = Server(KVStoreApplication(), host=args.host, port=args.port)
    srv.start()
    print(f"abci kvstore serving on {srv.addr[0]}:{srv.addr[1]} "
          f"({getattr(args, 'transport', 'socket')})", flush=True)
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    t0 = time.time()
    while not stop and (args.run_for <= 0
                        or time.time() < t0 + args.run_for):
        time.sleep(0.2)
    srv.stop()
    return 0


def _run_one(client, cmd: str, argv: list) -> None:
    if cmd in ("check_tx", "query") and not argv:
        print(f"usage: {cmd} <{'tx' if cmd == 'check_tx' else 'key'}> "
              f"(string or 0x-hex)")
        return
    if cmd == "info":
        r = client.info(abci.RequestInfo())
        print(f"-> data: {r.data!r} height: {r.last_block_height} "
              f"app_hash: {r.last_block_app_hash.hex()}")
    elif cmd == "check_tx":
        r = client.check_tx(abci.RequestCheckTx(tx=_tx_arg(argv[0])))
        print(f"-> code: {r.code} log: {r.log!r}")
    elif cmd == "query":
        r = client.query(abci.RequestQuery(data=_tx_arg(argv[0])))
        print(f"-> code: {r.code} key: {r.key!r} value: {r.value!r}")
    elif cmd == "commit":
        client.commit()
        print("-> ok")
    elif cmd == "echo":
        # no Echo RPC in the method table: info round-trips instead
        client.info(abci.RequestInfo())
        print(f"-> {argv[0] if argv else ''}")
    else:
        print(f"unknown command {cmd!r} "
              f"(info|check_tx|query|commit|echo)")


def cmd_console(args) -> int:
    """abci-cli console: interactive REPL against a running server."""
    client = _connect(args.addr)
    print(f"connected to {args.addr}; commands: "
          f"info, check_tx <tx>, query <key>, commit, echo, quit")
    for line in sys.stdin:
        parts = shlex.split(line.strip())
        if not parts:
            continue
        if parts[0] in ("quit", "exit"):
            break
        try:
            _run_one(client, parts[0], parts[1:])
        except Exception as e:  # noqa: BLE001 - REPL survives bad input
            print(f"error: {e}")
    client.close()
    return 0


def cmd_oneshot(args) -> int:
    client = _connect(args.addr)
    try:
        _run_one(client, args.abci_cmd, args.args)
    finally:
        client.close()
    return 0


def add_abci_subcommands(sub) -> None:
    """Mount the abci-cli under the main CLI (`cometbft_tpu abci ...`)."""
    p = sub.add_parser("abci", help="ABCI tools (serve/console/one-shot)")
    asub = p.add_subparsers(dest="abci_sub", required=True)

    q = asub.add_parser("kvstore", help="serve the kvstore app")
    q.add_argument("--host", default="127.0.0.1")
    q.add_argument("--port", type=int, default=26658)
    q.add_argument("--run-for", type=float, default=0)
    q.add_argument("--transport", choices=("socket", "grpc"),
                   default="socket",
                   help="ABCI server transport (abci-cli.go --abci)")
    q.set_defaults(fn=cmd_serve)

    q = asub.add_parser("console", help="interactive ABCI console")
    q.add_argument("--addr", default="127.0.0.1:26658")
    q.set_defaults(fn=cmd_console)

    for name in ("info", "check_tx", "query", "commit", "echo"):
        q = asub.add_parser(name)
        q.add_argument("args", nargs="*")
        q.add_argument("--addr", default="127.0.0.1:26658")
        q.set_defaults(fn=cmd_oneshot, abci_cmd=name)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="abci-cli")
    sub = parser.add_subparsers(dest="command", required=True)
    add_abci_subcommands(sub)
    args = parser.parse_args(["abci"] + (argv or sys.argv[1:]))
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
