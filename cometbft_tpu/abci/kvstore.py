"""kvstore: the canonical test application.

Reference: abci/example/kvstore/kvstore.go — key=value txs, deterministic
app hash over state, validator-update txs of the form
"val:base64pubkey!power" (kvstore.go:46 ValidatorSetChangePrefix).
"""
from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List

from cometbft_tpu.abci import types as abci

VALIDATOR_PREFIX = b"val:"


class KVStoreApplication(abci.Application):
    """In-memory kvstore with deterministic app hash and validator updates."""

    def __init__(self):
        self.state: Dict[bytes, bytes] = {}
        self.height = 0
        self.app_hash = b""
        self.staged: Dict[bytes, bytes] = {}
        self.val_updates: List[abci.ValidatorUpdate] = []

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _state_leaves(state: Dict[bytes, bytes], height: int):
        """Merkle leaves: one height leaf + one canonical leaf per k/v.

        The height leaf's 0xffffffff prefix can never collide with a
        kv leaf (whose prefix is the 4-byte key length)."""
        from cometbft_tpu.crypto.proof_ops import kv_leaf

        leaves = [b"\xff\xff\xff\xff" + height.to_bytes(8, "big")]
        leaves += [kv_leaf(k, v) for k, v in sorted(state.items())]
        return leaves

    def _compute_app_hash(self, height: int) -> bytes:
        """Merkle root over the sorted state (PROVABLE: query with
        prove=True returns an inclusion proof chaining a k/v to this
        root, which the light proxy verifies against a trusted
        header's app_hash — light/rpc/client.go:117)."""
        from cometbft_tpu.crypto import merkle

        return merkle.hash_from_byte_slices(
            self._state_leaves(self.state, height)
        )

    @staticmethod
    def _parse_val_tx(tx: bytes):
        """val:base64pubkey!power[!nonce] -> (pubkey bytes, power).

        The optional trailing nonce is ignored by the app but makes
        repeat rotations of the SAME validator (out at epoch e, back
        in at e+2, out again at e+5 — routine under committee
        re-election) produce distinct tx bytes, so the mempool's
        replay-protection cache can never swallow a later epoch's
        change as a duplicate of an earlier one."""
        if not tx.startswith(VALIDATOR_PREFIX):
            return None
        try:
            body = tx[len(VALIDATOR_PREFIX):].decode()
            parts = body.split("!")
            if len(parts) < 2:
                raise ValueError("missing power")
            power = int(parts[1])
            if power < 0:
                # update_with_change_set rejects negative power — a
                # cheap tx must not reach apply_block as a chain-
                # halting update; reject it at CheckTx/ProcessProposal
                # like any other malformed val tx
                raise ValueError("negative power")
            return base64.b64decode(parts[0]), power
        except Exception:
            raise ValueError(f"malformed validator tx: {tx!r}")

    # -- ABCI ----------------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="kvstore-tpu-0.1",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return abci.ResponseInitChain(app_hash=self._compute_app_hash(0))

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        tx = req.tx
        if tx.startswith(VALIDATOR_PREFIX):
            try:
                self._parse_val_tx(tx)
            except ValueError as e:
                return abci.ResponseCheckTx(code=1, log=str(e))
            return abci.ResponseCheckTx()
        # key=value or bare bytes (key == value), kvstore.go:116
        return abci.ResponseCheckTx()

    def process_proposal(
        self, req: abci.RequestProcessProposal
    ) -> abci.ResponseProcessProposal:
        """Reject blocks carrying malformed validator txs (the reference
        kvstore validates in ProcessProposal so byzantine proposals never
        reach FinalizeBlock)."""
        for tx in req.txs:
            if tx.startswith(VALIDATOR_PREFIX):
                try:
                    self._parse_val_tx(tx)
                except ValueError:
                    return abci.ResponseProcessProposal(
                        status=abci.PROCESS_PROPOSAL_REJECT
                    )
        return abci.ResponseProcessProposal()

    def finalize_block(
        self, req: abci.RequestFinalizeBlock
    ) -> abci.ResponseFinalizeBlock:
        self.staged = dict(self.state)
        # keyed by pubkey, LAST tx wins (the reference kvstore
        # accumulates ValUpdates in a map too): two rotations of the
        # same validator landing in one block — out in epoch k, back
        # in at k+1 — must collapse to ONE update, because
        # update_with_change_set rejects duplicate addresses and that
        # rejection would halt the chain on every honest node
        val_updates: dict = {}
        results = []
        for tx in req.txs:
            if tx.startswith(VALIDATOR_PREFIX):
                # malformed val txs get a non-OK result; raising here would
                # abort apply_block on every honest node and halt the chain
                try:
                    pub, power = self._parse_val_tx(tx)
                except ValueError as e:
                    results.append(abci.ExecTxResult(code=1, log=str(e)))
                    continue
                val_updates[pub] = abci.ValidatorUpdate(pub, power)
                results.append(abci.ExecTxResult())
                continue
            if b"=" in tx:
                k, v = tx.split(b"=", 1)
            else:
                k = v = tx
            self.staged[k] = v
            results.append(abci.ExecTxResult(data=v))
        self.val_updates = list(val_updates.values())
        self._pending_height = req.height
        self._pending_hash = self._computed_staged_hash(req.height)
        return abci.ResponseFinalizeBlock(
            tx_results=results,
            validator_updates=list(self.val_updates),
            app_hash=self._pending_hash,
        )

    def _computed_staged_hash(self, height: int) -> bytes:
        saved, self.state = self.state, self.staged
        try:
            return self._compute_app_hash(height)
        finally:
            self.state = saved

    def commit(self) -> abci.ResponseCommit:
        self.state = self.staged
        self.height = self._pending_height
        self.app_hash = self._pending_hash
        self._committed = (dict(self.state), self.height)
        self._maybe_snapshot()
        return abci.ResponseCommit()

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        # one atomic read: commit() swaps in a new tuple, so (state,
        # height) can never be torn across a concurrent commit — a torn
        # pair would make the returned proof unverifiable
        state, height = self._snapshot()
        v = state.get(req.data, b"")
        resp = abci.ResponseQuery(
            key=req.data, value=v, height=height,
            log="exists" if v else "does not exist",
        )
        if req.prove and v:
            from cometbft_tpu.crypto import merkle
            from cometbft_tpu.crypto.proof_ops import make_kv_op

            leaves = self._state_leaves(state, height)
            idx = 1 + sorted(state).index(req.data)
            _, proofs = merkle.proofs_from_byte_slices(leaves)
            resp.proof_ops = [make_kv_op(req.data, proofs[idx])]
        return resp

    def _snapshot(self):
        snap = getattr(self, "_committed", None)
        if snap is None:
            return dict(self.state), self.height
        return snap

    # -- state-sync snapshots (kvstore.go snapshot support) -----------------

    SNAPSHOT_CHUNK_SIZE = 64 * 1024

    def enable_snapshots(self, interval: int) -> None:
        """Take a snapshot every `interval` heights (config
        [statesync] snapshot-interval analog)."""
        self._snapshot_interval = interval
        self._snapshots = {}

    def _maybe_snapshot(self) -> None:
        interval = getattr(self, "_snapshot_interval", 0)
        if not interval or self.height == 0 or self.height % interval:
            return
        doc = json.dumps({
            "height": self.height,
            "app_hash": self.app_hash.hex(),
            "state": {k.hex(): v.hex() for k, v in self.state.items()},
        }).encode()
        chunks = [doc[i:i + self.SNAPSHOT_CHUNK_SIZE]
                  for i in range(0, max(len(doc), 1),
                                 self.SNAPSHOT_CHUNK_SIZE)]
        self._snapshots[self.height] = chunks
        # keep the most recent few (kvstore keeps a bounded set)
        for h in sorted(self._snapshots)[:-3]:
            del self._snapshots[h]

    def list_snapshots(self):
        out = []
        for h, chunks in sorted(getattr(self, "_snapshots", {}).items()):
            out.append(abci.Snapshot(
                height=h, format=1, chunks=len(chunks),
                hash=hashlib.sha256(b"".join(chunks)).digest(),
            ))
        return out

    def offer_snapshot(self, snapshot: abci.Snapshot) -> bool:
        if snapshot.format != 1 or snapshot.chunks < 1:
            return False
        self._restore = {"snapshot": snapshot, "chunks": [None] * snapshot.chunks}
        return True

    def load_snapshot_chunk(self, height, fmt, chunk) -> bytes:
        chunks = getattr(self, "_snapshots", {}).get(height)
        if chunks is None or fmt != 1 or not 0 <= chunk < len(chunks):
            return b""
        return chunks[chunk]

    def apply_snapshot_chunk(self, index, chunk, sender):
        r = getattr(self, "_restore", None)
        if r is None or not 0 <= index < len(r["chunks"]):
            return False
        r["chunks"][index] = chunk
        if any(c is None for c in r["chunks"]):
            return True
        blob = b"".join(r["chunks"])
        if hashlib.sha256(blob).digest() != r["snapshot"].hash:
            # the hash covers the WHOLE snapshot, so the bad chunk can't
            # be identified — ask the engine to refetch everything and
            # keep the restore session open (RETRY_SNAPSHOT semantics)
            n = len(r["chunks"])
            r["chunks"] = [None] * n
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY_SNAPSHOT,
                refetch_chunks=list(range(n)),
            )
        doc = json.loads(blob.decode())
        self.state = {bytes.fromhex(k): bytes.fromhex(v)
                      for k, v in doc["state"].items()}
        self.height = doc["height"]
        self.app_hash = bytes.fromhex(doc["app_hash"])
        self.staged = dict(self.state)
        self._committed = (dict(self.state), self.height)
        self._restore = None
        return True
