"""ABCI socket server + client: run the application out of process.

Reference: abci/server/socket_server.go + abci/client/socket_client.go —
a length-prefixed request/response stream over TCP (or unix) sockets;
the node side exposes the same Application interface so BlockExecutor /
Mempool don't know whether the app is in-process.

Wire format here: 4-byte big-endian length + JSON body (bytes fields
base64). The reference's protobuf framing is an implementation detail of
its Go codebase, not a consensus-critical encoding; what matters is the
14-method surface and the strict request/response ordering, which the
client preserves with a connection mutex exactly like the reference's
socket client.
"""
from __future__ import annotations

import base64
import dataclasses
import json
import socket
import struct
import threading
from typing import Any, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs.service import BaseService


def _enc(obj: Any):
    if dataclasses.is_dataclass(obj):
        return {k: _enc(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, (bytes, bytearray)):
        return {"__b": base64.b64encode(bytes(obj)).decode()}
    if isinstance(obj, (list, tuple)):
        return [_enc(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    return obj


def _dec(obj: Any):
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b"}:
            return base64.b64decode(obj["__b"])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(v) for v in obj]
    return obj


def _send_msg(conn: socket.socket, doc: dict) -> None:
    body = json.dumps(doc).encode()
    conn.sendall(struct.pack(">I", len(body)) + body)


def _recv_msg(conn: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = conn.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    body = b""
    while len(body) < n:
        chunk = conn.recv(min(1 << 20, n - len(body)))
        if not chunk:
            return None
        body += chunk
    return json.loads(body.decode())


# request constructor + response type per method
_METHODS = {
    "info": (abci.RequestInfo, abci.ResponseInfo),
    "init_chain": (abci.RequestInitChain, abci.ResponseInitChain),
    "check_tx": (abci.RequestCheckTx, abci.ResponseCheckTx),
    "prepare_proposal": (abci.RequestPrepareProposal,
                         abci.ResponsePrepareProposal),
    "process_proposal": (abci.RequestProcessProposal,
                         abci.ResponseProcessProposal),
    "finalize_block": (abci.RequestFinalizeBlock,
                       abci.ResponseFinalizeBlock),
    "commit": (None, abci.ResponseCommit),
    "query": (abci.RequestQuery, abci.ResponseQuery),
    "extend_vote": (abci.RequestExtendVote, abci.ResponseExtendVote),
    "verify_vote_extension": (abci.RequestVerifyVoteExtension,
                              abci.ResponseVerifyVoteExtension),
}

# plain-argument methods (the snapshot family takes positional args,
# not request dataclasses): name -> (args_rebuild, resp_rebuild)
_ARG_METHODS = {
    "list_snapshots": (None,
                       lambda r: [abci.Snapshot(**s) for s in r]),
    "offer_snapshot": (lambda a: [abci.Snapshot(**a[0])], None),
    "load_snapshot_chunk": (None, None),
    "apply_snapshot_chunk": (
        None,
        lambda r: r if isinstance(r, bool)
        else abci.ResponseApplySnapshotChunk(**r),
    ),
}


def _rebuild(cls, doc):
    """Dataclass from decoded dict, recursing into typed list fields."""
    if cls is abci.ResponseQuery:
        from cometbft_tpu.crypto.proof_ops import ProofOp

        ops = doc.pop("proof_ops", None) or []
        resp = abci.ResponseQuery(**doc)
        resp.proof_ops = [ProofOp(**o) for o in ops]
        return resp
    if cls is abci.ResponseFinalizeBlock:
        return abci.ResponseFinalizeBlock(
            tx_results=[abci.ExecTxResult(**r) for r in doc["tx_results"]],
            validator_updates=[
                abci.ValidatorUpdate(**u) for u in doc["validator_updates"]
            ],
            app_hash=doc["app_hash"],
        )
    if cls is abci.ResponseInitChain:
        return abci.ResponseInitChain(
            validators=[abci.ValidatorUpdate(**u)
                        for u in doc.get("validators", [])],
            app_hash=doc.get("app_hash", b""),
        )
    if cls is abci.RequestPrepareProposal:
        llc = doc.get("local_last_commit")
        return abci.RequestPrepareProposal(
            max_tx_bytes=doc.get("max_tx_bytes", 0),
            txs=doc.get("txs", []),
            height=doc.get("height", 0),
            proposer_address=doc.get("proposer_address", b""),
            local_last_commit=(abci.ExtendedCommitInfo(
                round=llc["round"],
                votes=[abci.ExtendedVoteInfo(**v) for v in llc["votes"]],
            ) if llc else None),
        )
    if cls is abci.RequestFinalizeBlock:
        dlc = doc.get("decided_last_commit")
        return abci.RequestFinalizeBlock(
            txs=doc.get("txs", []),
            hash=doc.get("hash", b""),
            height=doc.get("height", 0),
            proposer_address=doc.get("proposer_address", b""),
            time_seconds=doc.get("time_seconds", 0),
            decided_last_commit=(abci.CommitInfo(
                round=dlc["round"],
                votes=[abci.VoteInfo(**v) for v in dlc["votes"]],
            ) if dlc else None),
            misbehavior=[abci.Misbehavior(**m)
                         for m in doc.get("misbehavior", [])],
        )
    if cls is abci.RequestInitChain:
        return abci.RequestInitChain(
            time_seconds=doc.get("time_seconds", 0),
            chain_id=doc.get("chain_id", ""),
            validators=[abci.ValidatorUpdate(**u)
                        for u in doc.get("validators", [])],
            app_state_bytes=doc.get("app_state_bytes", b""),
            initial_height=doc.get("initial_height", 1),
        )
    return cls(**doc)


class ABCISocketServer(BaseService):
    """abci/server/socket_server.go: serve an Application over a socket."""

    def __init__(self, app: abci.Application, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__("ABCISocketServer")
        self.app = app
        self._listener = socket.create_server((host, port))
        self.addr = self._listener.getsockname()
        self._threads = []
        # one request at a time across ALL connections: ABCI apps are
        # not required to be concurrency-safe (local_client.go mutex)
        self._app_lock = threading.Lock()

    def on_start(self) -> None:
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="abci-accept")
        t.start()
        self._threads.append(t)

    def on_stop(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self.is_running():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="abci-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while self.is_running():
                try:
                    req = _recv_msg(conn)
                except OSError:
                    return
                if req is None:
                    return
                method = req.get("m")
                spec = _METHODS.get(method)
                argspec = _ARG_METHODS.get(method)
                if spec is None and argspec is None:
                    _send_msg(conn, {"err": f"unknown method {method!r}"})
                    continue
                try:
                    with self._app_lock:
                        fn = getattr(self.app, method)
                        if argspec is not None:
                            args = _dec(req.get("a", []))
                            if argspec[0] is not None:
                                args = argspec[0](args)
                            resp = fn(*args)
                        elif spec[0] is None:
                            resp = fn()
                        else:
                            resp = fn(_rebuild(spec[0], _dec(req["q"])))
                    _send_msg(conn, {"r": _enc(resp)})
                except Exception as e:  # noqa: BLE001 - surface app error
                    _send_msg(conn, {"err": repr(e)})


class ABCISocketClient(abci.Application):
    """abci/client/socket_client.go: an Application proxy over a socket.

    Implements the same interface the in-process app does, so Node /
    BlockExecutor / Mempool are agnostic to the process boundary
    (proxy.AppConns' role; all four logical connections share this one
    socket under a mutex, like the reference's local client)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._conn = socket.create_connection((host, port),
                                              timeout=timeout)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass

    def _call(self, method: str, req=None):
        req_cls, resp_cls = _METHODS[method]
        doc = {"m": method}
        if req_cls is not None:
            doc["q"] = _enc(req)
        with self._lock:
            _send_msg(self._conn, doc)
            resp = _recv_msg(self._conn)
        if resp is None:
            raise ConnectionError("abci socket closed")
        if "err" in resp:
            raise RuntimeError(f"abci app error: {resp['err']}")
        return _rebuild(resp_cls, _dec(resp["r"]))

    def info(self, req):
        return self._call("info", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def prepare_proposal(self, req):
        return self._call("prepare_proposal", req)

    def process_proposal(self, req):
        return self._call("process_proposal", req)

    def finalize_block(self, req):
        return self._call("finalize_block", req)

    def commit(self):
        return self._call("commit")

    def query(self, req):
        return self._call("query", req)

    def extend_vote(self, req):
        return self._call("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._call("verify_vote_extension", req)

    # snapshot family: positional-arg wire form (_ARG_METHODS)
    def _call_args(self, method: str, *args):
        resp_fix = _ARG_METHODS[method][1]
        with self._lock:
            _send_msg(self._conn, {"m": method, "a": _enc(list(args))})
            resp = _recv_msg(self._conn)
        if resp is None:
            raise ConnectionError("abci socket closed")
        if "err" in resp:
            raise RuntimeError(f"abci app error: {resp['err']}")
        r = _dec(resp["r"])
        return resp_fix(r) if resp_fix else r

    def list_snapshots(self):
        return self._call_args("list_snapshots")

    def offer_snapshot(self, snapshot):
        return self._call_args("offer_snapshot", snapshot)

    def load_snapshot_chunk(self, height, fmt, chunk):
        return self._call_args("load_snapshot_chunk", height, fmt, chunk)

    def apply_snapshot_chunk(self, index, chunk, sender):
        return self._call_args("apply_snapshot_chunk", index, chunk,
                               sender)
