"""AppConns: the four logical ABCI connections.

Reference: proxy/multi_app_conn.go — consensus, mempool, query, and
snapshot each get their own logical connection to the application so a
slow CheckTx cannot serialize behind FinalizeBlock at the CLIENT; the
in-process application itself is still guarded by one mutex
(abci/client/local_client.go — ABCI apps need not be concurrency-safe).

Two constructions:
  * in_process(app): four facades over the same Application sharing one
    RLock (local client semantics).
  * socket(host, port): four independent socket clients to one ABCI
    server — requests on different conns pipeline on the wire; the
    server's own app lock provides the final serialization.
"""
from __future__ import annotations

import threading

from cometbft_tpu.abci import types as abci

_FORWARDED = (
    "info", "init_chain", "check_tx", "prepare_proposal",
    "process_proposal", "finalize_block", "commit", "query",
    "extend_vote", "verify_vote_extension", "list_snapshots",
    "offer_snapshot", "load_snapshot_chunk", "apply_snapshot_chunk",
)


class _LockedConn:
    """One logical connection over a shared app + mutex
    (local_client.go's global-mutex model)."""

    def __init__(self, app: abci.Application, lock: threading.RLock):
        self._app = app
        self._lock = lock

    def __getattr__(self, name):
        if name not in _FORWARDED:
            raise AttributeError(name)
        fn = getattr(self._app, name)
        lock = self._lock

        def call(*args, **kwargs):
            with lock:
                return fn(*args, **kwargs)

        return call


class AppConns:
    """proxy.AppConns: .consensus / .mempool / .query / .snapshot."""

    def __init__(self, consensus, mempool, query, snapshot):
        self.consensus = consensus
        self.mempool = mempool
        self.query = query
        self.snapshot = snapshot

    @classmethod
    def in_process(cls, app: abci.Application) -> "AppConns":
        lock = threading.RLock()
        return cls(*(_LockedConn(app, lock) for _ in range(4)))

    @classmethod
    def socket(cls, host: str, port: int, timeout: float = 30.0
               ) -> "AppConns":
        from cometbft_tpu.abci.server import ABCISocketClient

        return cls(*(ABCISocketClient(host, port, timeout=timeout)
                     for _ in range(4)))

    @classmethod
    def grpc(cls, host: str, port: int, timeout: float = 30.0
             ) -> "AppConns":
        """Four logical conns over ONE multiplexed gRPC channel
        (grpc_client.go: HTTP/2 streams replace the socket client's
        per-connection ordering mutex)."""
        from cometbft_tpu.abci.grpc import ABCIGRPCClient

        client = ABCIGRPCClient(host, port, timeout=timeout)
        conns = cls(client, client, client, client)
        conns._grpc_client = client
        return conns

    @classmethod
    def from_addr(cls, addr: str, timeout: float = 30.0) -> "AppConns":
        """proxy_app address -> AppConns: ``tcp://h:p`` or ``h:p``
        (socket server), ``grpc://h:p`` (gRPC server) — the
        proxy.DefaultClientCreator dispatch (proxy/client.go)."""
        scheme, sep, rest = addr.partition("://")
        if not sep:
            scheme, rest = "tcp", addr
        host, _, port = rest.rpartition(":")
        host = host or "127.0.0.1"
        if scheme == "grpc":
            return cls.grpc(host, int(port), timeout=timeout)
        if scheme in ("tcp", "socket"):
            return cls.socket(host, int(port), timeout=timeout)
        raise ValueError(f"unknown proxy_app scheme {scheme!r}")

    def close(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            close = getattr(c, "close", None)
            if close is not None:
                close()
