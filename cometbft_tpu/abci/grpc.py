"""ABCI over gRPC: run the application out of process on HTTP/2.

Reference: abci/server/grpc_server.go + abci/client/grpc_client.go —
the third app-connection mode next to in-process and socket. The gRPC
mode's value over the socket client (which serializes every call under
one connection mutex, socket_client.go's ordering contract) is true
per-call multiplexing: HTTP/2 streams let CheckTx traffic, consensus
FinalizeBlock and snapshot serving proceed concurrently, which is why
the reference recommends it for apps that parallelize internally
(grpc_client.go:20-28).

Transport: real gRPC (grpcio) with a generic service handler — one
unary-unary method per ABCI method under the service name
``cometbft.abci.v1.ABCI``. Message bodies reuse the framed-JSON codec
of abci/server.py (base64 bytes fields); the reference's protobuf
payloads are a Go implementation detail, not a consensus encoding —
what matters is the 14-method surface, kept identical across all three
modes (abci/types.py Application).
"""
from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.server import (
    _ARG_METHODS,
    _METHODS,
    _dec,
    _enc,
    _rebuild,
)
from cometbft_tpu.libs.service import BaseService

SERVICE = "cometbft.abci.v1.ABCI"


def _ident(b: bytes) -> bytes:
    return b


class ABCIGRPCServer(BaseService):
    """abci/server/grpc_server.go: serve an Application over gRPC."""

    def __init__(self, app: abci.Application, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 8,
                 serialize_app: bool = True):
        super().__init__("ABCIGRPCServer")
        self.app = app
        self._host, self._port = host, port
        self._max_workers = max_workers
        self._server = None
        self.addr = (host, port)
        # ABCI applications need not be concurrency-safe
        # (abci/client/local_client.go's global-mutex model; the socket
        # server holds the same lock). Requests still multiplex on the
        # wire; a thread-safe app may pass serialize_app=False to let
        # handler threads run it concurrently.
        self._app_lock = threading.RLock() if serialize_app else None

    def _handler(self, method: str):
        app = self.app

        def call(request: bytes, context) -> bytes:
            import grpc

            try:
                doc = _dec(json.loads(request.decode()))
                import contextlib

                guard = (self._app_lock if self._app_lock is not None
                         else contextlib.nullcontext())
                with guard:
                    if method in _ARG_METHODS:
                        fix = _ARG_METHODS[method][0]
                        args = doc.get("a", [])
                        if fix:
                            args = fix(args)
                        r = getattr(app, method)(*args)
                    else:
                        req_cls, _ = _METHODS[method]
                        if req_cls is None:
                            r = getattr(app, method)()
                        else:
                            r = getattr(app, method)(
                                _rebuild(req_cls, doc["q"]))
                return json.dumps(_enc(r)).encode()
            except Exception as e:  # noqa: BLE001 - app errors -> status
                context.abort(grpc.StatusCode.INTERNAL,
                              f"abci app error: {e}")

        return call

    def on_start(self) -> None:
        import grpc

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers)
        )
        handlers = {}
        for m in list(_METHODS) + list(_ARG_METHODS):
            handlers[m] = grpc.unary_unary_rpc_method_handler(
                self._handler(m),
                request_deserializer=_ident,
                response_serializer=_ident,
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        port = self._server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        self.addr = (self._host, port)
        self._server.start()

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait()


class ABCIGRPCClient(abci.Application):
    """abci/client/grpc_client.go: an Application proxy over gRPC.

    Unlike ABCISocketClient there is NO connection mutex — gRPC
    multiplexes concurrent calls on one HTTP/2 channel, so the four
    logical AppConns issue requests in parallel (the reference grpc
    client's whole point)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        import grpc

        self._timeout = timeout
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._stubs = {
            m: self._channel.unary_unary(
                f"/{SERVICE}/{m}",
                request_serializer=_ident,
                response_deserializer=_ident,
            )
            for m in list(_METHODS) + list(_ARG_METHODS)
        }

    def wait_ready(self, timeout: float = 10.0) -> None:
        import grpc

        grpc.channel_ready_future(self._channel).result(timeout=timeout)

    def close(self) -> None:
        self._channel.close()

    def _call(self, method: str, req=None):
        _, resp_cls = _METHODS[method]
        doc = {"m": method}
        if req is not None:
            doc["q"] = _enc(req)
        body = self._stubs[method](
            json.dumps(doc).encode(), timeout=self._timeout
        )
        return _rebuild(resp_cls, _dec(json.loads(body.decode())))

    def info(self, req):
        return self._call("info", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def prepare_proposal(self, req):
        return self._call("prepare_proposal", req)

    def process_proposal(self, req):
        return self._call("process_proposal", req)

    def finalize_block(self, req):
        return self._call("finalize_block", req)

    def commit(self):
        return self._call("commit")

    def query(self, req):
        return self._call("query", req)

    def extend_vote(self, req):
        return self._call("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._call("verify_vote_extension", req)

    def _call_args(self, method: str, *args):
        resp_fix = _ARG_METHODS[method][1]
        body = self._stubs[method](
            json.dumps({"m": method, "a": _enc(list(args))}).encode(),
            timeout=self._timeout,
        )
        r = _dec(json.loads(body.decode()))
        return resp_fix(r) if resp_fix else r

    def list_snapshots(self):
        return self._call_args("list_snapshots")

    def offer_snapshot(self, snapshot):
        return self._call_args("offer_snapshot", snapshot)

    def load_snapshot_chunk(self, height, fmt, chunk):
        return self._call_args("load_snapshot_chunk", height, fmt, chunk)

    def apply_snapshot_chunk(self, index, chunk, sender):
        return self._call_args("apply_snapshot_chunk", index, chunk,
                               sender)
