"""loadtime: tx load generator + per-tx latency report.

Reference: test/loadtime — `load` stamps a timestamp into each tx
payload and drives broadcast_tx at a target rate (load/main.go via
tm-load-test); `report` recomputes per-tx latency from the block store
by subtracting the stamped time from the committing block's time
(report/report.go).
"""
from __future__ import annotations

import os
import statistics
import struct
import time
from dataclasses import dataclass
from typing import List, Optional

_MAGIC = b"loadtm01"
_HEADER = len(_MAGIC) + 8 + 8  # magic || seq(u64) || stamp_ns(u64)


def make_tx(seq: int, size: int = 64,
            stamp_ns: Optional[int] = None) -> bytes:
    """A load tx: magic || seq || wall-clock ns || padding
    (loadtime/payload proto analog, fixed binary layout)."""
    stamp = time.time_ns() if stamp_ns is None else stamp_ns
    body = _MAGIC + struct.pack(">QQ", seq, stamp)
    pad = max(0, size - len(body))
    return body + bytes((seq + i) & 0xFF for i in range(pad))


def parse_tx(tx: bytes):
    """(seq, stamp_ns) or None for non-load txs."""
    if len(tx) < _HEADER or not tx.startswith(_MAGIC):
        return None
    seq, stamp = struct.unpack(">QQ", tx[len(_MAGIC):_HEADER])
    return seq, stamp


def run_load(broadcast, rate: float, duration_s: float,
             size: int = 64) -> int:
    """Drive `broadcast(tx)` at ~rate tx/s for duration_s. Returns the
    number submitted. `broadcast` is any callable — an RPC client's
    broadcast_tx_sync or a node's broadcast_tx."""
    interval = 1.0 / rate if rate > 0 else 0.0
    t0 = time.monotonic()
    seq = 0
    while time.monotonic() - t0 < duration_s:
        broadcast(make_tx(seq, size))
        seq += 1
        next_at = t0 + seq * interval
        delay = next_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
    return seq


@dataclass
class LatencyReport:
    """report/report.go Report (subset)."""

    n_txs: int
    min_ms: float
    max_ms: float
    avg_ms: float
    p50_ms: float
    stddev_ms: float

    def __str__(self) -> str:
        return (f"{self.n_txs} txs  avg {self.avg_ms:.1f} ms  "
                f"p50 {self.p50_ms:.1f} ms  min {self.min_ms:.1f}  "
                f"max {self.max_ms:.1f}  stddev {self.stddev_ms:.1f}")


def report_from_blockstore(block_store) -> Optional[LatencyReport]:
    """Scan committed blocks for load txs; latency = block time -
    payload stamp (report/report.go:Generate)."""
    lat_ms: List[float] = []
    for h in range(max(1, block_store.base()),
                   block_store.height() + 1):
        blk = block_store.load_block(h)
        if blk is None:
            continue
        block_ns = (blk.header.time.seconds * 10**9
                    + blk.header.time.nanos)
        for tx in blk.data.txs:
            p = parse_tx(tx)
            if p is None:
                continue
            lat_ms.append((block_ns - p[1]) / 1e6)
    if not lat_ms:
        return None
    return LatencyReport(
        n_txs=len(lat_ms),
        min_ms=min(lat_ms),
        max_ms=max(lat_ms),
        avg_ms=statistics.fmean(lat_ms),
        p50_ms=statistics.median(lat_ms),
        stddev_ms=statistics.stdev(lat_ms) if len(lat_ms) > 1 else 0.0,
    )


def main(argv=None) -> int:
    """CLI: `loadtime load --rpc URL --rate R --duration D` and
    `loadtime report --data DIR`."""
    import argparse

    p = argparse.ArgumentParser(prog="loadtime")
    sub = p.add_subparsers(dest="cmd", required=True)
    q = sub.add_parser("load")
    q.add_argument("--rpc", required=True)
    q.add_argument("--rate", type=float, default=100.0)
    q.add_argument("--duration", type=float, default=10.0)
    q.add_argument("--size", type=int, default=64)
    q = sub.add_parser("report")
    q.add_argument("--data", required=True,
                   help="node data dir containing blockstore.db")
    args = p.parse_args(argv)
    if args.cmd == "load":
        from cometbft_tpu.rpc.client import HTTPClient

        http = HTTPClient(args.rpc)
        n = run_load(http.broadcast_tx_sync, args.rate, args.duration,
                     args.size)
        print(f"submitted {n} txs")
        return 0
    from cometbft_tpu.store.blockstore import BlockStore

    bs = BlockStore(os.path.join(args.data, "blockstore.db"))
    rep = report_from_blockstore(bs)
    print(rep if rep else "no load txs found")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
