"""Catch-up firehose: replay archival history as a streaming dataset.

Live blocksync (blocksync/reactor.py) is shaped by gossip: blocks
dribble in from peers, runs are short, and the valset is assumed
stable per run. Catch-up from an ARCHIVE is a different workload — the
history is already on disk (ours after statesync, or a donor's), so
the bottleneck is how fast commits can be packed, verified, and
applied. This engine treats that history like an input pipeline:

  * **Read-ahead.** Blocks are prefetched from the history source into
    a bounded buffer ahead of the replay cursor (``read_ahead`` deep),
    so store reads overlap verify/apply instead of serializing with
    them. The ``catchup.read_ahead`` failpoint sits on this seam.
  * **Maximal fused flushes.** Commit signatures are packed via
    ``validation.commit_packed_batch`` into cross-HEIGHT fused verify
    flushes (the StreamVerifier pipeline and its pinned staging pool),
    bounded only by ``max_run`` and valset-change boundaries.
  * **Boundary pre-scan + warm-ahead.** The buffer is scanned for
    ``validators_hash`` changes so epoch boundaries bound each fused
    segment exactly, and the moment a NEW next-valset becomes known
    (one height before the boundary) it is handed to the table warmer
    (verifyplane/warmer.py) — the epoch table builds AHEAD of the
    replay cursor, so the first flush after a rotation packs against a
    warm table instead of paying a cold build.
  * **Crash-resumable cursor.** A persisted :class:`CatchupCursor`
    (atomic JSON) records the verified high-water mark separately from
    the applied one. A kill mid-replay resumes without re-verifying a
    single already-applied block: heights at or below the verified
    mark skip signature verification entirely (they were verified
    against the same immutable commits before the crash), and heights
    at or below the applied state are never replayed at all.

Evidence rides the always-on :class:`CatchupLedger` — a bounded ring
of per-flush records on the LEDGER clock (virtual under simnet, so a
chaos soak's catch-up ledger replays byte-identically) served at
``/dump_catchup`` and diffed across rounds by tools/catchup_report.py.
A frozen ledger while catch-up is active fires the ``catchup_stall``
incident (libs/incidents.py).
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import incidents, tracing

fp.register("catchup.read_ahead",
            "catch-up history read-ahead seam (before each block is "
            "prefetched from the history source)")

# one fused verify segment: bounded like the live reactor's MAX_RUN so
# a verification failure localizes, and further bounded at valset
# boundaries (a segment never packs across two epochs)
MAX_RUN = 64

LEDGER_CAPACITY = 256


class CatchupError(Exception):
    pass


@dataclass
class CatchupJob:
    """One block's commit to verify — field-compatible with the
    pipeline's CommitJob (duck-typed on purpose: this module must not
    import blocksync/pipeline at module load, which pulls jax into
    host-only processes — the smoke bench and the simnet soak)."""

    vals: object
    block_id: object
    height: int
    commit: object
    chain_id: str


class HostCommitVerifier:
    """jax-free verify path: verify_commit_light per job on the host.
    The explicit choice for host-only runs (smoke bench, simnet soak,
    tier-1 tests) where importing the fused device pipeline is either
    forbidden or pointless."""

    def verify(self, jobs) -> List[Optional[Exception]]:
        from cometbft_tpu.types import validation as tv

        out: List[Optional[Exception]] = []
        for job in jobs:
            try:
                tv.verify_commit_light(job.chain_id, job.vals,
                                       job.block_id, job.height,
                                       job.commit, batch_fn=None)
                out.append(None)
            except tv.VerificationError as e:
                out.append(e)
        return out


class CatchupCursor:
    """Crash-resumable replay cursor, atomically persisted.

    ``verified`` is the signature-verification high-water mark;
    ``applied`` trails it (state application). Both are monotone. The
    file is written tmp+rename so a kill mid-save leaves the previous
    cursor intact — resume never trusts a torn write."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.verified = 0
        self.applied = 0
        self.resumed = False
        if path and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                self.verified = int(doc.get("verified", 0))
                self.applied = int(doc.get("applied", 0))
                self.resumed = True
            except (OSError, ValueError):
                pass  # corrupt cursor: resume conservatively from 0

    def save(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"verified": self.verified,
                       "applied": self.applied}, f)
        os.replace(tmp, self.path)

    def as_dict(self) -> dict:
        return {"verified": self.verified, "applied": self.applied,
                "resumed": self.resumed}


class CatchupLedger:
    """Always-on bounded ring of per-flush catch-up records.

    Every fused verify+apply segment appends one record; counters are
    cumulative for the engine run(s) feeding this ledger. All stamps
    ride the ledger clock (tracing.monotonic_ns) — byte-identical
    under simnet replay."""

    def __init__(self, capacity: int = LEDGER_CAPACITY):
        self._ring: deque = deque(maxlen=max(8, int(capacity)))
        self._lock = threading.Lock()
        self._seq = 0
        self.counters = {
            "flushes": 0, "blocks_applied": 0, "blocks_verified": 0,
            "blocks_skipped": 0, "sigs_verified": 0, "boundaries": 0,
            "warm_requests": 0, "resumes": 0,
        }

    def record(self, first: int, last: int, blocks: int, sigs: int,
               skipped: int, read_ms: float, verify_ms: float,
               apply_ms: float, boundary: bool, warmed: bool) -> dict:
        rec = {
            "seq": 0,  # patched under the lock
            "at_ms": round(tracing.monotonic_ns() / 1e6, 3),
            "first": first, "last": last, "blocks": blocks,
            "sigs": sigs, "skipped": skipped,
            "read_ms": round(read_ms, 3),
            "verify_ms": round(verify_ms, 3),
            "apply_ms": round(apply_ms, 3),
            "boundary": bool(boundary), "warmed": bool(warmed),
        }
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._ring.append(rec)
            c = self.counters
            c["flushes"] += 1
            c["blocks_applied"] += blocks
            c["blocks_verified"] += blocks - skipped
            c["blocks_skipped"] += skipped
            c["sigs_verified"] += sigs
            if boundary:
                c["boundaries"] += 1
            if warmed:
                c["warm_requests"] += 1
        return rec

    def note_resume(self) -> None:
        with self._lock:
            self.counters["resumes"] += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int = 8) -> List[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    def mark(self) -> tuple:
        with self._lock:
            return (id(self), self._seq)

    def advanced(self, mark: tuple) -> bool:
        return self.mark() != mark

    def summary(self) -> dict:
        with self._lock:
            recs = list(self._ring)
            c = dict(self.counters)
        out = dict(c)
        out["window_flushes"] = len(recs)
        if recs:
            span_ms = recs[-1]["at_ms"] - recs[0]["at_ms"]
            blocks = sum(r["blocks"] for r in recs)
            sigs = sum(r["sigs"] for r in recs)
            out["window_span_ms"] = round(span_ms, 3)
            if span_ms > 0:
                out["blocks_per_s"] = round(blocks / span_ms * 1000.0, 1)
                out["sigs_per_s"] = round(sigs / span_ms * 1000.0, 1)
            out["verify_ms_total"] = round(
                sum(r["verify_ms"] for r in recs), 3)
            out["apply_ms_total"] = round(
                sum(r["apply_ms"] for r in recs), 3)
            out["read_ms_total"] = round(
                sum(r["read_ms"] for r in recs), 3)
        return out


class StoreHistorySource:
    """History = a block store (ours post-statesync, or a donor's).

    ``load(h)`` returns ``(block, commit_for_h)`` — the commit comes
    from h+1's LastCommit with a seen-commit fallback at the tip
    (store/blockstore.py load_block_commit)."""

    def __init__(self, block_store):
        self.store = block_store

    def base(self) -> int:
        return self.store.base()

    def tip(self) -> int:
        return self.store.height()

    def load(self, h: int) -> Tuple[object, object]:
        blk = self.store.load_block(h)
        if blk is None:
            raise CatchupError(f"history missing block {h}")
        commit = self.store.load_block_commit(h)
        if commit is None:
            raise CatchupError(f"history missing commit for height {h}")
        return blk, commit


class CatchupEngine:
    """Drive state from ``state.last_block_height`` to the history tip.

    ``source`` is any object with ``tip()``/``load(h)`` (see
    :class:`StoreHistorySource`); ``apply_fn(state, block, commit) ->
    state`` applies one verified block (defaults to the execution
    stack when ``block_exec`` is given, mirroring the live reactor's
    save -> validate -> apply sequence). ``verifier`` is any object
    with ``verify(jobs)``: the pipeline's StreamVerifier for fused
    device flushes through the pinned staging pool (the default —
    built lazily so the import only happens on nodes that verify), or
    :class:`HostCommitVerifier` for jax-free host runs."""

    def __init__(self, source, state, *,
                 apply_fn: Optional[Callable] = None,
                 block_exec=None, block_store=None,
                 verifier=None,
                 cursor_path: Optional[str] = None,
                 read_ahead: int = 128, max_run: int = MAX_RUN,
                 warm_ahead: bool = True, warmer=None,
                 ledger: Optional[CatchupLedger] = None):
        if apply_fn is None and block_exec is None:
            raise ValueError("need apply_fn or block_exec")
        self.source = source
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.apply_fn = apply_fn or self._apply_via_exec
        if verifier is None:
            from cometbft_tpu.blocksync.pipeline import (
                make_stream_verifier,
            )

            verifier = make_stream_verifier()
        self.verifier = verifier
        self.cursor = CatchupCursor(cursor_path)
        self.read_ahead = max(1, int(read_ahead))
        self.max_run = max(1, int(max_run))
        self.warm_ahead = bool(warm_ahead)
        self.warmer = warmer
        # explicit None test: an EMPTY caller ledger is falsy (__len__)
        # but must still be the one the run records into
        self.ledger = ledger if ledger is not None else CatchupLedger()
        self._buf: deque = deque()  # (height, block, commit), ordered
        self._next_read = 0
        self._warmed_hash: Optional[bytes] = None
        if self.cursor.resumed:
            self.ledger.note_resume()

    # -- default apply path (the live reactor's sequence) ------------------

    def _apply_via_exec(self, state, block, commit):
        self.block_exec.validate_block(state, block)
        return self.block_exec.apply_block(state, block.block_id(),
                                           block)

    # -- the loop ----------------------------------------------------------

    def run(self, until: Optional[int] = None):
        """Replay to the history tip (or ``until``); returns the final
        state. Raises :class:`CatchupError` on a verification or
        history gap — and lets a failpoint crash propagate with the
        cursor already persisted, which is the whole point."""
        tip = self.source.tip() if until is None else int(until)
        start = self.state.last_block_height
        self._next_read = max(self._next_read, start + 1)
        if self.ledger is not None:
            _install_ledger(self.ledger)
        incidents.note_catchup(True)
        try:
            with tracing.span("catchup.run", cat="catchup",
                              from_height=start, to_height=tip):
                while self.state.last_block_height < tip:
                    self._step(tip)
        finally:
            incidents.note_catchup(False)
            self.cursor.save()
        return self.state

    def _refill(self, tip: int) -> float:
        # drop anything the cursor already passed (a resumed engine's
        # buffer starts empty, but a retried run may hold stale heads)
        h = self.state.last_block_height
        while self._buf and self._buf[0][0] <= h:
            self._buf.popleft()
        t0 = tracing.monotonic_ns()
        while len(self._buf) < self.read_ahead and self._next_read <= tip:
            fp.fail_point("catchup.read_ahead")
            blk, commit = self.source.load(self._next_read)
            self._buf.append((self._next_read, blk, commit))
            self._next_read += 1
        return (tracing.monotonic_ns() - t0) / 1e6

    def _step(self, tip: int) -> None:
        read_ms = self._refill(tip)
        if not self._buf:
            raise CatchupError(
                f"history exhausted at {self.state.last_block_height} "
                f"before tip {tip}"
            )
        # pre-scan: one fused segment = consecutive buffered blocks
        # under the CURRENT valset, bounded at the first hash change
        vals = self.state.validators
        vhash = vals.hash()
        seg: List[tuple] = []
        boundary = False
        for (h, blk, commit) in self._buf:
            if blk.header.validators_hash != vhash:
                boundary = True
                break
            seg.append((h, blk, commit))
            if len(seg) >= self.max_run:
                break
        if not seg:
            h0, blk0, _ = self._buf[0]
            raise CatchupError(
                f"block {h0} validators_hash does not match the state "
                f"valset at {self.state.last_block_height} — corrupt "
                f"history or wrong resume state"
            )
        # verify: one cross-height fused flush, skipping heights the
        # persisted cursor already verified (resume re-verifies ZERO)
        jobs = [CatchupJob(vals=vals, block_id=blk.block_id(),
                           height=h, commit=commit,
                           chain_id=self.state.chain_id)
                for (h, blk, commit) in seg
                if h > self.cursor.verified]
        skipped = len(seg) - len(jobs)
        sigs = 0
        t0 = tracing.monotonic_ns()
        if jobs:
            with tracing.span("catchup.verify", cat="catchup",
                              blocks=len(jobs),
                              from_height=jobs[0].height):
                errs = self.verifier.verify(jobs)
            for job, err in zip(jobs, errs):
                if err is not None:
                    raise CatchupError(
                        f"commit verification failed at height "
                        f"{job.height}: {err}"
                    )
            sigs = sum(
                sum(1 for s in job.commit.signatures
                    if getattr(s, "signature", None))
                for job in jobs)
            self.cursor.verified = max(self.cursor.verified, seg[-1][0])
        verify_ms = (tracing.monotonic_ns() - t0) / 1e6
        # apply in order; warm-ahead fires the moment the next epoch's
        # valset becomes known (state.next_validators changes), which
        # is one height BEFORE the boundary the pre-scan found
        warmed = False
        t0 = tracing.monotonic_ns()
        for (h, blk, commit) in seg:
            if self.block_store is not None:
                self.block_store.save_block(blk, commit)
            self.state = self.apply_fn(self.state, blk, commit)
            if self.warm_ahead and self._maybe_warm_ahead():
                warmed = True
            self._buf.popleft()
        apply_ms = (tracing.monotonic_ns() - t0) / 1e6
        self.cursor.applied = self.state.last_block_height
        self.cursor.save()
        self.ledger.record(
            first=seg[0][0], last=seg[-1][0], blocks=len(seg),
            sigs=sigs, skipped=skipped, read_ms=read_ms,
            verify_ms=verify_ms, apply_ms=apply_ms,
            boundary=boundary, warmed=warmed,
        )
        incidents.note_catchup(True)  # progress: re-arm the stall watch

    def _maybe_warm_ahead(self) -> bool:
        nv = self.state.next_validators
        try:
            nh = nv.hash()
        except Exception:  # noqa: BLE001 - exotic test valsets
            return False
        if nh == self.state.validators.hash() or nh == self._warmed_hash:
            return False
        self._warmed_hash = nh
        w = self.warmer
        if w is None:
            from cometbft_tpu.verifyplane import warmer as warmer_mod

            w = warmer_mod.global_warmer()
        if w is None:
            return False
        w.request_valset(nv, chain_id=self.state.chain_id)
        return True


# --------------------------------------------------------------------------
# the process-global ledger: whichever engine ran last owns the dump
# (the verify plane's _GLOBAL/_LAST discipline) — /dump_catchup and the
# incident snapshot tail read through these
# --------------------------------------------------------------------------

_GLOBAL: Optional[CatchupLedger] = None
_LAST: Optional[CatchupLedger] = None


def _install_ledger(led: CatchupLedger) -> None:
    global _GLOBAL, _LAST
    _GLOBAL = led
    _LAST = led


def set_global_ledger(led: Optional[CatchupLedger]) -> None:
    global _GLOBAL, _LAST
    if led is not None:
        _LAST = led
    _GLOBAL = led


def global_ledger() -> Optional[CatchupLedger]:
    return _GLOBAL or _LAST


def ledger_tail(n: int = 8) -> List[dict]:
    led = global_ledger()
    return [] if led is None else led.tail(n)


def dump_catchup() -> dict:
    """The /dump_catchup document."""
    led = global_ledger()
    if led is None:
        return {"records": [], "summary": {}, "counters": {}}
    return {"records": led.records(), "summary": led.summary(),
            "counters": dict(led.counters)}
