"""Blocksync reactor: catch up by streaming historical blocks through the
fused batch verifier, then hand off to consensus.

Reference: blocksync/reactor.go — poolRoutine (:286) peeks consecutive
blocks, verifies the first via the second's LastCommit
(`VerifyCommitLight`, :463), applies through the BlockExecutor (:513),
bans peers serving bad blocks (:480-496), switches to consensus when
caught up (:391-401).

TPU restructuring: instead of one VerifyCommitLight per block, a RUN of
consecutive ready blocks is verified in one fused multi-commit device
pass (pipeline.StreamVerifier). Validator-set changes mid-run are
handled by re-verifying from the height where the set changed — the
optimistic batch is correct whenever the set is stable, which is the
overwhelmingly common case in replay."""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from cometbft_tpu.blocksync.pipeline import CommitJob, StreamVerifier
from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import State
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types.block import Block

MAX_RUN = 64  # blocks fused per device pass (64 x 1k sigs fills a bucket)

fp.register("blocksync.process",
            "a run of verified-ready blocks about to be processed "
            "(raise = transient local verify/apply fault; the loop "
            "retries without banning the serving peers)")


class BlocksyncReactor(BaseService):
    def __init__(
        self,
        state: State,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        stream_verifier: Optional[StreamVerifier] = None,
        on_caught_up: Optional[Callable[[State], None]] = None,
        poll_interval: float = 0.02,
    ):
        super().__init__("BlocksyncReactor")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.pool = BlockPool(state.last_block_height + 1)
        self.verifier = stream_verifier or StreamVerifier(use_pallas=False)
        self.on_caught_up = on_caught_up
        self.poll_interval = poll_interval
        self.banned_peers: List[str] = []
        self.on_ban = None  # p2p hook: disconnect a banned peer
        # If no peer is ahead of us after this many seconds, declare
        # caught-up (reactor.go:391's switch-to-consensus timer): a fresh
        # network where everyone is at genesis must not wait forever.
        self.grace = 3.0
        self._thread: Optional[threading.Thread] = None

    # -- service -----------------------------------------------------------

    def on_start(self) -> None:
        self._thread = threading.Thread(
            target=self._pool_routine, daemon=True, name="blocksync"
        )
        self._thread.start()

    def on_stop(self) -> None:
        if self._thread:
            self._thread.join(timeout=5)

    # -- peer API (wired by p2p or tests) ----------------------------------

    def add_peer(self, peer_id: str, height: int,
                 request: Callable[[int], None]) -> None:
        self.pool.set_peer_range(peer_id, height, request)

    def receive_block(self, peer_id: str, block: Block) -> None:
        if tracing.enabled():
            tracing.instant("blocksync.block_received", cat="blocksync",
                            height=block.header.height, peer=peer_id)
        self.pool.add_block(peer_id, block)

    # -- the sync loop -----------------------------------------------------

    def _pool_routine(self) -> None:
        """poolRoutine (reactor.go:286)."""
        started = time.time()
        peerless_since = started
        while self.is_running():
            self.pool.make_requests()
            elapsed = time.time() - started
            if self.pool.num_peers() > 0:
                peerless_since = time.time()
                # peers known: caught up when nobody is ahead (after a
                # short grace so statuses can land)
                done = self.pool.is_caught_up() or (
                    elapsed > self.grace
                    and self.pool.max_peer_height()
                    <= self.state.last_block_height
                )
            else:
                # zero peers: wait longer before giving up — declaring
                # caught-up on an empty pool mid-handshake would strand
                # a lagging node in consensus (the lonely-node arm keeps
                # single-validator operation bootable). The clock runs
                # from when peers VANISHED, not reactor start (timeout
                # eviction can empty a mid-sync pool), and a node that
                # ever saw a higher advertised tip must not declare
                # done below it — wait for peers to re-register via
                # their next status instead.
                done = (
                    time.time() - peerless_since > max(self.grace, 10.0)
                    and self.state.last_block_height
                    >= self.pool.max_seen_height() - 1
                )
            if done:
                if self.on_caught_up:
                    self.on_caught_up(self.state)
                return
            # need blocks h..h+k AND h+k+1 (its LastCommit seals h+k)
            run = self.pool.peek_blocks(MAX_RUN + 1)
            if len(run) < 2:
                time.sleep(self.poll_interval)
                continue
            try:
                self._process_run(run)
            except Exception:  # noqa: BLE001 - local store/app failure
                import traceback

                traceback.print_exc()
                time.sleep(max(self.poll_interval, 0.25))  # retry, no ban

    def _process_run(self, run: List[Block]) -> None:
        """Verify blocks run[0..n-2] using each successor's LastCommit in
        one fused pass, then apply them in order."""
        fp.fail_point("blocksync.process")
        n = len(run) - 1
        jobs = []
        for i in range(n):
            first, second = run[i], run[i + 1]
            jobs.append(CommitJob(
                vals=self.state.validators,  # optimistic: stable valset
                block_id=first.block_id(),
                height=first.header.height,
                commit=second.last_commit,
                chain_id=self.state.chain_id,
            ))
        with tracing.span("blocksync.verify_run", cat="blocksync",
                          blocks=n, from_height=run[0].header.height):
            results = self.verifier.verify(jobs)
        # staleness marker: bumps exactly when a validator update lands
        # (state/execution.py _update_state). Once it moves, every
        # remaining job in the run was packed against a stale set and is
        # re-verified individually (epoch changes are rare in replay).
        pack_marker = self.state.last_height_validators_changed

        for i in range(n):
            first, second = run[i], run[i + 1]
            if self.state.last_height_validators_changed != pack_marker:
                redo = self.verifier.verify([CommitJob(
                    vals=self.state.validators,
                    block_id=first.block_id(),
                    height=first.header.height,
                    commit=second.last_commit,
                    chain_id=self.state.chain_id,
                )])
                results[i] = redo[0]
            if results[i] is not None:
                self._punish_pair(first.header.height)
                return  # stop the run; loop re-requests and retries
            try:
                self.block_exec.validate_block(self.state, first)
            except Exception:
                # validation failure = the peers fed us a bad block
                self._punish_pair(first.header.height)
                return
            # persistence/apply failures are LOCAL (disk errors, app
            # bugs): punishing the serving peers here would strip an
            # honest node of its sync peers (round-2 advisory). Let the
            # error surface; the run retries without banning.
            with tracing.span("blocksync.apply", cat="blocksync",
                              height=first.header.height):
                self.block_store.save_block(first, second.last_commit)
                self.state = self.block_exec.apply_block(
                    self.state, first.block_id(), first
                )
            self.pool.pop_block()

    def _punish_pair(self, height: int) -> None:
        """Either block of the failed (h, h+1) pair may be the bad one:
        the reference redoes and punishes BOTH sides
        (blocksync/reactor.go:480-496) — banning only h's server would let
        a malicious h+1 LastCommit get honest peers banned one by one."""
        peers = {self.pool.peer_of(height), self.pool.peer_of(height + 1)}
        self.pool.redo_block(height)
        self.pool.redo_block(height + 1)
        for peer in peers - {None}:
            self.pool.ban_peer(peer)
            self.banned_peers.append(peer)
            if self.on_ban is not None:
                self.on_ban(peer)

    # -- introspection -----------------------------------------------------

    def height(self) -> int:
        return self.state.last_block_height

    def wait_caught_up(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.pool.is_caught_up() or not self.is_running():
                return True
            time.sleep(0.02)
        return False
