"""Streaming multi-commit verification pipeline — the TPU blocksync core.

Reference shape: blocksync/reactor.go:463 verifies each streamed block's
commit serially (`state.Validators.VerifyCommitLight(...)` once per
block, ~1k sigs each). The TPU restructuring packs MANY consecutive
commits into one fused device pass: every signature row carries a
commit_id, the kernel verifies all rows in parallel and computes each
commit's voting-power quorum bit with a segmented one-hot tally
(ed25519_kernel.tally_core), so a 16k-signature pass retires ~16 blocks
of 1k validators at once.

Double buffering comes free from JAX async dispatch: the kernel call for
chunk k returns immediately, so the host packs chunk k+1 while the device
works; fetching chunk k's results overlaps the next dispatch
(SURVEY.md §7 stage 2's H2D-hiding requirement).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from cometbft_tpu.ops import ed25519_kernel as ek
from cometbft_tpu.types.commit import Commit
from cometbft_tpu.types.validation import (
    InvalidSignatureError,
    NotEnoughPowerError,
    VerificationError,
    _verify_basic,
)
from cometbft_tpu.types.validator import ValidatorSet

# Fixed commit-axis padding: keeps the kernel's static n_commits constant
# across runs (one compile per signature bucket, not per run length).
MAX_COMMITS_PER_CHUNK = 64

# Device-side sign-bytes stamping for the cached chunk path (ISSUE 19):
# ship per-row (sig, ts, flags) deltas plus ONE resident template per
# commit height instead of full packed rows — the catch-up firehose is
# exactly the cross-height shape the template cache amortizes. Flip off
# to force the legacy full-row pack (the bit-live differential oracle).
DEVICE_STAMP = True


@dataclass
class CommitJob:
    """One block's commit to verify (the VerifyCommitLight arguments)."""

    vals: ValidatorSet
    block_id: object
    height: int
    commit: Commit
    chain_id: str


@dataclass
class _Chunk:
    jobs: List  # [(global_idx, CommitJob)]
    row_job: np.ndarray   # (n,) job index per signature row
    row_idx: np.ndarray   # (n,) commit-signature index per row (blame)
    pending: tuple        # device arrays in flight
    row_pos: Optional[np.ndarray] = None  # device row per packed sig
    # (None = rows are dense 0..n-1; cached-table chunks stride commits
    # to the valset table period so row b mod M == validator index)


class StreamVerifier:
    """Packs CommitJobs into fused multi-commit device passes.

    verify(jobs) returns a list of Optional[VerificationError] — None for
    a commit that verified with quorum, the failure otherwise (bad sig
    rows get InvalidSignatureError with the exact commit-sig index, like
    the reference's per-sig blame fallback, types/validation.go:243-250).
    """

    def __init__(self, max_sigs: int = 65536, use_pallas: bool = False,
                 min_device_sigs: int = 129):
        from cometbft_tpu.libs.staging import StagingPool

        self.max_sigs = max_sigs
        self.use_pallas = use_pallas
        self._vs_cache = {}
        # below this many rows the device pass loses to a host verify
        # loop (dispatch + compile economics — the shouldBatchVerify gate,
        # types/validation.go:13-17, applied to the streaming path)
        self.min_device_sigs = min_device_sigs
        # private staging pool, 3 deep: up to 2 chunks fly while a 3rd
        # packs (the double-buffer window below), so rotation can never
        # hand back a buffer whose upload is still the newest dispatch
        self._staging = StagingPool(slots=3)

    # -- packing -----------------------------------------------------------

    @staticmethod
    def _template_msgs(jobs, job_idxs):
        """No-native fallback: vectorized template patching per commit
        (Commit.sign_bytes_rows via validation's toggle) — byte-equal
        to the legacy per-row vote_sign_bytes loop, shared by both
        pack paths."""
        from cometbft_tpu.types import validation as tv

        msgs = []
        for j, idxs in job_idxs:
            job = jobs[j][1]
            msgs += tv._commit_msgs(job.chain_id, job.commit, idxs)
        return msgs

    def _valset_arrays(self, vs):
        """(pub_bytes_list, power_list, all_32B) per ValidatorSet,
        cached by identity — the streaming loop re-reads one set for
        hundreds of consecutive commits."""
        cached = self._vs_cache.get(id(vs))
        if cached is not None and cached[3] is vs:
            return cached[:3]
        # tuples, not lists: immutable key columns hit the identity-
        # memoized content key in ed25519_cached.table_for_pubs
        keys = tuple(v.pub_key.data for v in vs.validators)
        powers = tuple(v.voting_power for v in vs.validators)
        keys_ok = all(len(k) == 32 for k in keys)
        if len(self._vs_cache) > 8:
            self._vs_cache.clear()
        # the valset itself rides in the entry so an id() collision with
        # a garbage-collected set can never alias
        self._vs_cache[id(vs)] = (keys, powers, keys_ok, vs)
        return keys, powers, keys_ok

    def _cached_table(self, jobs):
        """The valset window table when every job in the chunk shares one
        ed25519 valset (the dominant blocksync shape) — else None."""
        if not self.use_pallas:
            return None
        vs0 = jobs[0][1].vals
        if any(job.vals is not vs0 for _, job in jobs[1:]):
            return None
        keys, vpowers, keys_ok = self._valset_arrays(vs0)
        if not keys_ok or len(keys) < 2:
            return None
        from cometbft_tpu.ops import ed25519_cached as ec

        # device-resident per-valset cache: the steady sync stream hits
        # the identity memo and never re-hashes (or re-uploads) the set
        return ec.table_for_valset(vs0)

    def _pack_chunk_cached(self, jobs, table) -> Optional[_Chunk]:
        """Strided pack for the cached-table kernel: commit c occupies
        device rows [c*M, (c+1)*M) with validator i's signature at row
        c*M + i (the kernel derives the table key as row mod M). Rows
        with no countable signature stay dead (precheck=0, counted=0).
        """
        from cometbft_tpu import native
        from cometbft_tpu.ops import ed25519_cached as ec
        from cometbft_tpu.ops.ed25519_pallas import _PB
        from cometbft_tpu.types import canonical

        M = table.n_vals
        # static jobs-per-chunk — MUST match _split_for_tables or small
        # valsets would inflate B to max_sigs rows of mostly-dead work
        cap = min(MAX_COMMITS_PER_CHUNK, max(1, self.max_sigs // M))
        assert len(jobs) <= cap
        B = cap * M

        pubs: List[bytes] = []
        sigs: List[bytes] = []
        row_job: List[int] = []
        row_idx: List[int] = []
        row_pos: List[int] = []
        row_ts: List[tuple] = []
        job_idxs: List[tuple] = []  # (j, idxs) for the template fallback
        keys, _, _ = self._valset_arrays(jobs[0][1].vals)
        nvals = len(keys)
        for j, (_, job) in enumerate(jobs):
            css = job.commit.signatures
            idxs = [i for i, cs in enumerate(css)
                    if cs.for_block() and i < nvals]
            if not idxs:
                continue
            pubs += [keys[i] for i in idxs]
            sigs += [css[i].signature for i in idxs]
            row_ts += [(css[i].timestamp.seconds, css[i].timestamp.nanos)
                       for i in idxs]
            row_job += [j] * len(idxs)
            row_idx += idxs
            row_pos += [j * M + i for i in idxs]
            job_idxs.append((j, idxs))
        if not pubs:
            return None
        n = len(pubs)
        if any(len(s) != 64 for s in sigs):
            return None  # malformed rows: dense screen path handles
        pos = np.asarray(row_pos, np.int64)
        thresh = np.zeros((cap, ek.TALLY_LIMBS), np.int32)
        thresh[:, -1] = ek.POWER_MASK  # unreachable for padded job slots
        for j, (_, job) in enumerate(jobs):
            thresh[j] = ek.threshold_limbs(
                job.vals.total_voting_power() * 2 // 3
            )[0]
        # delta staging first: when every job stamps, the whole host
        # pack below (SHA-512 + mod-L per row) never runs
        pending = self._stamp_chunk(jobs, sigs, row_ts, row_job, pos,
                                    B, cap, table, thresh)
        if pending is not None:
            return _Chunk(list(jobs), np.asarray(row_job),
                          np.asarray(row_idx), pending, row_pos=pos)
        # dense native/numpy pack, then scatter to the strided layout
        packed = None
        if native.available():
            templates = []
            for _, job in jobs:
                enc = canonical.CanonicalVoteEncoder(
                    job.chain_id, canonical.PRECOMMIT_TYPE,
                    job.commit.height, job.commit.round,
                    job.commit.block_id,
                )
                templates.append(enc.template)
            packed = native.ed25519_pack_commits(
                b"".join(pubs), b"".join(sigs), templates,
                np.asarray(row_job, np.int32),
                np.asarray([s for s, _ in row_ts], np.int64),
                np.asarray([nn for _, nn in row_ts], np.int64), n,
            )
        if packed is not None:
            _, _, ry_d, rsign_d, sdig_d, hdig_d, pre_d = packed
        else:
            msgs = self._template_msgs(jobs, job_idxs)
            pbd = ek.pack_batch(pubs, msgs, sigs, pad_to=n)
            ry_d, rsign_d = pbd.ry, pbd.rsign
            sdig_d, hdig_d, pre_d = pbd.sdig, pbd.hdig, pbd.precheck
        # pinned staging: chunk arrays rotate through the verifier's
        # persistent pool so packing chunk k+1 reuses chunk k-2's memory
        pool = self._staging
        ry = pool.get("chunk.ry", (B, ry_d.shape[1]), ry_d.dtype)
        ry[pos] = ry_d[:n]
        rsign = pool.get("chunk.rsign", (B,), np.int32)
        rsign[pos] = np.asarray(rsign_d[:n], np.int32)
        sdig = pool.get("chunk.sdig", (B, sdig_d.shape[1]), sdig_d.dtype)
        sdig[pos] = sdig_d[:n]
        hdig = pool.get("chunk.hdig", (B, hdig_d.shape[1]), hdig_d.dtype)
        hdig[pos] = hdig_d[:n]
        precheck = pool.get("chunk.precheck", (B,), np.bool_)
        precheck[pos] = np.asarray(pre_d[:n], np.bool_)
        counted = pool.get("chunk.counted", (B,), np.bool_)
        counted[pos] = True
        commit_ids = pool.get("chunk.cid", (B,), np.int32)
        for j in range(cap):
            commit_ids[j * M:(j + 1) * M] = j
        pb = _PB(None, None, ry, rsign, sdig, hdig, precheck)
        out = pool.get("chunk.rows", ec.packed_rows_shape(B, cap),
                       np.int32)
        rows = ec.pack_rows_cached(pb, counted, commit_ids, thresh,
                                   out=out)
        pending = ec.verify_tally_rows_cached(rows, table, cap)
        return _Chunk(list(jobs), np.asarray(row_job),
                      np.asarray(row_idx), pending, row_pos=pos)

    def _stamp_chunk(self, jobs, sigs, row_ts, row_job, pos, B, cap,
                     table, thresh):
        """Delta staging for the cached chunk (ISSUE 19): stage only
        (sig, ts_words, flags) per row — 80 B instead of the full
        packed column set — and let the device stamping prologue
        expand each row against its height's resident template
        (tmpl_id == commit_id == the job index). Returns the pending
        device arrays, or None when the chunk must host-pack: stamping
        disabled, a pre-pub_raw table, more heights than the template
        matrix holds, or timestamp words outside the staged int32
        layout."""
        if not DEVICE_STAMP or getattr(table, "pub_raw", None) is None:
            return None
        from cometbft_tpu.ops import ed25519_cached as ec
        from cometbft_tpu.types import canonical

        if len(jobs) > ec.MAX_TEMPLATE_SITES:
            return None
        if any(not (-2**63 <= s < 2**63 and -2**31 <= nn < 2**31)
               for s, nn in row_ts):
            return None
        sites = []
        for _, job in jobs:
            tpl = canonical.VoteRowTemplate(
                job.chain_id, canonical.PRECOMMIT_TYPE,
                job.commit.height, job.commit.round,
                job.commit.block_id)
            sites.append(tpl.stamp_site())
        sec_a = np.fromiter((s for s, _ in row_ts), np.int64,
                            count=len(row_ts))
        nan_a = np.fromiter((nn for _, nn in row_ts), np.int64,
                            count=len(row_ts))
        try:
            ent = ec.template_entry(sites)
        except Exception:  # noqa: BLE001 - oversized site: host pack
            return None
        pool = self._staging
        dsig = pool.get("chunk.dsig", (B, 64), np.uint8)
        dsig[pos] = np.frombuffer(b"".join(sigs),
                                  np.uint8).reshape(-1, 64)
        dts = pool.get("chunk.dts", (B, 3), np.int32)
        dts[pos] = canonical.split_ts_words(sec_a, nan_a)
        dfl = pool.get("chunk.dflags", (B,), np.int32)
        rj = np.asarray(row_job, np.int64)
        # live | counted | tmpl_id<<2 | cid<<10 — every packed chunk
        # row is countable (the for_block filter already ran); dead
        # lanes keep the pool's zero fill (live=0 -> zero row)
        dfl[pos] = (3 | (rj << 2) | (rj << 10)).astype(np.int32)
        return ec.verify_tally_delta_cached(dsig, dts, dfl, ent, table,
                                            cap, thresh)

    def _pack_chunk(self, jobs) -> Optional[_Chunk]:
        """jobs: [(global_idx, CommitJob)] for this chunk."""
        from cometbft_tpu import native
        from cometbft_tpu.types import canonical

        pubs: List[bytes] = []
        sigs: List[bytes] = []
        row_job: List[int] = []
        row_idx: List[int] = []
        powers: List[int] = []
        row_ts: List[tuple] = []
        job_idxs: List[tuple] = []  # (j, idxs) for the template fallback
        well_formed = True
        native_possible = native.available()
        for j, (_, job) in enumerate(jobs):
            # per-valset key/power staging is cached (sync streams reuse
            # one set across hundreds of commits); the per-commit work is
            # a handful of comprehensions, not a 6-append row loop
            keys, vpowers, keys_ok = self._valset_arrays(job.vals)
            css = job.commit.signatures
            nvals = len(keys)
            idxs = [i for i, cs in enumerate(css)
                    if cs.for_block() and i < nvals]
            if not idxs:
                continue
            pubs += [keys[i] for i in idxs]
            sigs += [css[i].signature for i in idxs]
            if native_possible:  # consumed only by the native fast path
                row_ts += [
                    (css[i].timestamp.seconds, css[i].timestamp.nanos)
                    for i in idxs
                ]
            row_job += [j] * len(idxs)
            row_idx += idxs
            powers += [vpowers[i] for i in idxs]
            job_idxs.append((j, idxs))
            if not keys_ok or any(len(css[i].signature) != 64
                                  for i in idxs):
                well_formed = False  # numpy path screens bad rows
        if not pubs:
            return None
        n = len(pubs)
        if self.use_pallas:
            from cometbft_tpu.ops import ed25519_pallas as kp

            pad = kp.pad_to_tile(n)
        else:
            pad = ek.bucket_size(n)
        # native fast path: sign-bytes are assembled in C from one
        # (pre, suf) template per commit + per-row timestamps — the
        # hottest host loop of streaming verification never builds
        # Python message objects at all
        packed = None
        if well_formed and native_possible:
            templates = []
            for _, job in jobs:
                enc = canonical.CanonicalVoteEncoder(
                    job.chain_id, canonical.PRECOMMIT_TYPE,
                    job.commit.height, job.commit.round,
                    job.commit.block_id,
                )
                templates.append(enc.template)
            packed = native.ed25519_pack_commits(
                b"".join(pubs), b"".join(sigs), templates,
                np.asarray(row_job, np.int32),
                np.asarray([s for s, _ in row_ts], np.int64),
                np.asarray([nn for _, nn in row_ts], np.int64), pad,
            )
        if packed is not None:
            pb = ek.PackedBatch(n, pad, *packed)
        else:
            msgs = self._template_msgs(jobs, job_idxs)
            pb = ek.pack_batch(pubs, msgs, sigs, pad_to=pad)
        power5 = np.zeros((pad, ek.POWER_LIMBS), np.int32)
        power5[:n] = ek.power_limbs(np.asarray(powers, np.int64))
        counted = np.zeros((pad,), np.bool_)
        counted[:n] = True
        # the commit dimension is PADDED to a fixed size: n_commits is a
        # static arg of the jit'd kernel, so a varying count would force a
        # recompile (minutes on CPU) for every distinct run length
        c_pad = MAX_COMMITS_PER_CHUNK + 1
        commit_ids = np.zeros((pad,), np.int32)
        commit_ids[:n] = np.asarray(row_job, np.int32)
        # padding rows tally into the sink commit id so they can't pollute
        # job 0's quorum
        commit_ids[n:] = c_pad - 1
        thresh = np.zeros((c_pad, ek.TALLY_LIMBS), np.int32)
        thresh[:, -1] = ek.POWER_MASK  # unused/sink: unreachable threshold
        for j, (_, job) in enumerate(jobs):
            thresh[j] = ek.threshold_limbs(
                job.vals.total_voting_power() * 2 // 3
            )[0]

        pending = self._dispatch(pb, power5, counted, commit_ids, thresh,
                                 c_pad)
        return _Chunk(jobs, np.asarray(row_job), np.asarray(row_idx),
                      pending)

    def _dispatch(self, pb, power5, counted, commit_ids, thresh, n_commits):
        if self.use_pallas:
            from cometbft_tpu.ops import ed25519_pallas as kp

            # single fused H2D transfer per chunk (see kp.pack_rows)
            rows = kp.pack_rows(pb, power5, counted, commit_ids, thresh)
            return kp.verify_tally_rows(rows, thresh.shape[0])
        return ek.verify_tally_kernel(
            pb.ay, pb.asign, pb.ry, pb.rsign, pb.sdig, pb.hdig, pb.precheck,
            power5, counted, commit_ids, thresh, n_commits,
        )

    # -- the streaming loop ------------------------------------------------

    def _chunk_indexed(self, indexed):
        """Split [(global_idx, job)] into chunks of <= max_sigs rows."""
        cur, cur_sigs = [], 0
        for gi, job in indexed:
            n = len(job.commit.signatures)
            if cur and (cur_sigs + n > self.max_sigs
                        or len(cur) >= MAX_COMMITS_PER_CHUNK):
                yield cur
                cur, cur_sigs = [], 0
            cur.append((gi, job))
            cur_sigs += n
        if cur:
            yield cur

    def verify(
        self, jobs: Sequence[CommitJob]
    ) -> List[Optional[VerificationError]]:
        results: List[Optional[VerificationError]] = [None] * len(jobs)
        done = set()
        # structural prechecks stay host-side (cheap, no device round trip)
        for i, job in enumerate(jobs):
            try:
                _verify_basic(job.vals, job.block_id, job.height, job.commit)
            except VerificationError as e:
                results[i] = e
                done.add(i)

        # commits with non-ed25519 validators route to the grouped batch
        # dispatch (crypto/batch.py handles mixed key types); the fused
        # multi-commit pass below assumes uniform ed25519 rows
        for i, job in enumerate(jobs):
            if i in done:
                continue
            if any(
                v.pub_key.key_type != "ed25519" for v in job.vals.validators
            ):
                from cometbft_tpu.types import validation as tv

                try:
                    tv.verify_commit_light(
                        job.chain_id, job.vals, job.block_id, job.height,
                        job.commit, tv.device_batch_fn(),
                    )
                except VerificationError as e:
                    results[i] = e
                done.add(i)

        indexed = [(i, j) for i, j in enumerate(jobs) if i not in done]
        total_rows = sum(
            len(j.commit.signatures) for _, j in indexed
        )
        if total_rows < self.min_device_sigs:
            from cometbft_tpu.types import validation as tv

            for gi, job in indexed:
                try:
                    tv.verify_commit_light(
                        job.chain_id, job.vals, job.block_id, job.height,
                        job.commit, batch_fn=None,
                    )
                except VerificationError as e:
                    results[gi] = e
            return results

        in_flight: List[_Chunk] = []
        for chunk_pairs in self._split_for_tables(indexed):
            chunk = self._pack_any(chunk_pairs)
            if chunk is None:
                # zero packable rows (e.g. every signature ABSENT): fail
                # CLOSED — these commits tallied no power at all
                for gi, job in chunk_pairs:
                    results[gi] = NotEnoughPowerError(
                        0, job.vals.total_voting_power() * 2 // 3
                    )
            else:
                in_flight.append(chunk)
            # keep at most 2 chunks in flight: fetch the oldest while the
            # newest computes (double buffering)
            if len(in_flight) > 2:
                self._collect(in_flight.pop(0), results)
        for chunk in in_flight:
            self._collect(chunk, results)
        return results

    def _split_for_tables(self, indexed):
        """Chunk, then sub-split cached-table chunks to the static
        jobs-per-chunk capacity the strided layout compiles for."""
        for chunk_pairs in self._chunk_indexed(indexed):
            table = self._cached_table(chunk_pairs)
            if table is None:
                yield chunk_pairs
                continue
            cap = min(MAX_COMMITS_PER_CHUNK,
                      max(1, self.max_sigs // table.n_vals))
            for k in range(0, len(chunk_pairs), cap):
                yield chunk_pairs[k:k + cap]

    def _pack_any(self, jobs) -> Optional[_Chunk]:
        table = self._cached_table(jobs)
        if table is not None:
            chunk = self._pack_chunk_cached(jobs, table)
            if chunk is not None:
                return chunk  # malformed rows fall through to the screen
        return self._pack_chunk(jobs)

    def _collect(self, chunk: _Chunk, results) -> None:
        valid, tally, quorum = chunk.pending
        valid = np.asarray(valid)
        quorum = np.asarray(quorum)
        for j, (gi, job) in enumerate(chunk.jobs):
            rows = chunk.row_job == j
            if chunk.row_pos is not None:
                row_valid = valid[chunk.row_pos[rows]]
            else:
                row_valid = valid[: len(chunk.row_job)][rows]
            if not row_valid.all():
                bad = chunk.row_idx[rows][~row_valid][0]
                results[gi] = InvalidSignatureError(int(bad))
            elif not bool(quorum[j]):
                needed = job.vals.total_voting_power() * 2 // 3
                results[gi] = NotEnoughPowerError(-1, needed)


def make_stream_verifier(use_pallas: Optional[bool] = None,
                         max_sigs: int = 65536) -> StreamVerifier:
    if use_pallas is None:
        from cometbft_tpu.crypto.batch import _accel_backend

        use_pallas = _accel_backend()
    return StreamVerifier(max_sigs=max_sigs, use_pallas=use_pallas)
