"""Blocksync over p2p: the wire protocol around blocksync.reactor.

Reference: blocksync/reactor.go — BlocksyncChannel 0x40 (:59-66),
StatusRequest/StatusResponse/BlockRequest/BlockResponse/NoBlockResponse
messages, poolRoutine requests (:286), SwitchToConsensus (:391-401).

The verification/apply engine stays in blocksync.reactor.BlocksyncReactor
(fused multi-commit device passes); this module is the transport face:
it answers block/status requests from the store and feeds received
blocks/statuses into the pool.
"""
from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from cometbft_tpu.blocksync.reactor import BlocksyncReactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.switch import Peer, Reactor
from cometbft_tpu.store.blockstore import BlockStore
from cometbft_tpu.types import serde

BLOCKSYNC_CHANNEL = 0x40  # blocksync/reactor.go:59 BlocksyncChannel


class BlocksyncP2PReactor(Reactor):
    """p2p face of blocksync: status + block request/response."""

    def __init__(self, engine: Optional[BlocksyncReactor],
                 block_store: BlockStore,
                 status_interval: float = 2.0):
        super().__init__("BLOCKSYNC")
        self.engine = engine  # None on nodes that only SERVE blocks
        self.block_store = block_store
        self.status_interval = status_interval
        self._peers = {}  # peer_id -> Peer
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._status_thread: Optional[threading.Thread] = None
        if engine is not None:
            engine.on_ban = self._on_ban

    def channel_descriptors(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5,
                                  send_queue_capacity=1000,
                                  recv_message_capacity=64 * 1024 * 1024)]

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        with self._lock:
            self._peers[peer.peer_id] = peer
        peer.send(BLOCKSYNC_CHANNEL, json.dumps({"t": "status_req"}).encode())
        peer.send(BLOCKSYNC_CHANNEL, self._status_bytes())
        if self._status_thread is None and self.engine is not None:
            self._status_thread = threading.Thread(
                target=self._status_routine, daemon=True, name="bs-status"
            )
            self._status_thread.start()

    def remove_peer(self, peer: Peer, reason: str) -> None:
        with self._lock:
            self._peers.pop(peer.peer_id, None)
        if self.engine is not None:
            self.engine.pool.remove_peer(peer.peer_id)

    # -- outbound ----------------------------------------------------------

    def _status_bytes(self) -> bytes:
        return json.dumps({
            "t": "status",
            "base": self.block_store.base(),
            "height": self.block_store.height(),
        }).encode()

    def _status_routine(self) -> None:
        """Re-poll peer statuses while syncing (poolRoutine's ticker)."""
        while not self._stop.is_set():
            time.sleep(self.status_interval)
            if self.engine is None or not self.engine.is_running():
                return
            with self._lock:
                peers = list(self._peers.values())
            for p in peers:
                p.send(BLOCKSYNC_CHANNEL,
                       json.dumps({"t": "status_req"}).encode())

    def _send_request(self, peer_id: str, height: int) -> None:
        with self._lock:
            peer = self._peers.get(peer_id)
        if peer is not None:
            peer.send(BLOCKSYNC_CHANNEL,
                      json.dumps({"t": "block_req", "h": height}).encode())

    def _on_ban(self, peer_id: str) -> None:
        with self._lock:
            peer = self._peers.get(peer_id)
        if peer is not None and self.switch is not None:
            self.switch.stop_peer_for_error(peer, "blocksync: bad block")

    def stop_routines(self) -> None:
        self._stop.set()

    # -- inbound -----------------------------------------------------------

    def receive(self, chan_id: int, peer: Peer, msg: bytes) -> None:
        try:
            j = json.loads(msg.decode())
            t = j.get("t")
            if t == "status_req":
                peer.send(BLOCKSYNC_CHANNEL, self._status_bytes())
            elif t == "status":
                if self.engine is not None:
                    self.engine.add_peer(
                        peer.peer_id, int(j["height"]),
                        lambda h, pid=peer.peer_id: self._send_request(
                            pid, h
                        ),
                    )
            elif t == "block_req":
                h = int(j["h"])
                block = self.block_store.load_block(h)
                if block is None:
                    peer.send(BLOCKSYNC_CHANNEL, json.dumps(
                        {"t": "no_block", "h": h}
                    ).encode())
                else:
                    peer.send(BLOCKSYNC_CHANNEL, json.dumps({
                        "t": "block", "h": h,
                        "b": json.loads(serde.block_to_json(block)),
                    }).encode())
            elif t == "block":
                if self.engine is not None:
                    block = serde.block_from_json(json.dumps(j["b"]))
                    self.engine.receive_block(peer.peer_id, block)
            elif t == "no_block":
                # peer can't serve this height: let the pool re-route
                if self.engine is not None:
                    self.engine.pool.redo_block(int(j["h"]))
            else:
                raise ValueError(f"unknown blocksync message {t!r}")
        except Exception as e:  # noqa: BLE001 - malformed peer message
            self.switch.stop_peer_for_error(peer, f"bad blocksync msg: {e}")
