"""BlockPool: sliding-window parallel block requester.

Reference: blocksync/pool.go — 600 outstanding requests (:31-34), max 20
per peer, requesters re-assign on peer failure, PeekTwoBlocks/PopRequest
consumed by the reactor, peer height tracking via status messages.

Transport-agnostic: a peer is registered with a `request(height)`
callback (the p2p reactor wires a real channel; tests wire a local
chain). Blocks come back through add_block."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from cometbft_tpu.types.block import Block

MAX_PENDING_REQUESTS = 600   # pool.go:32 maxPendingRequests
MAX_PER_PEER = 20            # pool.go:33 maxPendingRequestsPerPeer


@dataclass
class _Peer:
    peer_id: str
    height: int
    request: Callable[[int], None]
    pending: int = 0


@dataclass
class _Requester:
    height: int
    peer_id: Optional[str] = None
    block: Optional[Block] = None


class BlockPool:
    def __init__(self, start_height: int):
        self.height = start_height  # next height to process
        self._peers: Dict[str, _Peer] = {}
        self._banned: set = set()
        self._requesters: Dict[int, _Requester] = {}
        self._lock = threading.Lock()

    # -- peer management ---------------------------------------------------

    def set_peer_range(self, peer_id: str, height: int,
                       request: Callable[[int], None]) -> None:
        """SetPeerRange (pool.go): register/refresh a peer and its tip."""
        with self._lock:
            if peer_id in self._banned:
                return  # a banned peer can't re-register via status spam
            p = self._peers.get(peer_id)
            if p is None:
                self._peers[peer_id] = _Peer(peer_id, height, request)
            else:
                p.height = max(p.height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(peer_id, None)
            for r in self._requesters.values():
                if r.peer_id == peer_id and r.block is None:
                    r.peer_id = None  # re-assignable

    def ban_peer(self, peer_id: str) -> None:
        """Reactor punishes a peer that served a bad block
        (blocksync/reactor.go:480-496); its pending blocks are dropped."""
        with self._lock:
            self._banned.add(peer_id)
            self._peers.pop(peer_id, None)
            for r in self._requesters.values():
                if r.peer_id == peer_id:
                    r.peer_id = None
                    r.block = None

    # -- request scheduling ------------------------------------------------

    def make_requests(self) -> int:
        """Fill the sliding window: assign unclaimed heights to peers with
        capacity. Returns how many requests were issued."""
        issued = []
        with self._lock:
            window_end = self.height + MAX_PENDING_REQUESTS
            for h in range(self.height, window_end):
                if h > self._max_peer_height():
                    break
                r = self._requesters.get(h)
                if r is None:
                    r = self._requesters[h] = _Requester(h)
                if r.peer_id is not None or r.block is not None:
                    continue
                peer = self._pick_peer(h)
                if peer is None:
                    continue
                r.peer_id = peer.peer_id
                peer.pending += 1
                issued.append((peer, h))
        for peer, h in issued:
            peer.request(h)
        return len(issued)

    def _max_peer_height(self) -> int:
        return max((p.height for p in self._peers.values()), default=0)

    def max_peer_height(self) -> int:
        with self._lock:
            return self._max_peer_height()

    def num_peers(self) -> int:
        with self._lock:
            return len(self._peers)

    def _pick_peer(self, height: int) -> Optional[_Peer]:
        best = None
        for p in self._peers.values():
            if p.height < height or p.pending >= MAX_PER_PEER:
                continue
            if best is None or p.pending < best.pending:
                best = p
        return best

    # -- block intake ------------------------------------------------------

    def add_block(self, peer_id: str, block: Block) -> bool:
        """AddBlock (pool.go): only accepted from the peer the height was
        requested from (anti-spam)."""
        with self._lock:
            r = self._requesters.get(block.header.height)
            if r is None or r.peer_id != peer_id or r.block is not None:
                return False
            r.block = block
            p = self._peers.get(peer_id)
            if p:
                p.pending = max(0, p.pending - 1)
            return True

    # -- consumption -------------------------------------------------------

    def peek_blocks(self, max_n: int = 2) -> List[Block]:
        """A run of consecutive available blocks starting at self.height
        (PeekTwoBlocks generalized — the fused multi-commit verifier eats
        as long a run as is ready)."""
        out: List[Block] = []
        with self._lock:
            for h in range(self.height, self.height + max_n):
                r = self._requesters.get(h)
                if r is None or r.block is None:
                    break
                out.append(r.block)
        return out

    def pop_block(self) -> None:
        """Advance past self.height (PopRequest)."""
        with self._lock:
            self._requesters.pop(self.height, None)
            self.height += 1

    def peer_of(self, height: int) -> Optional[str]:
        with self._lock:
            r = self._requesters.get(height)
            return r.peer_id if r else None

    def redo_block(self, height: int) -> Optional[str]:
        """A block failed verification: drop it (and everything above it
        from the same peer) for re-request; returns the offending peer."""
        with self._lock:
            r = self._requesters.get(height)
            if r is None:
                return None
            peer = r.peer_id
            for h, req in self._requesters.items():
                if h >= height and req.peer_id == peer:
                    req.block = None
                    req.peer_id = None
            return peer

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp: blocks up to maxPeerHeight-1 are applied
        (verifying height H needs H+1's LastCommit); consensus takes the
        tip after the switch."""
        with self._lock:
            maxh = self._max_peer_height()
            return maxh > 0 and self.height >= maxh
