"""BlockPool: sliding-window parallel block requester.

Reference: blocksync/pool.go — 600 outstanding requests (:31-34), max 20
per peer, requesters re-assign on peer failure, PeekTwoBlocks/PopRequest
consumed by the reactor, peer height tracking via status messages.

Transport-agnostic: a peer is registered with a `request(height)`
callback (the p2p reactor wires a real channel; tests wire a local
chain). Blocks come back through add_block.

Robustness (pool.go requestRetrySeconds + bpRequester.redo analog): a
request that a peer never answers TIMES OUT — the requester is released
back to the pool with an exponential-backoff cooldown and reassigned
(preferring a different peer), and a peer that keeps timing out is
dropped from the pool (it can re-register via its next status
message). Without this, one dead/flaky peer pins its assigned heights
forever and the sync wedges.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.types.block import Block

MAX_PENDING_REQUESTS = 600   # pool.go:32 maxPendingRequests
MAX_PER_PEER = 20            # pool.go:33 maxPendingRequestsPerPeer
REQUEST_TIMEOUT = 15.0       # pool.go requestRetrySeconds shape
RETRY_BACKOFF_BASE = 0.05    # first re-request cooldown
RETRY_BACKOFF_MAX = 2.0      # cap so a long outage still retries
PEER_TIMEOUT_LIMIT = 3       # consecutive timeouts before peer removal

fp.register("blocksync.request",
            "issuing a block request to a peer (flake = lost request)")
fp.register("blocksync.deliver",
            "a peer-delivered block arriving at the pool")


@dataclass
class _Peer:
    peer_id: str
    height: int
    request: Callable[[int], None]
    pending: int = 0
    timeouts: int = 0  # consecutive request timeouts


@dataclass
class _Requester:
    height: int
    peer_id: Optional[str] = None
    block: Optional[Block] = None
    attempts: int = 0      # failed/timed-out assignments so far
    deadline: float = 0.0  # when the outstanding request times out
    retry_at: float = 0.0  # backoff gate for the next assignment


class BlockPool:
    def __init__(self, start_height: int,
                 request_timeout: float = REQUEST_TIMEOUT):
        self.height = start_height  # next height to process
        self.request_timeout = request_timeout
        self._peers: Dict[str, _Peer] = {}
        self._banned: set = set()
        self._requesters: Dict[int, _Requester] = {}
        self._max_seen_height = 0  # highest tip EVER advertised
        self._lock = threading.Lock()

    # -- peer management ---------------------------------------------------

    def set_peer_range(self, peer_id: str, height: int,
                       request: Callable[[int], None]) -> None:
        """SetPeerRange (pool.go): register/refresh a peer and its tip."""
        with self._lock:
            if peer_id in self._banned:
                return  # a banned peer can't re-register via status spam
            p = self._peers.get(peer_id)
            if p is None:
                self._peers[peer_id] = _Peer(peer_id, height, request)
            else:
                p.height = max(p.height, height)
            self._max_seen_height = max(self._max_seen_height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self._remove_peer_locked(peer_id)

    def _remove_peer_locked(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)
        for r in self._requesters.values():
            if r.peer_id == peer_id and r.block is None:
                r.peer_id = None  # re-assignable

    def ban_peer(self, peer_id: str) -> None:
        """Reactor punishes a peer that served a bad block
        (blocksync/reactor.go:480-496); its pending blocks are dropped."""
        with self._lock:
            self._banned.add(peer_id)
            self._peers.pop(peer_id, None)
            for r in self._requesters.values():
                if r.peer_id == peer_id:
                    r.peer_id = None
                    r.block = None

    # -- request scheduling ------------------------------------------------

    def make_requests(self) -> int:
        """Fill the sliding window: time out stale requests, then assign
        unclaimed heights to peers with capacity. Returns how many
        requests were issued."""
        now = time.monotonic()
        issued = []
        with self._lock:
            self._expire_locked(now)
            window_end = self.height + MAX_PENDING_REQUESTS
            for h in range(self.height, window_end):
                if h > self._max_peer_height():
                    break
                r = self._requesters.get(h)
                if r is None:
                    r = self._requesters[h] = _Requester(h)
                if r.peer_id is not None or r.block is not None:
                    continue
                if r.retry_at > now:
                    continue  # backoff after a timeout/redo
                peer = self._pick_peer(h)
                if peer is None:
                    continue
                r.peer_id = peer.peer_id
                r.deadline = now + self.request_timeout
                peer.pending += 1
                issued.append((peer, h))
        sent = 0
        for peer, h in issued:
            try:
                fp.fail_point("blocksync.request")
                peer.request(h)
                sent += 1
            except Exception:  # noqa: BLE001 - a lost request, not fatal
                # the peer callback failed (dead transport, injected
                # fault): the request never left, so let the timeout
                # machinery reclaim the height instead of wedging it
                pass
        return sent

    def _expire_locked(self, now: float) -> None:
        """Timed-out outstanding requests are released with backoff
        (bpRequester redo); serially-unresponsive peers are dropped.
        A peer's timeout strike counts at most ONCE per sweep — a
        healthy peer with several requests in flight must get
        PEER_TIMEOUT_LIMIT full timeout rounds, not be evicted by one
        hiccup expiring its whole window at once."""
        struck: set = set()
        for r in self._requesters.values():
            if r.peer_id is None or r.block is not None:
                continue
            if now < r.deadline:
                continue
            peer = self._peers.get(r.peer_id)
            r.peer_id = None
            r.attempts += 1
            r.retry_at = now + min(
                RETRY_BACKOFF_BASE * (2 ** (r.attempts - 1)),
                RETRY_BACKOFF_MAX,
            )
            if peer is not None:
                peer.pending = max(0, peer.pending - 1)
                if peer.peer_id not in struck:
                    struck.add(peer.peer_id)
                    peer.timeouts += 1
                    if peer.timeouts >= PEER_TIMEOUT_LIMIT:
                        self._remove_peer_locked(peer.peer_id)

    def _max_peer_height(self) -> int:
        return max((p.height for p in self._peers.values()), default=0)

    def max_peer_height(self) -> int:
        with self._lock:
            return self._max_peer_height()

    def max_seen_height(self) -> int:
        """Highest tip any peer EVER advertised — survives peer
        removal, so the reactor can tell 'no peers yet' from 'my sole
        peer timed out mid-sync'."""
        with self._lock:
            return self._max_seen_height

    def num_peers(self) -> int:
        with self._lock:
            return len(self._peers)

    def _pick_peer(self, height: int) -> Optional[_Peer]:
        best = None
        for p in self._peers.values():
            if p.height < height or p.pending >= MAX_PER_PEER:
                continue
            if best is None or p.pending < best.pending:
                best = p
        return best

    # -- block intake ------------------------------------------------------

    def add_block(self, peer_id: str, block: Block) -> bool:
        """AddBlock (pool.go): only accepted from the peer the height was
        requested from (anti-spam)."""
        try:
            fp.fail_point("blocksync.deliver")
        except fp.FailpointError:
            return False  # injected delivery fault: block lost in flight
        with self._lock:
            r = self._requesters.get(block.header.height)
            if r is None or r.peer_id != peer_id or r.block is not None:
                return False
            r.block = block
            r.attempts = 0
            p = self._peers.get(peer_id)
            if p:
                p.pending = max(0, p.pending - 1)
                p.timeouts = 0  # a delivery proves the peer is alive
            return True

    # -- consumption -------------------------------------------------------

    def peek_blocks(self, max_n: int = 2) -> List[Block]:
        """A run of consecutive available blocks starting at self.height
        (PeekTwoBlocks generalized — the fused multi-commit verifier eats
        as long a run as is ready)."""
        out: List[Block] = []
        with self._lock:
            for h in range(self.height, self.height + max_n):
                r = self._requesters.get(h)
                if r is None or r.block is None:
                    break
                out.append(r.block)
        return out

    def pop_block(self) -> None:
        """Advance past self.height (PopRequest)."""
        with self._lock:
            self._requesters.pop(self.height, None)
            self.height += 1

    def peer_of(self, height: int) -> Optional[str]:
        with self._lock:
            r = self._requesters.get(height)
            return r.peer_id if r else None

    def redo_block(self, height: int) -> Optional[str]:
        """A block failed verification: drop it (and everything above it
        from the same peer) for re-request; returns the offending peer."""
        now = time.monotonic()
        with self._lock:
            r = self._requesters.get(height)
            if r is None:
                return None
            peer = r.peer_id
            for h, req in self._requesters.items():
                if h >= height and req.peer_id == peer:
                    req.block = None
                    req.peer_id = None
                    req.attempts += 1
                    req.retry_at = now + min(
                        RETRY_BACKOFF_BASE * (2 ** (req.attempts - 1)),
                        RETRY_BACKOFF_MAX,
                    )
            return peer

    def is_caught_up(self) -> bool:
        """pool.go IsCaughtUp: blocks up to maxPeerHeight-1 are applied
        (verifying height H needs H+1's LastCommit); consensus takes the
        tip after the switch."""
        with self._lock:
            maxh = self._max_peer_height()
            return maxh > 0 and self.height >= maxh
