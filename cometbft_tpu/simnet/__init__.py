"""Byzantine simnet: deterministic in-process adversarial network
simulation (see simnet/core.py for the architecture)."""
from cometbft_tpu.simnet.core import Link, SimNetwork, SimNode
from cometbft_tpu.simnet.harness import Simnet, SimnetFailure
from cometbft_tpu.simnet.schedule import (
    ScheduleError,
    random_schedule,
    schedule_from_json,
    schedule_to_json,
    validate_schedule,
)

__all__ = [
    "Link", "SimNetwork", "SimNode", "Simnet", "SimnetFailure",
    "ScheduleError", "random_schedule", "schedule_from_json",
    "schedule_to_json", "validate_schedule",
]
