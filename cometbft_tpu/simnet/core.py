"""Deterministic in-process network simulator for Byzantine testing.

The real e2e harness (tests/test_e2e.py; CometBFT's test/e2e/runner/
perturb.go) spawns OS processes and perturbs them with kill/disconnect —
too slow and too nondeterministic for tier-1 on a 1-core host. The
simnet replaces the wall clock, the thread scheduler, and the TCP stack
with ONE seeded, single-threaded discrete-event loop:

  * N real ``node``/``consensus`` stacks (full Node: stores, WAL,
    BlockExecutor, evidence pool, ABCI app) run unmodified — but their
    consensus receive routines are PUMPED by the scheduler instead of
    running as threads, their TimeoutTicker is a :class:`SimTicker`
    mapping timeouts onto simulated time, and ``Timestamp.now()`` reads
    the simulated clock (types/timestamp.set_now_source).
  * messages travel over :class:`SimTransport`/:class:`SimConn` — the
    in-memory analog of the p2p seams (p2p/transport.py Transport:
    listen/dial/on_conn; p2p/conn/connection.py MConnection:
    send(chan_id, msg)/on_receive) — through per-directed-link fault
    state: partition, probabilistic drop, latency+jitter, duplication
    and reordering, all drawn from ONE seeded RNG.
  * every node owns a private failpoint registry
    (libs/failpoints.fresh_registry); the scheduler swaps it in around
    that node's execution, so a schedule can arm ``consensus.wal.*``
    faults on node 2 without touching node 0. The isolation covers
    seams evaluated ON the scheduler thread (consensus, WAL, stores,
    evidence) — seams evaluated on background threads (e.g.
    ``verifyplane.dispatch`` on a shared plane's dispatcher) read
    whichever registry is installed at that instant and should be
    armed process-globally instead. A fired ``crash`` action halts the
    node in place; a ``restart`` op later rebuilds the Node over the
    same home dir and exercises the REAL WAL recovery path (consensus
    catchup_replay + store-into-app handshake replay).

Because every event (delivery, timeout, schedule op) executes at a
deterministic (time, seq) and all randomness flows from the seed, two
runs of the same (seed, schedule) produce byte-identical chains —
commit hashes match at every height on every node, which is what makes
a failing schedule replayable.

Wire formats and channel IDs are IMPORTED from the real reactors
(consensus/reactor._vote_bytes / _proposal_from_bytes,
evidence/reactor evidence_to_j, the commit_block catch-up push), so a
reactor format change is automatically what the simnet exercises. The
one divergence: proposals ride whole (the reactor's proposal dict plus
the serialized block) instead of as PartSet chunks — part-level gossip
is a transport concern the fault model already covers with
drop/reorder of whole messages.
"""
from __future__ import annotations

import heapq
import json
import logging
import random
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from cometbft_tpu.consensus.reactor import (
    DATA_CHANNEL,
    VOTE_CHANNEL,
    _proposal_from_bytes,
    _vote_bytes,
)
from cometbft_tpu.consensus.state import ProposalMsg
from cometbft_tpu.consensus.ticker import TimeoutInfo
from cometbft_tpu.evidence.reactor import EVIDENCE_CHANNEL
from cometbft_tpu.libs import failpoints as fp
from cometbft_tpu.libs import tracing
from cometbft_tpu.p2p import peerledger
from cometbft_tpu.types import serde
from cometbft_tpu.types.evidence import (
    EvidenceError,
    evidence_from_j,
    evidence_to_j,
)
from cometbft_tpu.types.timestamp import Timestamp, set_now_source

_log = logging.getLogger(__name__)

SIM_EPOCH_SECONDS = 1_700_000_000  # simulated time zero (fixed, seedable)


class Link:
    """Directed-link fault state (src -> dst). All probabilities are
    evaluated against the simnet's single seeded RNG at SEND time."""

    __slots__ = ("up", "drop", "delay", "jitter", "dup", "reorder",
                 "reorder_window")

    def __init__(self):
        self.up = True
        self.drop = 0.0        # P(message silently lost)
        self.delay = 0.01      # base latency, sim seconds
        self.jitter = 0.0      # uniform extra latency
        self.dup = 0.0         # P(delivered twice)
        self.reorder = 0.0     # P(extra delay >> jitter, so later msgs pass)
        self.reorder_window = 0.25


class SimConn:
    """One direction of an established sim connection — the MConnection
    seam (`send(chan_id, msg) -> bool`, `on_receive(chan_id, msg)`).
    Channel IDs are the real reactors'; the fault model applies per
    send. Carries the src node's peer-ledger record for the dst peer —
    the SAME p2p/peerledger.py seam the real MConnection writes, so a
    scheduled partition's drops are attributed per peer and the ledger
    replays byte-identically (stamps ride the virtual clock)."""

    def __init__(self, net: "SimNetwork", src: int, dst: int,
                 outbound: bool = True):
        self.net = net
        self.src = src
        self.dst = dst
        self.closed = False
        self.rec = net.nodes[src].peer_ledger.open_peer(
            f"n{dst}", outbound=outbound)

    def send(self, chan_id: int, msg: bytes, block: bool = True) -> bool:
        if self.closed:
            return False
        return self.net._send(self.src, self.dst, chan_id, msg,
                              rec=self.rec)

    def close(self) -> None:
        if not self.closed:
            self.net.nodes[self.src].peer_ledger.drop_peer(
                self.rec, "closed")
        self.closed = True


class SimTransport:
    """The Transport seam (p2p/transport.py: listen/dial/on_conn) over
    the hub. `dial` establishes both directions synchronously and hands
    each side its SimConn via on_conn — the in-memory analog of the
    upgrade handshake (identity is the node index; there is nothing to
    forge inside one process)."""

    def __init__(self, net: "SimNetwork", idx: int,
                 on_conn: Callable[[SimConn], None]):
        self.net = net
        self.idx = idx
        self.on_conn = on_conn
        self.listening = False

    def listen(self) -> int:
        self.listening = True
        return self.idx

    def dial(self, peer_idx: int) -> SimConn:
        peer = self.net.nodes[peer_idx]
        if not peer.transport.listening:
            raise ConnectionError(f"sim node {peer_idx} not listening")
        ours = SimConn(self.net, self.idx, peer_idx)
        theirs = SimConn(self.net, peer_idx, self.idx, outbound=False)
        self.on_conn(ours)
        peer.transport.on_conn(theirs)
        return ours

    def close(self) -> None:
        self.listening = False


class SimTicker:
    """TimeoutTicker over simulated time, with the reference's override
    semantics (consensus/ticker.py TimeoutTicker: one live timer; a
    newer (height, round, step) replaces it; older/equal schedules are
    ignored)."""

    def __init__(self, net: "SimNetwork", node: "SimNode"):
        self.net = net
        self.node = node
        self._current: Optional[Tuple[TimeoutInfo, list]] = None

    def schedule(self, ti: TimeoutInfo) -> None:
        if self._current is not None:
            cur, alive = self._current
            if (ti.height, ti.round, ti.step) <= (
                cur.height, cur.round, cur.step
            ):
                return
            alive[0] = False  # cancel the displaced timer
        alive = [True]
        self._current = (ti, alive)
        self.net.schedule(ti.duration,
                          lambda: self._fire(ti, alive),
                          label=f"timeout n{self.node.idx}")

    def _fire(self, ti: TimeoutInfo, alive: list) -> None:
        if not alive[0] or not self.node.alive:
            return
        alive[0] = False
        cs = self.node.node.consensus
        cs.internal_queue.put(("timeout", ti))
        self.net._pump(self.node)

    def stop(self) -> None:
        if self._current is not None:
            self._current[1][0] = False


class SimNode:
    """One simulated validator: a real Node plus its sim plumbing.

    Byzantine knobs (armed by schedule ops / simnet.actors):
      equivocate_budget — next K own votes are double-signed: the real
        vote goes out AND a conflicting vote for a fabricated block ID,
        signed with the raw private key (bypassing FilePV's double-sign
        guard, as a real byzantine signer would).
      garbage_budget — next K own votes go out with garbage signatures
        (the real vote still enters the node's own sets; peers must
        reject the forgery without breaking their verify plane).
    """

    def __init__(self, net: "SimNetwork", idx: int, app_factory, priv,
                 home: str, group: int = 0):
        self.net = net
        self.idx = idx
        # which chain group this node validates (multi-chain simnet:
        # group g runs chain net.chain_ids[g]; meshes never cross)
        self.group = group
        self.app_factory = app_factory
        self.priv = priv
        self.home = home
        self.registry = fp.fresh_registry(fp.simulated_crash)
        self.transport = SimTransport(net, idx, self._on_conn)
        self.conns: Dict[int, SimConn] = {}  # peer idx -> outbound conn
        # gossip observatory: one per node, surviving restarts — the
        # same always-on ledger a real Switch carries, on the virtual
        # clock (byte-identical across replays)
        self.peer_ledger = peerledger.PeerLedger()
        self.node = None
        self.alive = False
        self.crashed = False
        self.restarts = 0
        self.equivocate_budget = 0
        self.garbage_budget = 0
        # height -> committed block hash, recorded as the chain grows
        # (survives kills: read from the store before it closes)
        self.commit_hashes: Dict[int, bytes] = {}
        # recent own votes (real, as signed) for sync-tick retransmission
        self._own_votes: deque = deque(maxlen=8)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Build the real Node and bring its consensus up WITHOUT the
        receive-routine thread — node/node.py on_start minus every
        thread, so the scheduler owns all execution."""
        from cometbft_tpu.node.node import Node
        from cometbft_tpu.privval.file_pv import FilePV

        with self.net._node_scope(self):
            # apps that persist (the bootstrap soak's KV-with-snapshots)
            # get the node's home dir so a restart reopens THEIR state;
            # plain factories (KVStoreApplication) take no kwargs
            try:
                app = self.app_factory(home=self.home)
            except TypeError:
                app = self.app_factory()
            self.node = Node(
                app, self.net.geneses[self.group].copy(),
                privval=FilePV(self.priv), home=self.home,
                broadcast=self._broadcast, timeouts=self.net.timeouts,
            )
            cs = self.node.consensus
            cs.ticker = SimTicker(self.net, self)
            cs.on_evidence = self._gossip_own_evidence
            # on_start is bypassed (the scheduler owns execution), so
            # register the height ledger here: incident snapshots and
            # /dump_heights read the module global — last-started node
            # wins, which is deterministic under the scheduler
            from cometbft_tpu.consensus import heightledger

            heightledger.set_global_ledger(cs.height_ledger)
            # the net/sign late-signer join + last-started-wins global
            # registration (incident snapshots, replay-blob tails)
            cs.height_ledger.peer_ledger = self.peer_ledger
            peerledger.set_global_ledger(self.peer_ledger)
            # mark the service running without spawning its thread: the
            # scheduler pumps the queues the thread would have drained
            with cs._lock:
                cs._started = True
            self.alive = True
            self.crashed = False
            if cs._wal_path:
                cs._catchup_replay()
            cs.internal_queue.put(("start_round", cs.height, 0))
        self.transport.listen()

    def _on_conn(self, conn: SimConn) -> None:
        self.conns[conn.dst] = conn

    def connect_full_mesh(self) -> None:
        """Full mesh WITHIN this node's chain group: independent chains
        share the process (and the verify plane) but never a link."""
        for j, other in enumerate(self.net.nodes):
            if j != self.idx and other.group == self.group \
                    and other.alive and j not in self.conns:
                self.transport.dial(j)

    def halt(self, reason: str) -> None:
        """Crash landing: no graceful teardown beyond releasing file
        handles (sqlite commits are already durable; the WAL close is
        the same best-effort close consensus._halt performs)."""
        if not self.alive:
            return
        _log.warning("simnet node %d halted: %s", self.idx, reason)
        tracing.instant("simnet.halt", cat="simnet", node=self.idx)
        self._record_commits()
        self.alive = False
        self.crashed = True
        cs = self.node.consensus
        with cs._lock:
            cs._stopped = True
        cs.ticker.stop()
        for c in self.conns.values():
            c.close()
        self.conns.clear()
        try:
            if cs.wal:
                cs.wal.close()
        except Exception:  # noqa: BLE001 - crash path, best-effort
            pass
        self._close_stores()

    def restart(self) -> None:
        """Rebuild over the same home dir: handshake replay feeds the
        stores back into a fresh app, consensus catchup-replays its WAL
        — the recovery path PR 1's kill matrix hardened, now driven
        mid-simulation."""
        assert not self.alive, "restart of a live node"
        self.restarts += 1
        tracing.instant("simnet.restart", cat="simnet", node=self.idx)
        self.start()
        self.connect_full_mesh()
        for other in self.net.nodes:
            if other.idx != self.idx and other.alive:
                other.connect_full_mesh()
        self.net._pump(self)

    def stop(self) -> None:
        """Graceful teardown at end of run."""
        if not self.alive:
            return
        self._record_commits()
        self.alive = False
        cs = self.node.consensus
        with cs._lock:
            cs._stopped = True
        cs.ticker.stop()
        if cs.wal:
            cs.wal.close()
        self._close_stores()

    def _close_stores(self) -> None:
        n = self.node
        try:
            n.indexer_service.stop()
        except Exception:  # noqa: BLE001 - service thread may be gone
            pass
        for closer in (n.block_store.close, n.state_store.close,
                       n.tx_indexer.close, n.block_indexer.close):
            try:
                closer()
            except Exception:  # noqa: BLE001 - already closed
                pass

    # -- chain observation -------------------------------------------------

    def height(self) -> int:
        if self.node is None:
            return 0
        return self.node.consensus.state.last_block_height

    def _record_commits(self) -> None:
        """Record committed block hashes while the store is open."""
        if self.node is None:
            return
        h = self.height()
        start = max(1, max(self.commit_hashes, default=0) + 1)
        for hh in range(start, h + 1):
            try:
                blk = self.node.block_store.load_block(hh)
            except Exception:  # noqa: BLE001 - store closing
                return
            if blk is not None:
                self.commit_hashes[hh] = blk.hash()

    # -- outbound ----------------------------------------------------------

    def _broadcast(self, msg) -> None:
        """ConsensusState's broadcast seam; runs inside a pump."""
        kind, payload = msg
        if kind == "vote":
            self._own_votes.append(payload)  # the REAL vote, as signed
            for data in self._vote_wire_msgs(payload):
                self._send_all(VOTE_CHANNEL, data)
        elif kind == "proposal":
            self._send_all(DATA_CHANNEL, _proposal_bytes(payload))

    def retransmit_votes(self) -> None:
        """Re-send current-height own votes (the gossipVotesRoutine
        analog, reactor.go:737): one-shot transmissions lost to drops,
        partitions, or a garbage-signing phase must eventually be
        replaced by the stored REAL votes, or rounds wedge forever with
        every validator waiting on votes nobody will resend. Goes back
        through the actor pipeline, so an active garbage budget garbles
        retransmissions too — recovery starts when the budget runs dry,
        exactly like a byzantine phase ending."""
        if not self.alive:
            return
        h = self.node.consensus.height
        for vote in list(self._own_votes):
            if vote.height != h:
                continue
            for data in self._vote_wire_msgs(vote):
                self._send_all(VOTE_CHANNEL, data)

    def _vote_wire_msgs(self, vote) -> List[bytes]:
        """Apply byzantine actor knobs to one outgoing own-vote."""
        from cometbft_tpu.simnet import actors

        if self.garbage_budget > 0:
            self.garbage_budget -= 1
            return [_vote_bytes(actors.garbage_sign(vote, self.net.rng))]
        out = [_vote_bytes(vote)]
        if self.equivocate_budget > 0 and not vote.block_id.is_nil():
            self.equivocate_budget -= 1
            out.append(_vote_bytes(actors.conflicting_vote(
                vote, self.priv, self.net.chain_ids[self.group]
            )))
        return out

    def _send_all(self, chan_id: int, data: bytes,
                  except_peer: Optional[int] = None) -> None:
        for j, conn in self.conns.items():
            if j != except_peer:
                conn.send(chan_id, data)

    def _gossip_own_evidence(self, ev) -> None:
        """consensus.on_evidence: push locally-discovered evidence
        (evidence/reactor.py broadcast_evidence analog)."""
        self._send_all(EVIDENCE_CHANNEL,
                       json.dumps(evidence_to_j(ev)).encode())


class SimNetwork:
    """The hub: event queue, links, clock, and N SimNodes.

    Multi-chain hosting (`n_chains` > 1): the net carries K independent
    chain groups of `n_nodes` validators each — per-group chain_id,
    genesis, and keys; full mesh within a group, no links across — all
    pumped by the ONE scheduler. The groups share the process, which
    means they share a process-global verify plane when a test mounts
    one: K chains' signature work coalescing into single fused flushes
    is exactly the multi-tenant hosting story verifyplane/tenants.py
    implements, and group g is key-identical to a solo net seeded
    seed+g so its commits can be diffed against a solo run.

    Epoch-scale churn (`extra_validators` > 0): beyond the N running
    node-validators, the network carries a deterministic POOL of
    passive tail validators — pubkey-only members (hash-derived 32-byte
    keys; they never vote, so no curve math is ever paid for them) with
    stake weights. A proportional election (simnet/actors.py, the
    arXiv 2004.12990 rule) seats `committee_size` of them at genesis
    and the ``epoch`` schedule op re-elects K% of that committee per
    epoch through kvstore ``val:`` txs — i.e. through the REAL
    ABCI -> update_with_change_set -> state/execution.py rotation
    path on every node. Node-validators hold a supermajority of power
    by construction (checked at init), so the passive tail can churn
    freely without wedging quorum — exactly the production shape where
    a handful of big operators stay while the long tail re-elects."""

    def __init__(self, n_nodes: int, seed: int, basedir: str,
                 app_factory=None, timeouts=None, chain_id: str = "simnet",
                 power: int = 10, extra_validators: int = 0,
                 committee_size: Optional[int] = None,
                 n_chains: int = 1):
        import hashlib
        import os

        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.consensus.ticker import TimeoutParams
        from cometbft_tpu.crypto.keys import PrivKey, PubKey
        from cometbft_tpu.state.state import State
        from cometbft_tpu.types.validator import Validator, ValidatorSet

        # multi-chain simnet (the appchain-hosting shape): K chain
        # groups of n_nodes each, every group a fully independent chain
        # — own chain_id, own genesis, own validator keys, links only
        # within the group — all driven by ONE scheduler in ONE process,
        # so a process-global verify plane coalesces their signature
        # work exactly like a hosting pod would. n_nodes is PER CHAIN.
        self.n_chains = max(1, int(n_chains))
        self.n_per_chain = n_nodes
        if self.n_chains > 1 and extra_validators:
            raise ValueError(
                "extra_validators (epoch churn) supports single-chain "
                "simnets only — the tail pool and election state are "
                "per-network, not per-group")
        self.seed = seed
        self.rng = random.Random(seed)
        self.now = 0.0
        self._seq = 0
        self.events: list = []  # heap of (time, seq, fn, label)
        self.chain_id = chain_id
        self.chain_ids = ([chain_id] if self.n_chains == 1 else
                          [f"{chain_id}-{g}"
                           for g in range(self.n_chains)])
        # Sim seconds are free; REAL work per height (WAL fsyncs, sqlite
        # commits, host-path signature verifies) is not. The commit
        # timeout paces the chain relative to schedule windows — 0.25
        # keeps a height comfortably longer than the default link delay
        # while preventing schedules measured in sim-seconds from
        # burning dozens of wall-clock-expensive heights.
        self.timeouts = timeouts or TimeoutParams(
            propose=1.0, propose_delta=0.25,
            prevote=0.5, prevote_delta=0.25,
            precommit=0.5, precommit_delta=0.25,
            commit=0.25,
        )
        # chain g's keys derive from (seed + g, local index): group g
        # of a K-chain net is KEY-IDENTICAL to a solo single-chain net
        # built with seed seed+g — which is what lets the coalescing
        # acceptance compare a chain's commits on the shared plane
        # against the same chain run alone, byte for byte. n_chains=1
        # reduces to the original derivation exactly.
        self.privs = [
            PrivKey.generate(
                (((seed + i // n_nodes) % 2**32)
                 .to_bytes(4, "big"))              # seeds are arbitrary
                + bytes([i % n_nodes + 1]) + b"\x51" * 27  # replay blobs
            )
            for i in range(n_nodes * self.n_chains)
        ]
        val_lists = [
            [Validator(p.pub_key(), power)
             for p in self.privs[g * n_nodes:(g + 1) * n_nodes]]
            for g in range(self.n_chains)
        ]
        val_list = val_lists[0]
        # passive tail pool + proportional genesis committee (the
        # epoch-rotation surface; see the class docstring)
        self.tail_pubs: List[bytes] = []
        self.tail_stakes: Dict[int, tuple] = {}
        self.epoch_state: Optional[Dict] = None
        if extra_validators > 0:
            from cometbft_tpu.simnet import actors

            self.tail_pubs = [
                hashlib.sha256(
                    b"simnet-tail-%d-%d" % (seed % 2**32, i)
                ).digest()
                for i in range(extra_validators)
            ]
            self.tail_stakes = {
                i: (self.tail_pubs[i], 1 + i % 7)
                for i in range(extra_validators)
            }
            size = min(committee_size or max(1, extra_validators // 2),
                       extra_validators)
            total_stake = sum(s for _, s in self.tail_stakes.values())
            if n_nodes * power <= 2 * total_stake:
                raise ValueError(
                    f"node power {n_nodes}x{power} must exceed 2x the "
                    f"tail stake total {total_stake}: the passive tail "
                    f"never votes, so it must never hold a blocking "
                    f"1/3 — raise `power` (the churn tests use 10^5+)"
                )
            ranked = sorted(
                range(extra_validators),
                key=lambda i: actors.election_score(
                    seed, 0, *self.tail_stakes[i]),
                reverse=True,
            )
            committee = sorted(ranked[:size])
            self.epoch_state = {
                "epoch": 0, "size": size,
                "committee": committee,
                "standby": sorted(ranked[size:]),
            }
            val_list += [
                Validator(PubKey(self.tail_pubs[i], "ed25519"),
                          self.tail_stakes[i][1])
                for i in committee
            ]
        self.geneses = [
            State.make_genesis(
                self.chain_ids[g], ValidatorSet(val_lists[g]),
                genesis_time=Timestamp(SIM_EPOCH_SECONDS, 0),
            )
            for g in range(self.n_chains)
        ]
        self.genesis = self.geneses[0]  # single-chain callers' alias
        total = n_nodes * self.n_chains
        app_factory = app_factory or KVStoreApplication
        self.nodes = [
            SimNode(self, i, app_factory, self.privs[i],
                    os.path.join(basedir, f"n{i}"), group=i // n_nodes)
            for i in range(total)
        ]
        self.links: Dict[Tuple[int, int], Link] = {
            (i, j): Link()
            for i in range(total) for j in range(total) if i != j
        }
        self.sync_interval = 0.5  # catch-up push cadence, sim seconds
        self._clock_installed = False

    def group_nodes(self, g: int) -> List[SimNode]:
        """The SimNodes validating chain group g (chain_ids[g])."""
        return [n for n in self.nodes if n.group == g]

    # -- clock + scheduler -------------------------------------------------

    def _sim_now(self) -> Timestamp:
        ns = int(round(self.now * 1_000_000_000))
        return Timestamp(SIM_EPOCH_SECONDS + ns // 1_000_000_000,
                         ns % 1_000_000_000)

    def _install_clock(self) -> None:
        if not self._clock_installed:
            set_now_source(self._sim_now)
            # traces run on the virtual clock too: every span/instant
            # timestamp is Timestamp.now().to_ns() = a deterministic
            # function of the schedule, so the same (seed, schedule)
            # exports an IDENTICAL trace
            tracing.set_clock(lambda: Timestamp.now().to_ns())
            self._clock_installed = True

    def _uninstall_clock(self) -> None:
        if self._clock_installed:
            set_now_source(None)
            tracing.set_clock(None)
            self._clock_installed = False

    def schedule(self, delay: float, fn: Callable[[], None],
                 label: str = "") -> None:
        self._seq += 1
        heapq.heappush(self.events,
                       (self.now + max(0.0, delay), self._seq, fn, label))

    @contextmanager
    def _node_scope(self, node: SimNode):
        """Execute with `node`'s failpoint registry installed (and the
        sim clock active)."""
        self._install_clock()
        old = fp.swap_registry(node.registry)
        try:
            yield
        finally:
            fp.swap_registry(old)

    # -- run loop ----------------------------------------------------------

    def start(self) -> None:
        self._install_clock()
        for n in self.nodes:
            n.start()
        for n in self.nodes:
            n.connect_full_mesh()
        for n in self.nodes:
            # first pump AFTER the mesh exists, so round-0 proposals and
            # votes actually reach peers
            self.schedule(0.0, lambda n=n: self._pump(n),
                          f"boot n{n.idx}")
        self.schedule(self.sync_interval, self._sync_tick, "sync")

    def run_until(self, cond: Optional[Callable[[], bool]] = None,
                  max_time: float = 120.0) -> bool:
        """Pop events until `cond()` holds or ABSOLUTE simulated time
        `max_time` is reached. Returns whether cond was met (True when
        cond is None and the loop ran out the clock)."""
        self._install_clock()
        while True:
            if cond is not None and cond():
                return True
            if not self.events:
                break
            t, _seq, fn, _label = self.events[0]
            if t > max_time:
                break
            heapq.heappop(self.events)
            self.now = max(self.now, t)
            fn()
        self.now = max(self.now, max_time)
        return cond() if cond is not None else True

    def close(self) -> None:
        for n in self.nodes:
            try:
                n.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                _log.exception("simnet node %d teardown failed", n.idx)
        self._uninstall_clock()

    # -- transport ---------------------------------------------------------

    def _send(self, src: int, dst: int, chan_id: int,
              payload: bytes, rec=None) -> bool:
        link = self.links[(src, dst)]
        if not link.up:
            # the partition is VISIBLE in the ledger: the lost message
            # is attributed to the partitioned peer, which is what the
            # chaos-soak acceptance asserts
            if rec is not None:
                peerledger.note_link_drop(rec)
            return False
        r = self.rng
        if link.drop > 0.0 and r.random() < link.drop:
            if rec is not None:
                peerledger.note_inj_drop(rec)
            return True  # accepted for delivery, silently lost
        if rec is not None:
            peerledger.note_sent(rec, chan_id, len(payload))
        delay = link.delay
        if link.jitter > 0.0:
            delay += link.jitter * r.random()
        if link.reorder > 0.0 and r.random() < link.reorder:
            # push far enough back that later sends overtake this one
            delay += link.reorder_window * (0.5 + r.random())
            if rec is not None:
                peerledger.note_inj_delay(rec)
        self.schedule(delay,
                      lambda: self._deliver(dst, chan_id, payload, src),
                      f"deliver {src}->{dst}")
        if link.dup > 0.0 and r.random() < link.dup:
            self.schedule(delay + link.delay,
                          lambda: self._deliver(dst, chan_id, payload,
                                                src),
                          f"dup {src}->{dst}")
        return True

    def _deliver(self, dst: int, chan_id: int, payload: bytes,
                 src: Optional[int] = None) -> None:
        node = self.nodes[dst]
        if not node.alive:
            return
        if src is not None:
            rec = node.peer_ledger.rec_for(f"n{src}")
            if rec is not None:
                peerledger.note_recv(rec, chan_id, len(payload))
        crash = None
        with self._node_scope(node):
            try:
                self._route(node, chan_id, payload, src)
            except fp.SimulatedCrash as e:
                crash = str(e)
            except Exception:  # noqa: BLE001 - hostile payload, log only
                _log.exception("simnet node %d dropped message on %#x",
                               dst, chan_id)
        if crash is not None:
            node.halt(crash)
            return
        self._pump(node)

    def _route(self, node: SimNode, chan_id: int, payload: bytes,
               src: Optional[int] = None) -> None:
        """Inbound demux — the reactors' receive() analog, minus the
        per-peer bookkeeping the flood model doesn't need."""
        cs = node.node.consensus
        j = json.loads(payload.decode())
        if chan_id == VOTE_CHANNEL:
            # the reactor's bare vote_to_j wire form
            vote = serde.vote_from_j(j)
            # vote-propagation attribution: first-seen stamp + the
            # delivering hop (duplicate deliveries — link.dup faults,
            # retransmissions — count as dup receipts), same seam as
            # ConsensusReactor._receive_vote; gated to the two heights
            # the ledger ever joins so junk keys can't pin the table
            if cs.height - 1 <= vote.height <= cs.height:
                node.peer_ledger.note_vote_seen(
                    (vote.height, vote.round, vote.vote_type,
                     vote.validator_index),
                    f"n{src}" if src is not None else "?")
            cs.receive_vote(vote)
        elif chan_id == DATA_CHANNEL:
            if j.get("t") == "commit_block":
                cs.receive_commit_block(
                    serde.block_from_json(j["b"]),
                    serde.commit_from_j(j["c"]),
                )
            else:
                prop = _proposal_from_bytes(j)
                block = serde.block_from_json(j["b"])
                cs.receive_proposal(ProposalMsg(prop, block))
        elif chan_id == EVIDENCE_CHANNEL:
            ev = evidence_from_j(j)
            try:
                fresh = node.node.evidence_pool.add_evidence(ev)
            except EvidenceError as e:
                _log.warning("simnet node %d rejected evidence: %s",
                             node.idx, e)
                return
            if fresh:
                # relay exactly like evidence/reactor.py receive():
                # everyone EXCEPT the peer it came from
                node._send_all(EVIDENCE_CHANNEL, payload,
                               except_peer=src)
        else:
            raise ValueError(f"unknown sim channel {chan_id:#x}")

    # -- the pump ----------------------------------------------------------

    def _pump(self, node: SimNode) -> None:
        """Drain the node's consensus queues — the receive routine's
        loop body (consensus/state.py _receive_routine), executed
        synchronously under the scheduler."""
        if not node.alive:
            return
        cs = node.node.consensus
        crash = None
        with self._node_scope(node):
            while crash is None:
                item = self._next_item(cs)
                if item is None:
                    break
                try:
                    cs._handle(item, write_wal=True)
                except fp.SimulatedCrash as e:
                    crash = str(e)
                except Exception:  # noqa: BLE001 - engine must not die
                    _log.exception("simnet node %d handler failed",
                                   node.idx)
        node._record_commits()
        if crash is not None:
            node.halt(crash)

    @staticmethod
    def _next_item(cs):
        import queue as _q

        try:
            return cs.internal_queue.get_nowait()
        except _q.Empty:
            pass
        try:
            return cs.msg_queue.get_nowait()
        except _q.Empty:
            return None

    # -- catch-up ----------------------------------------------------------

    def _sync_tick(self) -> None:
        """Periodic catch-up pushes: any node ahead of a connected,
        reachable peer pushes the decided block + seen commit for the
        peer's next height (consensus/reactor.py _send_catchup). This is
        what restores liveness after partitions heal and after node
        restarts — the votes for old heights are gone, the blocks are
        not. Same-height recovery rides the vote retransmission pass."""
        for src in self.nodes:
            src.retransmit_votes()
        for i, src in enumerate(self.nodes):
            if not src.alive:
                continue
            for jdx, conn in list(src.conns.items()):
                dst = self.nodes[jdx]
                if not dst.alive or not self.links[(i, jdx)].up:
                    continue
                want = dst.node.consensus.height
                if src.height() < want:
                    continue
                try:
                    block = src.node.block_store.load_block(want)
                    commit = src.node.block_store.load_seen_commit(want)
                except Exception:  # noqa: BLE001 - store mid-close
                    continue
                if block is None or commit is None:
                    continue
                conn.send(DATA_CHANNEL, json.dumps({
                    "t": "commit_block",
                    "b": serde.block_to_json(block),
                    "c": serde.commit_to_j(commit),
                }).encode())
        self.schedule(self.sync_interval, self._sync_tick, "sync")


# -- wire helpers ----------------------------------------------------------
# votes reuse the reactor's _vote_bytes verbatim (imported above); the
# proposal message is the reactor's proposal dict plus the whole block
# embedded as its pre-serialized string — one encode here, one decode on
# receive, exactly like the commit_block push (the reactor ships the
# block as PartSet chunks instead; see the module docstring)


def _proposal_bytes(pm: ProposalMsg) -> bytes:
    from cometbft_tpu.consensus import reactor as creactor

    j = json.loads(creactor._proposal_bytes(pm).decode())
    j["b"] = serde.block_to_json(pm.block)
    return json.dumps(j).encode()
