"""The simnet harness: schedules -> runs -> safety/liveness/evidence
assertions, with seed+schedule replay on every failure.

This is the scenario-coverage engine the ROADMAP's perf PRs validate
against: any consensus/evidence/verify-plane change can be driven
through partitions, byzantine actors, crashes, and failpoint faults in
deterministic, replayable simulated time.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from cometbft_tpu.libs import tracing
from cometbft_tpu.simnet import actors
from cometbft_tpu.simnet.core import EVIDENCE_CHANNEL, SimNetwork
from cometbft_tpu.simnet.schedule import (
    schedule_to_json,
    validate_schedule,
)


class SimnetFailure(AssertionError):
    """A simnet assertion failed. str() carries the replay blob — feed
    it back through Simnet(seed=...).run(schedule) (or
    tools/simnet_fuzz.py --replay). For single-run schedules (the
    fuzzer's shape) the rerun is byte-identical; a MULTI-phase scenario
    (several run() calls with mid-run assertions) additionally needs
    its phase boundaries — rerun the originating test, whose code IS
    that phase structure."""

    def __init__(self, msg: str, seed: int, schedule: List[Dict],
                 include_ledger: bool = True,
                 include_heights: bool = True,
                 include_incidents: bool = True,
                 include_peers: bool = True,
                 include_controller: bool = True):
        self.seed = seed
        self.schedule = schedule
        text = msg
        # when tracing is on, the tail of the span/event ring rides the
        # failure: the last thing the simulation did before wedging,
        # in order, on the virtual clock
        trace_tail = tracing.tail(40)
        if trace_tail:
            text += "\ntrace tail: " + " ".join(trace_tail)
        # the verify plane's always-on flush ledger needs no knob: if a
        # plane ran (or stopped) during this simulation, its last few
        # flushes ride the blob too — stage costs on the virtual clock.
        # The harness passes include_ledger=False when the ledger never
        # moved during ITS run (the module-global ledger survives
        # unrelated earlier planes in the same process — that history
        # would misdirect whoever debugs this blob).
        from cometbft_tpu import verifyplane

        led_tail = verifyplane.ledger_tail(8) if include_ledger else []
        if led_tail:
            text += "\nflush ledger tail: " + " | ".join(led_tail)
        # the always-on height ledger: where the last commits' latency
        # went (stage timeline on the virtual clock) — same move-mark
        # gating as the flush ledger
        from cometbft_tpu.consensus import heightledger
        from cometbft_tpu.libs import incidents

        h_tail = heightledger.ledger_tail(8) if include_heights else []
        if h_tail:
            text += "\nheight ledger tail: " + " | ".join(h_tail)
        # the gossip observatory's per-peer tail: which links were
        # eating/queueing messages when the run failed (same move-mark
        # gating as the other always-on ledgers)
        from cometbft_tpu.p2p import peerledger

        p_tail = peerledger.ledger_tail(8) if include_peers else []
        if p_tail:
            text += "\npeer ledger tail: " + " | ".join(p_tail)
        # the self-tuning control plane's decision tail: what the loop
        # moved (and in which direction) before the run failed — the
        # decisions are count-based on deterministic poke sites, so the
        # tail in a replayed blob matches the original byte for byte
        from cometbft_tpu.libs import controller as controlplane

        c_tail = controlplane.controller_tail(8) \
            if include_controller else []
        if c_tail:
            text += "\ncontroller decisions: " + " | ".join(c_tail)
        # incidents frozen DURING this simulation (commit stalls, round
        # escalations, ...) are first-class replay evidence
        inc_tail = incidents.incident_tail(4) if include_incidents \
            else []
        if inc_tail:
            text += "\nincidents: " + " | ".join(inc_tail)
        # the replay blob stays LAST: consumers (and the fuzzer) parse
        # everything after "replay:" as one JSON document
        text += f"\nreplay: {schedule_to_json(seed, schedule)}"
        super().__init__(text)


class Simnet:
    """Build-run-assert wrapper around :class:`SimNetwork`."""

    def __init__(self, n_nodes: int, seed: int, basedir: str, **kw):
        self.net = SimNetwork(n_nodes, seed, basedir, **kw)
        self.schedule: List[Dict] = []
        self._started = False
        # every flood-op CheckTx response, in injection order: the soak
        # scenarios assert overload verdicts are EXPLICIT (code +
        # retry hint), never silent drops
        self.flood_results: List[Dict] = []
        # every gateway_sync client verdict, in sync order — the
        # forged-header scenario asserts honest clients complete and
        # the whole verdict stream replays byte-identically
        self.gateway_results: List[Dict] = []
        # every epoch op's election outcome (who rotated out/in, how
        # many val txs were injected) — the churn soak asserts the
        # rotation stream replays byte-identically
        self.epoch_results: List[Dict] = []
        # flush-/height-ledger + incident positions at sim start:
        # failure blobs attach each tail only if it advanced during
        # THIS simulation
        from cometbft_tpu import verifyplane
        from cometbft_tpu.consensus import heightledger
        from cometbft_tpu.libs import controller as controlplane
        from cometbft_tpu.libs import incidents
        from cometbft_tpu.p2p import peerledger

        self._ledger_mark = verifyplane.ledger_mark()
        self._height_mark = heightledger.ledger_mark()
        self._incident_mark = incidents.incident_mark()
        self._peer_mark = peerledger.ledger_mark()
        self._controller_mark = controlplane.controller_mark()

    # -- running -----------------------------------------------------------

    def run(self, schedule: List[Dict],
            until: Optional[Callable[[], bool]] = None,
            until_height: Optional[int] = None,
            max_time: float = 120.0) -> bool:
        """Apply `schedule` and run simulated time forward until the
        condition holds (or `max_time` more simulated seconds pass).
        Reentrant: later run() calls continue the same simulation with
        additional schedule ops."""
        net = self.net
        validate_schedule(schedule, len(net.nodes))
        self.schedule = sorted(
            self.schedule + [dict(op) for op in schedule],
            key=lambda o: float(o["at"]),
        )
        if not self._started:
            self._started = True
            net.start()
        for op in schedule:
            delay = max(0.0, float(op["at"]) - net.now)
            net.schedule(delay, lambda op=op: self._apply(op),
                         f"op:{op['op']}")
        if until is None and until_height is not None:
            target = until_height
            # an open-loop flood is SUSTAINED traffic: reaching the
            # target height mid-window must not end the run, or the
            # soak would assert overload behavior against a flood that
            # never fully fired
            horizon = max(
                (float(o["at"]) + float(o.get("duration", 0.0))
                 for o in self.schedule if o["op"] == "flood"),
                default=0.0,
            )
            until = lambda: net.now >= horizon and all(  # noqa: E731
                n.height() >= target for n in net.nodes if n.alive
            ) and any(n.alive for n in net.nodes)
        return net.run_until(until, max_time=net.now + max_time)

    def close(self) -> None:
        self.net.close()

    def __enter__(self) -> "Simnet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- schedule ops ------------------------------------------------------

    def _apply(self, op: Dict) -> None:
        net = self.net
        kind = op["op"]
        # every fault-schedule op becomes a trace instant, so a trace
        # of a wedged run shows the perturbation timeline inline with
        # the consensus/WAL spans it perturbed
        tracing.instant("simnet.op", cat="simnet", op=kind,
                        at=float(op["at"]))
        if kind == "partition":
            groups = [set(g) for g in op["groups"]]
            group_of = {}
            for gi, g in enumerate(groups):
                for i in g:
                    group_of[i] = gi
            for (i, j), link in net.links.items():
                link.up = (i in group_of and j in group_of
                           and group_of[i] == group_of[j])
        elif kind == "heal":
            for link in net.links.values():
                link.up = True
                link.drop = link.dup = link.reorder = 0.0
                link.jitter = 0.0
        elif kind == "link":
            frm = op.get("frm")
            to = op.get("to")
            for (i, j), link in net.links.items():
                if frm is not None and i not in frm:
                    continue
                if to is not None and j not in to:
                    continue
                for key in ("drop", "delay", "jitter", "dup", "reorder"):
                    if key in op:
                        setattr(link, key, float(op[key]))
        elif kind == "kill":
            net.nodes[op["node"]].halt("schedule kill")
        elif kind == "restart":
            node = net.nodes[op["node"]]
            if not node.alive:
                node.restart()
        elif kind == "failpoint":
            net.nodes[op["node"]].registry.arm_from_spec(op["spec"])
        elif kind == "equivocate":
            net.nodes[op["node"]].equivocate_budget += int(
                op.get("votes", 1)
            )
        elif kind == "garbage":
            net.nodes[op["node"]].garbage_budget += int(op.get("votes", 1))
        elif kind == "light_attack":
            self._launch_light_attack(op)
        elif kind == "gateway_sync":
            self._launch_gateway_sync(op)
        elif kind == "tx":
            node = net.nodes[op["node"]]
            if node.alive:
                node.node.mempool.check_tx(bytes.fromhex(op["data"]))
        elif kind == "flood":
            self._launch_flood(op)
        elif kind == "epoch":
            self._launch_epoch(op)
        elif kind == "controller":
            self._launch_controller(op)

    # flood txs are signed with ONE deterministic throwaway key (a
    # function of nothing but this constant), so the same (seed,
    # schedule) floods byte-identical txs
    _FLOOD_KEY_SEED = b"simnet-flood-key" + b"\x00" * 16

    def _launch_flood(self, op: Dict) -> None:
        """Open-loop tx stream: rate*duration injections at FIXED sim
        times (injection never waits on a response — the open-loop
        discipline of test/loadtime), through the target node's full
        broadcast_tx path (admission control + sigtx verify via the
        BULK lane when signed + ABCI CheckTx)."""
        net = self.net
        idx = int(op["node"])
        rate = float(op["rate"])
        count = int(round(rate * float(op["duration"])))
        size = int(op.get("size", 16))
        signed = bool(op.get("signed", False))
        priv = sigtx = None
        if signed:
            from cometbft_tpu.crypto.keys import PrivKey
            from cometbft_tpu.mempool import sigtx

            priv = PrivKey.generate(self._FLOOD_KEY_SEED)
        base = len(self.flood_results)

        def inject(k: int, tx: bytes) -> None:
            node = net.nodes[idx]
            if not node.alive:
                self.flood_results.append(
                    {"seq": base + k, "at": net.now, "code": None,
                     "log": "target dead"})
                return
            with net._node_scope(node):
                resp = node.node.broadcast_tx(tx)
            self.flood_results.append(
                {"seq": base + k, "at": net.now, "code": resp.code,
                 "log": resp.log})
            net._pump(node)

        for k in range(count):
            payload = (b"flood-%d-%d=" % (idx, base + k)).ljust(
                size, b"x")
            tx = sigtx.wrap(priv, payload) if signed else payload
            net.schedule(k / rate, lambda k=k, tx=tx: inject(k, tx),
                         f"flood n{idx}")

    def _launch_controller(self, op: Dict) -> None:
        """Mount the self-tuning control plane on the target node:
        attached to that node's admission gate + height ledger (and
        the process-global verify plane, when a scenario started one),
        registered as THE module-global controller so the consensus-
        step pokes start deciding. Decisions are count-based on
        deterministic poke sites (the dispatcher-drain seam only ever
        moves the flight deck, whose grow signal needs fused device
        flushes no simnet plane produces), so the decision stream is a
        pure function of (seed, schedule)."""
        import sys

        from cometbft_tpu.libs import controller as controlplane

        net = self.net
        snode = net.nodes[int(op["node"])]
        if not snode.alive:
            return
        kwargs = {k: v for k, v in op.items()
                  if k not in ("at", "op", "node", "bounds")}
        ctl = controlplane.Controller(**kwargs)
        vp = sys.modules.get("cometbft_tpu.verifyplane.plane")
        plane = vp._GLOBAL if vp is not None else None
        # JSON bounds arrive as {actuator: [lo, hi]} — without them
        # every actuator clamps to (base, base) and the mounted loop
        # observes but never moves
        bounds = {name: (float(b[0]), float(b[1]))
                  for name, b in (op.get("bounds") or {}).items()}
        ctl.attach(
            plane=plane,
            admission=snode.node.mempool.admission,
            height_ledger=snode.node.consensus.height_ledger,
            bounds=bounds,
        )
        controlplane.set_global_controller(ctl)
        snode.node.controller = ctl

    def _launch_epoch(self, op: Dict) -> None:
        """One epoch of proportional committee re-election over the
        network's passive validator tail. The deterministic election
        (actors.proportional_election, a pure function of (seed, epoch
        index, committee)) picks who rotates; the change set becomes
        kvstore ``val:`` txs injected into EVERY alive node's mempool
        (simnet mempools don't gossip, and whichever node proposes next
        must carry the rotation), flowing through the real
        ABCI validator-update -> update_with_change_set ->
        state/execution.py path — the valset rotates at H+2 and
        commits stay byte-identical across replays."""
        import base64

        net = self.net
        rec: Dict = {"seq": len(self.epoch_results), "at": net.now}
        st = net.epoch_state
        if st is None:
            rec["error"] = ("no validator tail pool — build the "
                            "Simnet with extra_validators > 0")
            self.epoch_results.append(rec)
            return
        st["epoch"] += 1
        churn = float(op.get("churn", 0.25))
        committee, standby, out, inn = actors.proportional_election(
            net.seed, st["epoch"], st["committee"], st["standby"],
            net.tail_stakes, churn,
        )
        st["committee"], st["standby"] = committee, standby
        # the !e<epoch> nonce keeps repeat rotations of one validator
        # byte-distinct, so mempool replay protection can't swallow a
        # later epoch's change as a dup of an earlier one
        nonce = b"!e%d" % st["epoch"]
        txs = [b"val:" + base64.b64encode(net.tail_pubs[i]) + b"!0"
               + nonce for i in out]
        txs += [b"val:" + base64.b64encode(net.tail_pubs[i])
                + b"!%d" % net.tail_stakes[i][1] + nonce for i in inn]
        # the named node's verdicts ride the record; a dead target
        # falls to the next alive index (deterministic, so the replay
        # stream is too) — rotation-while-killed must still rotate
        codes: List = []
        target = int(op["node"])
        alive = [n.idx for n in net.nodes if n.alive]
        rec_idx = next((i for i in alive if i >= target),
                       alive[0] if alive else None)
        for node in net.nodes:
            if not node.alive:
                continue
            with net._node_scope(node):
                for tx in txs:
                    try:
                        r = node.node.mempool.check_tx(tx)
                        code = getattr(r, "code", 0)
                    except Exception as e:  # noqa: BLE001 - recorded
                        code = repr(e)[:80]
                    if node.idx == rec_idx:
                        codes.append(code)
            net._pump(node)
        rec.update({"epoch": st["epoch"], "churn": churn,
                    "out": list(out), "in": list(inn),
                    "txs": len(txs), "codes": codes,
                    "committee_size": len(committee)})
        self.epoch_results.append(rec)

    def _launch_gateway_sync(self, op: Dict) -> None:
        """Mount a light-client gateway on the target node and drive K
        client syncs through it at fixed sim times. Synchronous on the
        scheduler thread (no plane runs in the simnet, so gateway
        verification takes the inline host path) — same (seed,
        schedule) therefore yields a byte-identical verdict stream.
        Forged clients submit a lying-primary claim; the gateway's
        divergence path feeds the node's evidence pool and the evidence
        gossips like the node's own (consensus-found) evidence would."""
        net = self.net
        idx = int(op["node"])
        snode = net.nodes[idx]
        if not snode.alive:
            return
        from cometbft_tpu.lightgate import LightGateway

        gw = getattr(snode, "lightgate", None)
        if gw is None:
            with net._node_scope(snode):
                gw = LightGateway.for_node(snode.node)
                gw.start(register=False)
            snode.lightgate = gw
            gw.on_attack_evidence = snode._gossip_own_evidence
        clients = int(op["clients"])
        trusted = int(op.get("trusted", 1))
        target = int(op["target"])
        forged = {int(i) for i in op.get("forged", [])}
        claim = None
        if forged:
            claim = actors.forged_claim(
                net.privs, net.genesis.validators, net.chain_id,
                [int(i) for i in op["byz"]], target, net._sim_now(),
            )
        base = len(self.gateway_results)

        def sync(k: int) -> None:
            if not snode.alive:
                self.gateway_results.append(
                    {"seq": base + k, "at": net.now, "status": None,
                     "log": "gateway node dead"})
                return
            with net._node_scope(snode):
                try:
                    v = gw.verify(trusted, target,
                                  claimed=claim if k in forged else None)
                    rec = {"seq": base + k, "at": net.now,
                           "status": v["status"],
                           "target_hash": v["target_hash"],
                           "cached": v["cached"],
                           "evidence_added": v.get("evidence_added")}
                except Exception as e:  # noqa: BLE001 - verdict stream
                    rec = {"seq": base + k, "at": net.now,
                           "status": "error", "log": repr(e)[:200]}
            self.gateway_results.append(rec)
            net._pump(snode)

        for k in range(clients):
            net.schedule(k * 0.002, lambda k=k: sync(k),
                         f"gateway_sync n{idx}")

    def _launch_light_attack(self, op: Dict) -> None:
        net = self.net
        target = net.nodes[op["target"]]
        if not target.alive:
            return
        height = int(op.get("height", 1))
        ev = actors.build_light_attack(
            net.privs, net.genesis.validators, net.chain_id,
            [int(i) for i in op["byz"]], height, net._sim_now(),
        )
        import json

        from cometbft_tpu.types.evidence import evidence_to_j

        net._deliver(target.idx, EVIDENCE_CHANNEL,
                     json.dumps(evidence_to_j(ev)).encode())

    # -- assertions --------------------------------------------------------

    def _fail(self, msg: str) -> "SimnetFailure":
        from cometbft_tpu import verifyplane
        from cometbft_tpu.consensus import heightledger
        from cometbft_tpu.libs import controller as controlplane
        from cometbft_tpu.libs import incidents
        from cometbft_tpu.p2p import peerledger

        return SimnetFailure(
            msg, self.net.seed, self.schedule,
            include_ledger=verifyplane.ledger_advanced(self._ledger_mark),
            include_heights=heightledger.ledger_advanced(
                self._height_mark),
            include_incidents=incidents.incident_advanced(
                self._incident_mark),
            include_peers=peerledger.ledger_advanced(self._peer_mark),
            include_controller=controlplane.controller_advanced(
                self._controller_mark),
        )

    def commit_hashes(self) -> List[Dict[int, bytes]]:
        """Per-node height -> committed block hash (incl. killed nodes'
        pre-crash history)."""
        for n in self.net.nodes:
            if n.alive:
                n._record_commits()
        return [dict(n.commit_hashes) for n in self.net.nodes]

    def assert_safety(self) -> None:
        """No two nodes ever committed different blocks at one height."""
        per_node = self.commit_hashes()
        agreed: Dict[int, bytes] = {}
        owner: Dict[int, int] = {}
        for idx, hashes in enumerate(per_node):
            for h, bh in hashes.items():
                if h in agreed and agreed[h] != bh:
                    raise self._fail(
                        f"SAFETY VIOLATION at height {h}: node "
                        f"{owner[h]} committed {agreed[h].hex()[:16]}, "
                        f"node {idx} committed {bh.hex()[:16]}"
                    )
                agreed.setdefault(h, bh)
                owner.setdefault(h, idx)

    def assert_liveness(self, min_new_heights: int = 2,
                        max_time: float = 30.0) -> None:
        """After the schedule (heal included), the chain must still
        grow: every ALIVE node gains >= min_new_heights. Requires a
        live quorum — with > 1/3 of power dead the assertion is
        vacuous and raises a schedule error instead."""
        net = self.net
        alive = [n for n in net.nodes if n.alive]
        if 3 * len(alive) <= 2 * len(net.nodes):
            raise self._fail(
                "liveness asserted without a live 2/3 quorum "
                f"({len(alive)}/{len(net.nodes)} alive)"
            )
        floor = min(n.height() for n in alive)
        target = floor + min_new_heights
        ok = net.run_until(
            lambda: all(n.height() >= target
                        for n in net.nodes if n.alive),
            max_time=net.now + max_time,
        )
        if not ok:
            heights = {n.idx: n.height() for n in net.nodes if n.alive}
            raise self._fail(
                f"LIVENESS failure: wanted height {target} on every "
                f"live node within {max_time}s sim time, got {heights}"
            )

    def assert_evidence_committed(self, predicate=None,
                                  max_time: float = 30.0) -> object:
        """Run until some node's committed chain contains evidence
        (optionally matching `predicate`); returns the evidence object.
        Every node must then reach that height with the same block."""
        net = self.net
        found: list = []
        scanned: Dict[int, int] = {}  # node idx -> last height scanned

        def scan() -> bool:
            for n in net.nodes:
                if not n.alive:
                    continue
                tip = n.height()
                for h in range(scanned.get(n.idx, 0) + 1, tip + 1):
                    scanned[n.idx] = h
                    blk = n.node.block_store.load_block(h)
                    if blk is None or not blk.evidence:
                        continue
                    for ev in blk.evidence:
                        if predicate is None or predicate(ev):
                            found.append((n.idx, h, ev))
                            return True
            return False

        if not net.run_until(scan, max_time=net.now + max_time):
            sizes = {n.idx: n.node.evidence_pool.size()
                     for n in net.nodes if n.alive}
            raise self._fail(
                f"EVIDENCE never committed (pending pools: {sizes})"
            )
        idx, h, ev = found[0]
        # committed on every live node, same block
        ref = self.net.nodes[idx].node.block_store.load_block(h).hash()
        ok = net.run_until(
            lambda: all(n.height() >= h for n in net.nodes if n.alive),
            max_time=net.now + max_time,
        )
        if not ok:
            raise self._fail(
                f"evidence block {h} not replicated to every live node"
            )
        for n in net.nodes:
            if not n.alive:
                continue
            blk = n.node.block_store.load_block(h)
            if blk is None or blk.hash() != ref:
                raise self._fail(
                    f"node {n.idx} disagrees on evidence block {h}"
                )
        # the pool moved it pending -> committed
        key = ev.hash()
        for n in net.nodes:
            if n.alive and key in n.node.evidence_pool._pending:
                raise self._fail(
                    f"node {n.idx} still holds committed evidence as "
                    f"pending"
                )
        return ev
