"""Byzantine validator actors for the simnet.

Three behaviors from the adversarial-consensus literature
(arXiv:2302.00418 treats equivocation detection and batch verification
of adversarial inputs as first-class; CometBFT's e2e runner injects the
same classes):

  * equivocator — double-signs prevotes/precommits. Honest nodes must
    surface it as DuplicateVoteEvidence (consensus/height_vote_set.py
    conflict detection -> evidence/pool.py -> block inclusion ->
    mark_committed).
  * garbage signer — gossips syntactically-valid votes with forged
    signatures. The verify path (host or verify plane) must reject them
    without poisoning coalesced batches and without tripping the
    circuit breaker (a bad SIGNATURE is a verdict, not a device fault).
  * light-client attacker — a >=1/3 coalition signs a forged header at
    a committed height; the resulting LightClientAttackEvidence (with
    its conflicting-commit proof attached) must pass
    verify_light_client_attack on honest nodes and flow through the
    same pool -> block -> mark_committed pipeline.
"""
from __future__ import annotations

import hashlib
from dataclasses import replace
from typing import List

from cometbft_tpu.types import canonical
from cometbft_tpu.types.block_id import BlockID, PartSetHeader
from cometbft_tpu.types.commit import (
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    CommitSig,
)
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.timestamp import Timestamp
from cometbft_tpu.types.vote import Vote


def election_score(seed: int, epoch: int, pub: bytes, stake: int) -> float:
    """Deterministic stake-weighted sampling key (Efraimidis–Spirakis
    A-Res): u^(1/stake) with u drawn from a hash of (seed, epoch, pub).
    Ranking the pool by this key descending IS a proportional weighted
    sample without replacement — a member's selection probability is
    proportional to its stake, the committee-election property the
    proportional rule of arXiv 2004.12990 targets. Pure function of its
    arguments: the same (seed, schedule) elects the same committees in
    every replay."""
    h = hashlib.sha256(
        b"simnet-election" + seed.to_bytes(8, "big", signed=True)
        + epoch.to_bytes(8, "big") + pub
    ).digest()
    u = (int.from_bytes(h[:8], "big") + 1) / float(2 ** 64 + 1)
    return u ** (1.0 / max(1, int(stake)))


def proportional_election(seed: int, epoch: int, committee, standby,
                          stakes, churn: float):
    """One epoch of deterministic proportional committee election with
    bounded churn.

    committee / standby: disjoint lists of pool member indices;
    stakes: {index: (pub_bytes, stake)} — scores key on the member's
    PUBKEY so an index renumbering can never re-seed the draw; churn:
    fraction of the committee re-elected this epoch. The K = round(churn * size) sitting members
    with the WORST stake-weighted score this epoch rotate out and the
    K best-scoring standby members rotate in (so every seat turnover is
    itself a proportional draw). Returns (new_committee, new_standby,
    rotated_out, rotated_in) — all index lists, sorted for determinism.

    This is the election half of the simnet epoch driver; the harness
    turns the rotation into kvstore ``val:`` txs so the change set
    flows through the REAL ABCI -> update_with_change_set ->
    state/execution.py pipeline on every node."""
    committee = sorted(int(i) for i in committee)
    standby = sorted(int(i) for i in standby)
    size = len(committee)
    k = min(int(round(max(0.0, float(churn)) * size)), size,
            len(standby))
    if k == 0 or not committee:
        return committee, standby, [], []

    def score(i: int) -> float:
        return election_score(seed, epoch, stakes[i][0], stakes[i][1])

    out = sorted(sorted(committee, key=score)[:k])
    inn = sorted(sorted(standby, key=score, reverse=True)[:k])
    new_committee = sorted(set(committee) - set(out) | set(inn))
    new_standby = sorted(set(standby) - set(inn) | set(out))
    return new_committee, new_standby, out, inn


def _fake_block_id(tag: bytes) -> BlockID:
    h = hashlib.sha256(b"simnet-byzantine-" + tag).digest()
    return BlockID(h, PartSetHeader(1, h))


def conflicting_vote(vote: Vote, priv, chain_id: str) -> Vote:
    """The equivocator's second signature: same (height, round, type),
    different block ID, properly signed with the RAW private key —
    FilePV would refuse (privval/file_pv.py double-sign guard), which is
    precisely why a byzantine signer doesn't use it."""
    bad = replace(
        vote,
        block_id=_fake_block_id(b"%d-%d-%d" % (
            vote.height, vote.round, vote.vote_type
        )),
        signature=b"", extension=b"", extension_signature=b"",
    )
    bad.signature = priv.sign(bad.sign_bytes(chain_id))
    return bad


def garbage_sign(vote: Vote, rng) -> Vote:
    """The garbage signer's output: the vote with a seeded-random 64-byte
    forgery in place of the signature (still structurally valid, so it
    reaches signature verification — and, when a verify plane runs,
    coalesces into shared device batches)."""
    return replace(vote, signature=bytes(rng.getrandbits(8)
                                         for _ in range(64)))


def build_light_attack(privs, valset, chain_id: str,
                       byz_idxs: List[int], height: int,
                       now: Timestamp) -> LightClientAttackEvidence:
    """Forge a conflicting header at `height` sealed by the byzantine
    coalition, and package it as LightClientAttackEvidence with the
    commit proof attached.

    The coalition must hold >= 1/3 of the voting power at `height` for
    the evidence to verify (types/validation.py
    verify_commit_light_trusting with the default (1, 3) trust level) —
    the same threshold a real light-client attack needs."""
    forged = hashlib.sha256(
        b"simnet-forged-header-%d" % height
    ).digest()
    bid = BlockID(forged, PartSetHeader(1, forged))
    sigs = [CommitSig.absent() for _ in range(len(valset))]
    byz_addrs = []
    for idx in byz_idxs:
        priv = privs[idx]
        addr = priv.pub_key().address()
        vidx, val = valset.get_by_address(addr)
        assert val is not None, "byzantine index not in validator set"
        v = Vote(
            vote_type=canonical.PRECOMMIT_TYPE, height=height, round=0,
            block_id=bid, timestamp=now, validator_address=addr,
            validator_index=vidx,
        )
        sigs[vidx] = CommitSig(
            BLOCK_ID_FLAG_COMMIT, addr, now,
            priv.sign(v.sign_bytes(chain_id)),
        )
        byz_addrs.append(addr)
    return LightClientAttackEvidence(
        conflicting_header_hash=forged,
        conflicting_height=height,
        common_height=height,
        byzantine_validators=byz_addrs,
        total_voting_power=valset.total_voting_power(),
        timestamp=now,
        conflicting_commit=Commit(height, 0, bid, sigs),
    )


def forged_claim(privs, valset, chain_id: str, byz_idxs: List[int],
                 height: int, now: Timestamp) -> dict:
    """The wire-shaped claim a light client deceived by a lying primary
    submits to `lightgate_verify`: a forged header at `height` plus the
    byzantine coalition's commit sealing it ({"header": .., "commit":
    ..} in serde JSON form). Unlike :func:`build_light_attack` this is
    the RAW divergent view — the GATEWAY turns it into
    LightClientAttackEvidence through the light client's
    _make_attack_evidence path, which is exactly the seam the scenario
    exercises."""
    from cometbft_tpu.types import serde
    from cometbft_tpu.types.block import Header

    header = Header(
        chain_id=chain_id, height=height, time=now,
        last_block_id=BlockID(),
        validators_hash=valset.hash(),
        next_validators_hash=valset.hash(),
        proposer_address=valset.validators[0].address,
        app_hash=hashlib.sha256(b"simnet-forged-app-%d" % height
                                ).digest(),
    )
    hh = header.hash()
    bid = BlockID(hh, PartSetHeader(1, hh))
    sigs = [CommitSig.absent() for _ in range(len(valset))]
    for idx in byz_idxs:
        priv = privs[idx]
        addr = priv.pub_key().address()
        vidx, val = valset.get_by_address(addr)
        assert val is not None, "byzantine index not in validator set"
        v = Vote(
            vote_type=canonical.PRECOMMIT_TYPE, height=height, round=0,
            block_id=bid, timestamp=now, validator_address=addr,
            validator_index=vidx,
        )
        sigs[vidx] = CommitSig(
            BLOCK_ID_FLAG_COMMIT, addr, now,
            priv.sign(v.sign_bytes(chain_id)),
        )
    return {
        "header": serde.header_to_j(header),
        "commit": serde.commit_to_j(Commit(height, 0, bid, sigs)),
    }
