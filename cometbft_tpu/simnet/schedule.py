"""Declarative fault schedules for the simnet.

A schedule is a JSON-serializable list of timed ops — the analog of
CometBFT's e2e perturbation plans (test/e2e/runner/perturb.go), but
deterministic and replayable: `(seed, schedule)` fully determines a run,
and every harness assertion failure prints both.

Op catalog (each op is a plain dict, `at` in simulated seconds):

  {"at": t, "op": "partition", "groups": [[0,1],[2,3]]}
      Nodes communicate only within their group (links across groups go
      down). Unlisted nodes are isolated.
  {"at": t, "op": "heal"}
      All links up, fault probabilities reset to zero.
  {"at": t, "op": "link", "frm": [..], "to": [..], "drop": p,
   "delay": s, "jitter": s, "dup": p, "reorder": p}
      Set fault parameters on the selected directed links (omit
      frm/to for all links; only the keys present are changed).
  {"at": t, "op": "kill", "node": i}
      Crash-halt node i (no graceful teardown; stores/WAL stay on disk).
  {"at": t, "op": "restart", "node": i}
      Rebuild node i over its home dir (WAL recovery + handshake replay).
  {"at": t, "op": "failpoint", "node": i, "spec": "name=action[..]"}
      Arm a libs/failpoints spec on node i's PRIVATE registry.
  {"at": t, "op": "equivocate", "node": i, "votes": k}
      Node i double-signs its next k own non-nil votes.
  {"at": t, "op": "garbage", "node": i, "votes": k}
      Node i's next k own votes leave with garbage signatures.
  {"at": t, "op": "light_attack", "byz": [..], "target": i,
   "height": h}
      Deliver a forged-header LightClientAttackEvidence (signed by the
      byz validators at height h) to node i as evidence gossip.
  {"at": t, "op": "tx", "node": i, "data": "<hex>"}
      Inject a transaction into node i's mempool.
  {"at": t, "op": "gateway_sync", "node": i, "clients": k,
   "trusted": h0, "target": h, "forged": [..], "byz": [..]}
      Mount a light-client gateway on node i (cometbft_tpu.lightgate)
      and drive k client syncs through it at fixed sim times: each
      client asks to verify `target` from `trusted`. Clients whose
      index is listed in "forged" submit a forged claimed header
      sealed by the "byz" validators (a lying-primary feed) — the
      gateway must answer them with divergent verdicts, push
      LightClientAttackEvidence through the node's evidence pool, and
      keep serving the honest clients. Every verdict is recorded on
      Simnet.gateway_results (replay-assertable).
  {"at": t, "op": "epoch", "node": i, "churn": k}
      One epoch of proportional committee re-election over the
      network's passive validator tail (SimNetwork extra_validators):
      the deterministic election (simnet/actors.proportional_election,
      seeded by (seed, epoch index)) rotates churn*committee_size
      members out/in, and the change set is injected as kvstore
      ``val:`` txs into every alive node's mempool starting at node i
      — so the rotation flows through the REAL ABCI validator-update
      -> ValidatorSet.update_with_change_set -> state/execution.py
      path and lands in the valset at H+2. Election outcomes are
      recorded on Simnet.epoch_results (replay-assertable); a network
      built without a tail records an error instead of perturbing
      nothing silently.
  {"at": t, "op": "flood", "node": i, "rate": txs_per_sim_second,
   "duration": s, "signed": bool, "size": payload_bytes}
      Open-loop sustained tx stream into node i's broadcast_tx path:
      rate*duration txs injected at FIXED simulated times (open-loop —
      injection never waits on responses, like test/loadtime). With
      "signed": true each tx rides a sigtx envelope (deterministic key)
      so CheckTx signature verification exercises the verify plane's
      BULK lane. Every CheckTx response is recorded on the harness
      (Simnet.flood_results) so overload verdicts are assertable.
  {"at": t, "op": "controller", "node": i, "slo_commit_p99_ms": ms,
   "decision_interval": k, "cooldown": c,
   "bounds": {actuator: [lo, hi]}, ...}
      Mount the self-tuning control plane (libs/controller.Controller)
      on node i: attached to that node's admission gate + height
      ledger and the process-global verify plane, registered as THE
      module-global controller so the consensus-step / dispatcher-
      drain pokes start deciding. Any Controller constructor kwarg may
      ride in the op. Decisions are count-based on deterministic poke
      sites, so the /dump_controller decision stream replays
      byte-identically for the same (seed, schedule); the decision
      tail rides every SimnetFailure replay blob.
"""
from __future__ import annotations

import json
from typing import Dict, List

OPS = ("partition", "heal", "link", "kill", "restart", "failpoint",
       "equivocate", "garbage", "light_attack", "gateway_sync", "tx",
       "flood", "epoch", "controller")

_LINK_KEYS = ("drop", "delay", "jitter", "dup", "reorder")


class ScheduleError(Exception):
    pass


def validate_schedule(schedule: List[Dict], n_nodes: int) -> None:
    """Structural validation so a typo'd schedule fails loudly up front
    instead of silently perturbing nothing."""
    for op in schedule:
        if not isinstance(op, dict) or "op" not in op or "at" not in op:
            raise ScheduleError(f"malformed op {op!r}")
        kind = op["op"]
        if kind not in OPS:
            raise ScheduleError(f"unknown op {kind!r}")
        if float(op["at"]) < 0:
            raise ScheduleError(f"negative time in {op!r}")
        for key in ("node", "target"):
            if key in op and not 0 <= int(op[key]) < n_nodes:
                raise ScheduleError(f"{key} out of range in {op!r}")
        for key in ("byz", "frm", "to"):
            sel = op.get(key, [])
            if not isinstance(sel, (list, tuple)):
                raise ScheduleError(
                    f"{key} must be a list of node ids in {op!r}"
                )
            for i in sel:
                if not 0 <= int(i) < n_nodes:
                    raise ScheduleError(
                        f"{key} node out of range in {op!r}"
                    )
        # node-targeting ops must NAME their target up front: a missing
        # selector otherwise validates fine and KeyErrors mid-simulation
        # (a replay-blob failure instead of this loud ScheduleError)
        if kind in ("kill", "restart", "failpoint", "equivocate",
                    "garbage", "tx", "flood", "gateway_sync",
                    "epoch", "controller") \
                and "node" not in op:
            raise ScheduleError(f"{kind} requires a node in {op!r}")
        if kind == "controller":
            for key in ("slo_commit_p99_ms", "slo_gateway_wait_ms",
                        "slo_bulk_wait_ms"):
                if key in op and float(op[key]) <= 0:
                    raise ScheduleError(
                        f"controller {key} must be > 0 in {op!r}")
            if int(op.get("decision_interval", 1)) < 1:
                raise ScheduleError(
                    f"controller decision_interval must be >= 1 "
                    f"in {op!r}")
            if int(op.get("cooldown", 0)) < 0:
                raise ScheduleError(
                    f"controller cooldown must be >= 0 in {op!r}")
            for name, b in (op.get("bounds") or {}).items():
                if (not isinstance(b, (list, tuple)) or len(b) != 2
                        or float(b[0]) > float(b[1])):
                    raise ScheduleError(
                        f"controller bounds[{name!r}] must be a "
                        f"[lo, hi] pair with lo <= hi in {op!r}")
        if kind == "epoch":
            churn = float(op.get("churn", 0.25))
            if not 0.0 < churn <= 1.0:
                raise ScheduleError(
                    f"epoch churn must be in (0, 1] in {op!r}")
        if kind == "gateway_sync":
            if int(op.get("clients", 0)) < 1:
                raise ScheduleError(
                    f"gateway_sync needs clients >= 1 in {op!r}")
            if int(op.get("target", 0)) < 1:
                raise ScheduleError(
                    f"gateway_sync needs target >= 1 in {op!r}")
            forged = op.get("forged", [])
            if not isinstance(forged, (list, tuple)):
                raise ScheduleError(
                    f"forged must be a list of client indices in {op!r}")
            for i in forged:
                if not 0 <= int(i) < int(op["clients"]):
                    raise ScheduleError(
                        f"forged client index out of range in {op!r}")
            if forged and not op.get("byz"):
                raise ScheduleError(
                    f"gateway_sync with forged clients needs byz "
                    f"signers in {op!r}")
        if kind == "light_attack" and "target" not in op:
            raise ScheduleError(
                f"light_attack requires a target in {op!r}")
        if kind == "partition":
            seen = set()
            for grp in op.get("groups", []):
                for i in grp:
                    if not 0 <= int(i) < n_nodes or i in seen:
                        raise ScheduleError(f"bad partition {op!r}")
                    seen.add(i)
        if kind == "failpoint":
            from cometbft_tpu.libs.failpoints import parse_spec

            parse_spec(op.get("spec", ""))  # raises on malformed specs
        if kind == "flood":
            if float(op.get("rate", 0)) <= 0:
                raise ScheduleError(f"flood rate must be > 0 in {op!r}")
            if float(op.get("duration", 0)) <= 0:
                raise ScheduleError(
                    f"flood duration must be > 0 in {op!r}")
            if int(op.get("size", 16)) < 1:
                raise ScheduleError(f"flood size must be >= 1 in {op!r}")


def schedule_to_json(seed: int, schedule: List[Dict]) -> str:
    """The replay blob printed on every simnet failure."""
    return json.dumps({"seed": seed, "schedule": schedule}, sort_keys=True)


def schedule_from_json(blob: str):
    j = json.loads(blob)
    return j["seed"], j["schedule"]


def random_schedule(rng, n_nodes: int, horizon: float = 20.0,
                    n_ops: int = 6, epochs: bool = False) -> List[Dict]:
    """A seeded random schedule for the fuzzer (tools/simnet_fuzz.py):
    draws from the full op catalog, keeps kills bounded so quorum can
    survive, and always heals before the horizon so liveness is
    checkable afterwards. `epochs=True` adds the epoch-rotation op to
    the pool (only meaningful when the fuzzer built its Simnet with a
    validator tail — rotation then interleaves with partitions, kills
    and floods exactly like production re-election under faults)."""
    ops: List[Dict] = []
    killed: set = set()
    max_kill = max(0, (n_nodes - 1) // 3)
    pool = ["partition", "link", "kill_restart", "failpoint",
            "equivocate", "garbage", "tx"]
    if epochs:
        pool += ["epoch", "epoch"]  # rotation-heavy: churn is the point
    for _ in range(n_ops):
        at = round(rng.uniform(1.0, horizon * 0.6), 3)
        kind = rng.choice(pool)
        if kind == "partition":
            cut = rng.randrange(1, n_nodes)
            idxs = list(range(n_nodes))
            rng.shuffle(idxs)
            ops.append({"at": at, "op": "partition",
                        "groups": [sorted(idxs[:cut]),
                                   sorted(idxs[cut:])]})
            ops.append({"at": round(at + rng.uniform(1.0, 4.0), 3),
                        "op": "heal"})
        elif kind == "link":
            ops.append({
                "at": at, "op": "link",
                "drop": round(rng.uniform(0.0, 0.2), 3),
                "delay": round(rng.uniform(0.005, 0.05), 4),
                "jitter": round(rng.uniform(0.0, 0.02), 4),
                "dup": round(rng.uniform(0.0, 0.1), 3),
                "reorder": round(rng.uniform(0.0, 0.1), 3),
            })
        elif kind == "kill_restart":
            if len(killed) >= max_kill:
                continue
            victim = rng.randrange(n_nodes)
            killed.add(victim)
            ops.append({"at": at, "op": "kill", "node": victim})
            ops.append({"at": round(at + rng.uniform(1.0, 4.0), 3),
                        "op": "restart", "node": victim})
        elif kind == "failpoint":
            node = rng.randrange(n_nodes)
            point = rng.choice([
                "consensus.wal.pre_vote", "consensus.wal.post_vote",
                "consensus.wal.pre_proposal", "consensus.pre_finalize",
            ])
            action = rng.choice(["raise", "crash"])
            ops.append({"at": at, "op": "failpoint", "node": node,
                        "spec": f"{point}={action}*1"})
            if action == "crash":
                ops.append({"at": round(at + rng.uniform(1.0, 4.0), 3),
                            "op": "restart", "node": node})
        elif kind == "equivocate":
            ops.append({"at": at, "op": "equivocate",
                        "node": rng.randrange(n_nodes), "votes": 1})
        elif kind == "garbage":
            ops.append({"at": at, "op": "garbage",
                        "node": rng.randrange(n_nodes),
                        "votes": rng.randrange(1, 4)})
        elif kind == "epoch":
            ops.append({"at": at, "op": "epoch",
                        "node": rng.randrange(n_nodes),
                        "churn": round(rng.uniform(0.1, 0.5), 2)})
        else:
            ops.append({"at": at, "op": "tx",
                        "node": rng.randrange(n_nodes),
                        "data": bytes(
                            f"k{rng.randrange(1000)}=v", "ascii"
                        ).hex()})
    # terminal heal so post-schedule liveness is meaningful
    ops.append({"at": round(horizon * 0.7, 3), "op": "heal"})
    ops.sort(key=lambda o: o["at"])
    return ops
