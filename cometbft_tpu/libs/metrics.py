"""Prometheus-compatible metrics: counters, gauges, histograms.

Reference: the metricsgen-generated per-package metrics structs
(consensus/metrics.go:24-91, blocksync/metrics.go, p2p, mempool, state)
exported via the prometheus server (node/node.go:846). This module is
the registry + text-exposition core; per-subsystem metric sets live
next to their components and the node serves /metrics over HTTP.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Metric:
    def __init__(self, name: str, help_: str, typ: str):
        self.name = name
        self.help = help_
        self.type = typ
        self._values: Dict[tuple, float] = {}
        self._lock = threading.Lock()

    def labels(self, **labels) -> "_Bound":
        return _Bound(self, tuple(sorted(labels.items())))

    def _add(self, key: tuple, v: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def _set(self, key: tuple, v: float) -> None:
        with self._lock:
            self._values[key] = v

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.type}"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(key)} {v:g}")
        return out


class _Bound:
    def __init__(self, metric: Metric, key: tuple):
        self.metric = metric
        self.key = key

    def inc(self, v: float = 1.0) -> None:
        self.metric._add(self.key, v)

    def set(self, v: float) -> None:
        self.metric._set(self.key, v)

    def observe(self, v: float) -> None:  # histogram-backed
        self.metric._observe(self.key, v)  # type: ignore[attr-defined]


class Counter(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_, "counter")

    def inc(self, v: float = 1.0, **labels) -> None:
        self._add(tuple(sorted(labels.items())), v)


class Gauge(Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_, "gauge")

    def set(self, v: float, **labels) -> None:
        self._set(tuple(sorted(labels.items())), v)

    def inc(self, v: float = 1.0, **labels) -> None:
        self._add(tuple(sorted(labels.items())), v)


class Histogram(Metric):
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)

    def __init__(self, name, help_="", buckets=None):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts: Dict[tuple, List[int]] = {}
        self._sums: Dict[tuple, float] = {}

    def observe(self, v: float, **labels) -> None:
        self._observe(tuple(sorted(labels.items())), v)

    def _observe(self, key: tuple, v: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1)
            )
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
        for key, counts in items:
            cum = 0
            for i, ub in enumerate(self.buckets):
                cum += counts[i]
                lk = key + (("le", f"{ub:g}"),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            cum += counts[-1]
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} "
                       f"{sums.get(key, 0.0):g}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {cum}")
        return out


class Registry:
    def __init__(self, namespace: str = "cometbft"):
        self.namespace = namespace
        self._metrics: List[Metric] = []
        self._lock = threading.Lock()

    def _full(self, subsystem: str, name: str) -> str:
        return f"{self.namespace}_{subsystem}_{name}"

    def counter(self, subsystem, name, help_="") -> Counter:
        m = Counter(self._full(subsystem, name), help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, subsystem, name, help_="") -> Gauge:
        m = Gauge(self._full(subsystem, name), help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, subsystem, name, help_="", buckets=None) -> Histogram:
        m = Histogram(self._full(subsystem, name), help_, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class NodeMetrics:
    """The metric set the node wires into its components — the union of
    the reference's consensus/p2p/mempool/blocksync metricsgen structs
    (consensus/metrics.go:24-91 etc.), prometheus-text compatible names."""

    def __init__(self, registry: Optional[Registry] = None):
        r = self.registry = registry or Registry()
        # consensus
        self.height = r.gauge("consensus", "height",
                              "Height of the chain")
        self.rounds = r.gauge("consensus", "rounds",
                              "Round of the current height")
        self.validators = r.gauge("consensus", "validators",
                                  "Number of validators")
        self.block_interval = r.histogram(
            "consensus", "block_interval_seconds",
            "Time between this and the last block",
            buckets=(0.1, 0.25, 0.5, 1, 2, 5, 10, 30),
        )
        self.num_txs = r.gauge("consensus", "num_txs",
                               "Number of transactions in the latest block")
        self.total_txs = r.counter("consensus", "total_txs",
                                   "Total transactions committed")
        self.block_size = r.gauge("consensus", "block_size_bytes",
                                  "Size of the latest block")
        # device verifier (TPU-native addition)
        self.verify_batches = r.counter(
            "crypto", "verify_batches_total",
            "Device batch-verification dispatches")
        self.verify_sigs = r.counter(
            "crypto", "verify_sigs_total",
            "Signatures verified on device")
        self.verify_seconds = r.histogram(
            "crypto", "verify_seconds",
            "Device batch verification wall time",
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1),
        )
        self.breaker_open = r.gauge(
            "crypto", "breaker_open",
            "1 while the device circuit breaker is OPEN "
            "(batches on the host fallback path)")
        # verify plane (continuous-batching scheduler)
        self.plane_queue_depth = r.gauge(
            "verifyplane", "queue_depth",
            "Signature rows pending in the verify plane")
        self.plane_batch_size = r.histogram(
            "verifyplane", "batch_size",
            "Rows per dispatched verify-plane flush",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
        )
        self.plane_wait_seconds = r.histogram(
            "verifyplane", "submit_to_result_seconds",
            "Verify-plane submit-to-result latency",
            buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025,
                     0.05, 0.1, 0.5),
        )
        self.plane_padding_waste = r.counter(
            "verifyplane", "padding_waste_total",
            "Dead rows added padding flushes to compiled bucket shapes")
        self.plane_pack_seconds = r.histogram(
            "verifyplane", "pack_seconds",
            "Host-side staging time per verify-plane flush (template "
            "packing + row scatter, before device dispatch)",
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1),
        )
        self.plane_h2d_bytes = r.counter(
            "verifyplane", "h2d_bytes_total",
            "Bytes of packed signature rows staged host-to-device by "
            "verify-plane flushes (valset tables are device-resident "
            "and excluded)")
        # mempool
        self.mempool_size = r.gauge("mempool", "size",
                                    "Pending transactions")
        # p2p
        self.peers = r.gauge("p2p", "peers", "Connected peers")
        # blocksync
        self.blocksync_syncing = r.gauge("blocksync", "syncing",
                                         "1 while block-syncing")

    def expose_text(self) -> str:
        # scrape-time refresh: the breaker trips inside
        # crypto.batch.verify_batch_direct with no metrics handle, so
        # the gauge is sampled here instead of pushed on state change —
        # /metrics is always current even with the plane idle/disabled
        try:
            from cometbft_tpu.crypto import batch as cbatch

            self.breaker_open.set(
                1.0 if cbatch.device_breaker().state == "open" else 0.0
            )
        except Exception:  # noqa: BLE001 - scrape must never fail
            pass
        return self.registry.expose_text()
